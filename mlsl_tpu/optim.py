"""ZeRO-1-sharded Adafactor: factored second moments under distributed update.

The distributed-update path (reference owned-kernel partitioning,
src/mlsl_impl.cpp:388-444) hands each data rank a flat contiguous shard of a
layer's gradient, which breaks shape-dependent transforms like Adafactor: the
factored statistics are row/col means of the full weight matrices. This module
restores them cross-shard:

- per-element row/col/leaf state indices are precomputed host-side for the
  layer's padded flat layout and stored as distributed int32 buffers (each rank
  holds only its owned slice);
- each step, every rank segment-sums g^2 from its owned shard into partial
  row/col statistics and a psum over the gradient group completes them — the
  factored vectors are tiny (O(rows+cols)), so the extra wire cost is
  negligible next to the increment AllGather;
- the EMA'd v_row/v_col stay replicated (identical on every rank by
  construction), while elementwise state (non-factored leaves' v, momentum)
  stays owned-shard only — the ZeRO-1 memory split Adafactor was built for.

Numerics replicate optax.adafactor's chain exactly (scale_by_factored_rms ->
clip_by_block_rms -> lr -> scale_by_param_block_rms -> ema -> weight decay ->
sign flip), so the sharded path is oracle-testable against the plain replicated
path. Per-leaf block quantities (RMS clipping, parameter scale) are likewise
assembled from owned-shard partials via segment sums + psum.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from mlsl_tpu.comm.collectives import _BUF_SPEC, _group_rank, smap
from mlsl_tpu.comm.mesh import DATA_AXIS, NUM_GRID_AXES, SEQ_AXIS
from mlsl_tpu.log import mlsl_assert


# -- elastic reshard primitives (mlsl_tpu.elastic) ----------------------------
#
# Live shrink/grow re-shards ZeRO-1 optimizer state ACROSS world sizes with
# no checkpoint restore: the drain collective below all-gathers each rank's
# owned shard into the full flat vector (one engine-routed all_gather over
# the gradient group, run on the pre-reshard mesh during the drain window),
# and place_owned_vector re-partitions it over the survivor topology's
# ownership chunks. The coordinator (elastic.py) pairs them under a reshard
# plan the static verifier proves covers every shard element exactly once
# (analysis/plan.py verify_reshard, MLSL-A140/A141) before execution.


def gather_owned_full(topo, buf, grad_axes=(DATA_AXIS, SEQ_AXIS)) -> np.ndarray:
    """All-gather a ZeRO-1 owned-shard distributed buffer (grid + (k,)) into
    the full flat ``(d * k,)`` host vector — the elastic drain collective.

    The gather runs ON the buffer's (pre-reshard) mesh: group-rank order of
    the tiled all_gather matches the ownership chunk order (grad-group rank
    r owns contiguous chunk r, reference src/mlsl_impl.cpp:403-435), so the
    concatenation IS the padded flat layout. The result is replicated; one
    addressable shard (a survivor's copy) is read back."""
    from mlsl_tpu.comm import algos

    mesh = topo.mesh

    def body(g):
        flat = g.reshape(g.shape[NUM_GRID_AXES:])
        return algos.inline_allgather(flat, grad_axes, tiled=True)

    sm = smap(body, mesh, in_specs=_BUF_SPEC, out_specs=P(), check=False)
    out = jax.jit(sm)(buf)
    return np.asarray(out.addressable_shards[0].data)


def place_owned_vector(new_topo, vec: np.ndarray, count: int,
                       padded_new: int, d_new: int):
    """Re-partition a full flat state vector onto a (possibly different-size)
    topology's ZeRO-1 ownership chunks: truncate the old padding to
    ``count``, re-pad to the survivor world's ``padded_new``, and shard the
    ``d_new`` equal chunks over the data axis — the write half of an elastic
    reshard."""
    mlsl_assert(
        padded_new % d_new == 0 and padded_new >= count,
        "reshard target geometry invalid: padded %d vs d=%d, count=%d",
        padded_new, d_new, count,
    )
    k_new = padded_new // d_new
    flat = np.asarray(vec).reshape(-1)[:count]
    flat = np.pad(flat, (0, padded_new - count))
    grid = new_topo.grid_shape
    mlsl_assert(
        grid == (1, d_new, 1, 1),
        "elastic ZeRO-1 reshard supports a pure data-parallel grid "
        "(replica=seq=model=1); got %s", grid,
    )
    chunks = flat.reshape(1, d_new, 1, 1, k_new)
    return new_topo.shard_buffer(np.ascontiguousarray(chunks))


@dataclasses.dataclass(frozen=True)
class ShardedAdafactor:
    """Adafactor config usable on every trainer path.

    On the plain (replicated) path the trainer runs ``as_optax()``; under
    distributed_update it runs the cross-shard implementation in this module
    with identical numerics.
    """

    learning_rate: float
    min_dim_size_to_factor: int = 128
    decay_rate: float = 0.8
    decay_offset: int = 0
    multiply_by_parameter_scale: bool = True
    clipping_threshold: Optional[float] = 1.0
    momentum: Optional[float] = None
    weight_decay_rate: Optional[float] = None
    eps: float = 1e-30

    def as_optax(self):
        import optax

        return optax.adafactor(
            learning_rate=self.learning_rate,
            min_dim_size_to_factor=self.min_dim_size_to_factor,
            decay_rate=self.decay_rate,
            decay_offset=self.decay_offset,
            multiply_by_parameter_scale=self.multiply_by_parameter_scale,
            clipping_threshold=self.clipping_threshold,
            momentum=self.momentum,
            weight_decay_rate=self.weight_decay_rate,
            eps=self.eps,
            factored=True,
        )


def _factored_dims(shape, min_dim_size_to_factor: int):
    """The two largest axes to factor over, or None (optax's rule exactly:
    optax/_src/factorized.py _factored_dims)."""
    if len(shape) < 2:
        return None
    sorted_dims = np.argsort(shape)
    if shape[sorted_dims[-2]] < min_dim_size_to_factor:
        return None
    return int(sorted_dims[-2]), int(sorted_dims[-1])


def build_adafactor_layout(
    leaf_shapes, padded_count: int, data_size: int, min_dim_size_to_factor: int
) -> dict:
    """Host-side static index layout for one layer's padded flat gradient.

    Returns per-element index vectors over the full padded layout (split into
    per-rank owned slices by the caller) plus the tiny per-state host vectors.
    Sentinel convention: the LAST slot of each state/divisor vector is a dummy
    (factor 1, divisor 1) addressed by padding and by elements the state does
    not apply to.
    """
    count = int(sum(int(np.prod(s)) for s in leaf_shapes))
    mlsl_assert(
        padded_count % data_size == 0,
        "padded count %d not divisible by data size %d", padded_count, data_size,
    )
    row_ids, col_ids, leaf_ids, fact_mask = [], [], [], []
    row_divs, col_divs, rowmean_ids, leaf_sizes = [], [], [], []
    n_row = n_col = 0
    for li, shape in enumerate(leaf_shapes):
        shape = tuple(int(d) for d in shape)
        sz = int(np.prod(shape)) if shape else 1
        leaf_sizes.append(sz)
        fd = _factored_dims(shape, min_dim_size_to_factor)
        if fd is None:
            row_ids.append(np.full(sz, -1, np.int64))
            col_ids.append(np.full(sz, -1, np.int64))
            fact_mask.append(np.zeros(sz, np.float32))
        else:
            d1, d0 = fd
            nd = len(shape)
            grids = np.indices(shape)
            r_shape = tuple(np.delete(shape, d0))
            c_shape = tuple(np.delete(shape, d1))
            r_coords = [grids[a] for a in range(nd) if a != d0]
            c_coords = [grids[a] for a in range(nd) if a != d1]
            row_ids.append(
                (np.ravel_multi_index(r_coords, r_shape).reshape(-1) + n_row)
            )
            col_ids.append(
                (np.ravel_multi_index(c_coords, c_shape).reshape(-1) + n_col)
            )
            fact_mask.append(np.ones(sz, np.float32))
            # v_row entry -> its mean group (optax: mean over axis reduced_d1
            # of the d0-reduced tensor); v_row/v_col entry -> reduction sizes
            reduced_d1 = d1 - 1 if d1 > d0 else d1
            rm_shape = tuple(np.delete(r_shape, reduced_d1))
            if rm_shape:
                rg = np.indices(r_shape)
                rm_coords = [
                    rg[a] for a in range(len(r_shape)) if a != reduced_d1
                ]
                rowmean_ids.append(
                    np.ravel_multi_index(rm_coords, rm_shape).reshape(-1)
                    + (max(rowmean_ids[-1]) + 1 if rowmean_ids else 0)
                )
            else:
                rowmean_ids.append(
                    np.zeros(int(np.prod(r_shape)), np.int64)
                    + (max(rowmean_ids[-1]) + 1 if rowmean_ids else 0)
                )
            row_divs.append(np.full(int(np.prod(r_shape)), shape[d0], np.float32))
            col_divs.append(np.full(int(np.prod(c_shape)), shape[d1], np.float32))
            n_row += int(np.prod(r_shape))
            n_col += int(np.prod(c_shape))
        leaf_ids.append(np.full(sz, li, np.int64))

    n_leaf = len(leaf_shapes)
    pad = padded_count - count
    row_full = np.concatenate(row_ids + [np.full(pad, -1, np.int64)])
    col_full = np.concatenate(col_ids + [np.full(pad, -1, np.int64)])
    leaf_full = np.concatenate(leaf_ids + [np.full(pad, n_leaf, np.int64)])
    fact_full = np.concatenate(fact_mask + [np.zeros(pad, np.float32)])
    # a fully-factored layer needs NO elementwise moment: v stays a (1,) dummy,
    # preserving Adafactor's sublinear state memory (the point of factoring)
    has_elementwise = bool((fact_full[:count] == 0).any()) if count else False
    # sentinel = last slot
    row_full = np.where(row_full < 0, n_row, row_full)
    col_full = np.where(col_full < 0, n_col, col_full)
    rowmean = (
        np.concatenate(rowmean_ids) if rowmean_ids else np.zeros(0, np.int64)
    )
    n_rowmean = int(rowmean.max()) + 1 if rowmean.size else 0
    return {
        "count": count,
        "has_elementwise": has_elementwise,
        "n_row": n_row,
        "n_col": n_col,
        "n_leaf": n_leaf,
        "n_rowmean": n_rowmean,
        "row_ids": row_full.astype(np.int32),
        "col_ids": col_full.astype(np.int32),
        "leaf_ids": leaf_full.astype(np.int32),
        "fact_mask": fact_full,
        "pad_mask": np.concatenate(
            [np.ones(count, np.float32), np.zeros(pad, np.float32)]
        ),
        "row_div": np.concatenate(
            row_divs + [np.ones(1, np.float32)]
        ) if row_divs else np.ones(1, np.float32),
        "col_div": np.concatenate(
            col_divs + [np.ones(1, np.float32)]
        ) if col_divs else np.ones(1, np.float32),
        "rowmean_ids": rowmean.astype(np.int32),
        "rowmean_div": np.array(
            [
                np.sum(rowmean == g) for g in range(n_rowmean)
            ],
            np.float32,
        ) if n_rowmean else np.ones(0, np.float32),
        "leaf_sizes": np.asarray(leaf_sizes + [1], np.float32),
    }


def _shard_ids(topo, layout, data_size: int):
    """Distributed int32/float32 buffers holding each rank's owned slice of the
    per-element index vectors (grad-group rank r owns contiguous chunk r).

    Layout contract: the ownership chunks are laid out along the DATA axis only
    — replica/seq/model must be degenerate. Under seq>1 the owned chunk would
    have to follow the grad group (data x seq); under model>1 the per-leaf
    id vectors themselves differ per model shard. Both need a per-(axis-coord)
    layout this function does not build, so reject loudly instead of sharding
    ids onto the wrong ranks."""
    grid = topo.grid_shape
    r, d, s, m = grid
    mlsl_assert(
        r == 1 and s == 1 and m == 1 and d == data_size,
        "ShardedAdafactor's factored-stats layout supports a pure data-parallel "
        "grid (replica=seq=model=1); got grid (%d,%d,%d,%d) with data_size=%d. "
        "Use optimizer.as_optax() for hybrid grids.",
        r, d, s, m, data_size,
    )
    k = layout["row_ids"].shape[0] // data_size

    def buf(vec):
        per_rank = vec.reshape(data_size, k)
        # grid is (replica, data, seq, model); the data axis indexes the owned
        # chunk (guard above pins the other axes to 1).
        global_arr = per_rank.reshape(1, data_size, 1, 1, k)
        return topo.shard_buffer(np.ascontiguousarray(global_arr))

    return {
        "row_ids": buf(layout["row_ids"]),
        "col_ids": buf(layout["col_ids"]),
        "leaf_ids": buf(layout["leaf_ids"]),
        "fact_mask": buf(layout["fact_mask"]),
        "pad_mask": buf(layout["pad_mask"]),
    }


def init_adafactor_state(topo, layout, cfg: ShardedAdafactor, data_size: int):
    """Distributed state buffers: replicated tiny factored vectors, owned-shard
    elementwise vectors."""
    grid = topo.grid_shape
    k = layout["row_ids"].shape[0] // data_size

    def repl(n):
        return topo.shard_buffer(np.zeros((*grid, n), np.float32))

    state = {
        "count": topo.shard_buffer(np.zeros((*grid, 1), np.int32)),
        "v_row": repl(layout["n_row"] + 1),
        "v_col": repl(layout["n_col"] + 1),
        "v": repl(k if layout["has_elementwise"] else 1),
    }
    if cfg.momentum is not None:
        state["m"] = repl(k)
    return state


def build_adafactor_inc_fn(
    mesh,
    topo,
    cfg: ShardedAdafactor,
    layout: dict,
    data_size: int,
    with_scale: bool = False,
    grad_axes=(DATA_AXIS, SEQ_AXIS),
):
    """Jitted (owned grad buffer, state buffers, replicated layer subtree
    [, scale]) -> (owned increment buffer, new state buffers).

    The increment is optax.adafactor's update (sign included), so the caller
    applies it with p + inc, exactly like the SGD/adam distributed paths.
    """
    ids = _shard_ids(topo, layout, data_size)
    n_row, n_col = layout["n_row"], layout["n_col"]
    n_leaf, n_rowmean = layout["n_leaf"], layout["n_rowmean"]
    row_div = jnp.asarray(layout["row_div"])
    col_div = jnp.asarray(layout["col_div"])
    rowmean_ids = jnp.asarray(layout["rowmean_ids"])
    rowmean_div = jnp.asarray(layout["rowmean_div"])
    leaf_sizes = jnp.asarray(layout["leaf_sizes"])
    padded = layout["row_ids"].shape[0]
    k = padded // data_size

    def body(g, state, subtree, s, row_ids, col_ids, leaf_ids, fact_mask, pad_mask):
        g = s * g.reshape(g.shape[NUM_GRID_AXES:]) / data_size
        local = {
            key: v.reshape(v.shape[NUM_GRID_AXES:]) for key, v in state.items()
        }
        row_ids = row_ids.reshape(-1)
        col_ids = col_ids.reshape(-1)
        leaf_ids = leaf_ids.reshape(-1)
        fact_mask = fact_mask.reshape(-1)
        pad_mask = pad_mask.reshape(-1)

        count = local["count"][0]
        step = count - cfg.decay_offset
        beta = 1.0 - (step.astype(jnp.float32) + 1.0) ** (-cfg.decay_rate)

        gsq = g * g + cfg.eps
        has_elem = layout["has_elementwise"]
        # --- factored second moments: partial sums -> psum -> EMA ----------
        row_part = jax.ops.segment_sum(
            gsq * fact_mask, row_ids, num_segments=n_row + 1
        )
        col_part = jax.ops.segment_sum(
            gsq * fact_mask, col_ids, num_segments=n_col + 1
        )
        # mlsl-lint: disable=A201 -- factored second-moment statistics are
        # optimizer-internal in-graph math, not a request collective the
        # engine could route (they fuse with the segment sums around them)
        row_sums = lax.psum(row_part, grad_axes)
        col_sums = lax.psum(col_part, grad_axes)  # mlsl-lint: disable=A201
        v_row = beta * local["v_row"] + (1.0 - beta) * row_sums / row_div
        v_col = beta * local["v_col"] + (1.0 - beta) * col_sums / col_div
        if n_rowmean:
            rowmean = (
                jax.ops.segment_sum(
                    v_row[:n_row], rowmean_ids, num_segments=n_rowmean
                )
                / rowmean_div
            )
            row_factor = (v_row[:n_row] / rowmean[rowmean_ids]) ** -0.5
        else:
            row_factor = jnp.ones((0,), jnp.float32)
        row_factor = jnp.concatenate([row_factor, jnp.ones((1,), jnp.float32)])
        col_factor = jnp.concatenate(
            [v_col[:n_col] ** -0.5, jnp.ones((1,), jnp.float32)]
        )
        u_fact = g * row_factor[row_ids] * col_factor[col_ids]
        # --- non-factored elementwise moment (owned shard; skipped entirely
        # for fully-factored layers, where v is a (1,) dummy) ---------------
        if has_elem:
            v_new = beta * local["v"] + (1.0 - beta) * gsq
            u_elem = g * v_new ** -0.5
            u = jnp.where(fact_mask > 0, u_fact, u_elem) * pad_mask
        else:
            v_new = local["v"]
            u = u_fact * pad_mask

        # --- clip_by_block_rms over each REAL leaf -------------------------
        if cfg.clipping_threshold is not None:
            leaf_sq = lax.psum(  # mlsl-lint: disable=A201 -- see above
                jax.ops.segment_sum(u * u, leaf_ids, num_segments=n_leaf + 1),
                grad_axes,
            )
            leaf_rms = jnp.sqrt(leaf_sq / leaf_sizes)
            denom = jnp.maximum(1.0, leaf_rms / cfg.clipping_threshold)
            u = u / denom[leaf_ids]

        u = u * cfg.learning_rate

        # --- scale_by_param_block_rms (params are replicated) --------------
        if cfg.multiply_by_parameter_scale:
            leaves = jax.tree.leaves(subtree)
            p_rms = jnp.stack(
                [
                    jnp.maximum(
                        jnp.sqrt(jnp.mean(l.astype(jnp.float32) ** 2)), 1e-3
                    )
                    for l in leaves
                ]
                + [jnp.ones((), jnp.float32)]
            )
            u = u * p_rms[leaf_ids]

        max32 = np.iinfo(np.int32).max
        new_state = {
            # optax numerics.safe_increment: clamp BEFORE the +1 can wrap
            "count": jnp.where(count < max32, count + 1, max32)[None],
            "v_row": v_row,
            "v_col": v_col,
            "v": v_new,
        }
        if cfg.momentum is not None:
            m = cfg.momentum * local["m"] + (1.0 - cfg.momentum) * u
            new_state["m"] = m
            u = m
        if cfg.weight_decay_rate is not None:
            flat_p = jnp.concatenate(
                [l.reshape(-1).astype(jnp.float32) for l in jax.tree.leaves(subtree)]
            )
            flat_p = jnp.pad(flat_p, (0, padded - flat_p.shape[0]))
            grank = _group_rank(
                grad_axes, dict(zip(mesh.axis_names, mesh.devices.shape))
            )
            p_owned = lax.dynamic_slice(flat_p, (grank * k,), (k,))
            u = u + cfg.weight_decay_rate * p_owned
        u = -u  # optax chain ends with scale(-1); increments are ADDED to params

        grid1 = (1,) * NUM_GRID_AXES
        return (
            u.reshape(grid1 + u.shape),
            jax.tree.map(lambda l: l.reshape(grid1 + l.shape), new_state),
        )

    state_keys = ["count", "v_row", "v_col", "v"] + (
        ["m"] if cfg.momentum is not None else []
    )
    state_specs = {key: _BUF_SPEC for key in state_keys}
    id_args = (
        ids["row_ids"], ids["col_ids"], ids["leaf_ids"],
        ids["fact_mask"], ids["pad_mask"],
    )
    id_specs = tuple(_BUF_SPEC for _ in id_args)

    if with_scale:
        def inc(g, state, subtree, s):
            sm = smap(
                body, mesh,
                in_specs=(_BUF_SPEC, state_specs, P(), P()) + id_specs,
                out_specs=(_BUF_SPEC, state_specs),
                check=False,
            )
            return sm(g, state, subtree, s, *id_args)

        return jax.jit(inc)

    def inc(g, state, subtree):
        sm = smap(
            lambda g, st, sub, *idv: body(g, st, sub, 1.0, *idv), mesh,
            in_specs=(_BUF_SPEC, state_specs, P()) + id_specs,
            out_specs=(_BUF_SPEC, state_specs),
            check=False,
        )
        return sm(g, state, subtree, *id_args)

    return jax.jit(inc)
