"""Public enums and small value types.

Mirrors the reference API surface (include/mlsl.hpp:88-172) with TPU-appropriate
extensions: ``DataType`` gains bf16/f16/int8 (first-class on TPU MXU), and
``QuantParams`` replaces the reference's dlopen'd library contract
(include/mlsl.hpp:162-171) with the parameters of the built-in Pallas kernels.
"""

from __future__ import annotations

import dataclasses
import enum

import jax.numpy as jnp


class DataType(enum.IntEnum):
    """Element types for activations and parameters (reference include/mlsl.hpp:88-93).

    The reference supports FLOAT/DOUBLE/BYTE; on TPU the natural set also includes
    bfloat16 (MXU-native), float16 and int8.
    """

    FLOAT = 0
    DOUBLE = 1
    BYTE = 2
    BFLOAT16 = 3
    FLOAT16 = 4
    INT8 = 5
    INT32 = 6


_JNP_DTYPES = {
    DataType.FLOAT: jnp.float32,
    DataType.DOUBLE: jnp.float64,
    DataType.BYTE: jnp.uint8,
    DataType.BFLOAT16: jnp.bfloat16,
    DataType.FLOAT16: jnp.float16,
    DataType.INT8: jnp.int8,
    DataType.INT32: jnp.int32,
}

_DTYPE_SIZES = {
    DataType.FLOAT: 4,
    DataType.DOUBLE: 8,
    DataType.BYTE: 1,
    DataType.BFLOAT16: 2,
    DataType.FLOAT16: 2,
    DataType.INT8: 1,
    DataType.INT32: 4,
}


def jnp_dtype(dt: DataType):
    """DataType -> jnp dtype."""
    return _JNP_DTYPES[DataType(dt)]


def dtype_size(dt: DataType) -> int:
    """Element size in bytes (reference: dataTypeSize in src/mlsl_impl.cpp:251)."""
    return _DTYPE_SIZES[DataType(dt)]


class PhaseType(enum.IntEnum):
    """Training vs testing phase (reference include/mlsl.hpp:96-100)."""

    TRAIN = 0
    TEST = 1


class GroupType(enum.IntEnum):
    """Process-group selector (reference include/mlsl.hpp:114-119).

    DATA: processes holding the same model shard for different batches (data parallel).
    MODEL: processes holding different model shards for the same batch (model parallel).
    GLOBAL: all processes.
    SEQ (extension, absent in the 2016-era reference): processes holding different
    sequence chunks of the same batch (sequence/context parallelism).
    """

    DATA = 0
    MODEL = 1
    GLOBAL = 2
    SEQ = 3


class ReductionType(enum.IntEnum):
    """Reduction ops for Reduce/AllReduce/ReduceScatter (reference include/mlsl.hpp:122-127)."""

    SUM = 0
    MIN = 1
    MAX = 2


class OpType(enum.IntEnum):
    """Compute-operation kinds (reference include/mlsl.hpp:136-148)."""

    CC = 0      # cross-correlation: IA and OA independent, has parameters
    BIAS = 1    # same IA/OA, has parameters
    ACT = 2     # same IA/OA, no parameters
    POOL = 3    # same IA/OA, no parameters
    SPLIT = 4   # OA depends on IA (=OA1+OA2...), no parameters
    CONCAT = 5  # OA = concat(IA1, IA2, ...), no parameters
    BCAST = 6   # OA1 = IA, OA2 = IA, ...
    REDUCE = 7  # OA = IA1 + IA2 + ...
    DATA = 8    # only OA (input layer)
    EVAL = 9    # only IA (loss layer)


class CompressionType(enum.IntEnum):
    """Gradient-compression selector (reference include/mlsl.hpp:151-155).

    TOPK (extension): top-k magnitude sparsification with error feedback — the
    importance-weighted-pruning family of ring-allreduce compressors."""

    NONE = 0
    QUANTIZATION = 1
    TOPK = 2


@dataclasses.dataclass
class QuantParams:
    """Quantization configuration.

    The reference (include/mlsl.hpp:162-171) names a dlopen'd library providing
    compress/decompress/reduce_sum. Three forms are honored here:

    - default: the built-in Pallas int8-block + error-feedback kernels (reference
      quant/quant.c:153-211 semantics) with the block geometry below;
    - ``compress_fn``/``decompress_fn`` (+ optional ``reduce_sum_fn``): jittable
      user callables traced into the compiled ring collective — the TPU-native
      form of a pluggable codec (see comm/codec.py for the contract);
    - ``lib_path`` + the three symbol names: the reference's exact dlopen
      contract, loaded via ctypes and bridged with host callbacks.
    """

    block_size: int = 256        # bytes per quantized block (scale + int8 payload)
    elem_in_block: int = 256     # elements quantized per block (one shared scale)
    lib_path: str | None = None  # dlopen'd codec library (reference quant/quant.c:96-133)
    quant_buffer_func_name: str | None = None
    dequant_buffer_func_name: str | None = None
    reduce_sum_func_name: str | None = None
    # jittable-callable codec (TPU-native plug-in form; see comm/codec.py)
    compress_fn: object = None       # compress(f32[n]) -> payload pytree
    decompress_fn: object = None     # decompress(payload, n) -> f32[n]
    reduce_sum_fn: object = None     # optional (payload, payload) -> payload
