"""Buffer checker: runtime validation of every buffer handed to a collective.

TPU-native analog of the reference's PointerChecker (src/pointer_checker.{hpp,cpp}:
a debug allocator-range tracker consulted before every MPI call under
ENABLE_CHKP_INT). Raw pointers don't exist here; the failure modes that do are wrong
global shape, wrong dtype, wrong sharding (buffer laid out for a different topology)
and non-finite payloads. Enabled via MLSL_CHKP=1 (off by default; MLSL_CHKP=2 adds
payload finiteness).

Threaded through THREE boundaries (the reference checks only the MPI call):
request Start (comm/request.py), the bucket pack — each member buffer is
validated against its own request descriptor before it joins a coalesced
round (core/bucketing.py) — and feed decode outputs (data/feed.py via
:func:`check_feed_batch`).

CHKP_VALUES batches its finiteness verdicts per round instead of syncing the
device per buffer: ``check_buffer`` queues one tiny on-device ``isfinite.all``
program per Start (async — no host sync), and :func:`flush_values` resolves
every queued verdict with ONE device_get at the next completion boundary
(CommRequest.wait/test). A full backward pass of N layers costs one sync, not
N. The check therefore RAISES AT THE ROUND'S FIRST WAIT, naming every
offending buffer — not at the Start that queued it.

Hit/violation counters live in core/stats (CHKP line in mlsl_stats.log).
"""

from __future__ import annotations

import threading
from typing import List, Tuple

import numpy as np
import jax

from mlsl_tpu.config import _env_int
from mlsl_tpu.log import MLSLError
from mlsl_tpu.types import jnp_dtype

CHKP_OFF = 0
CHKP_SHAPE = 1   # shape/dtype/sharding checks (cheap, no sync)
CHKP_VALUES = 2  # + finiteness check (batched; one sync per round)


def level() -> int:
    # read fresh each Start (tests toggle the env var at runtime); top-level
    # import keeps this per-Start hot path free of import machinery
    return _env_int("MLSL_CHKP", 0)


# queued CHKP_VALUES verdicts: (domain, label, on-device bool scalar).
# Process-wide like the stats counters — Starts and Waits can come from
# different threads (the dispatcher's progress thread completes deferred
# rounds). The DOMAIN keeps subsystems' rounds separate: a comm wait must
# never drain (and raise) a feed batch's queued verdict or vice versa —
# the error has to surface at the boundary whose recovery ladder owns it.
_pending: List[Tuple[str, str, jax.Array]] = []
_plock = threading.Lock()


def _record(event: str, n: int = 1) -> None:
    # lazy import: core.stats pulls in the obs tracer; the checker must stay
    # importable from the bottom of the comm stack
    from mlsl_tpu.core import stats as stats_mod

    stats_mod.record_chkp(event, n)


def _violation(msg: str, *args) -> None:
    _record("violations")
    raise MLSLError(msg % args if args else msg)


def check_buffer(buf, desc, lvl: int = None) -> None:
    """Validate a distributed buffer against its request descriptor.

    Raises MLSLError (like the reference's CHECK_RANGE failures) on mismatch.
    At CHKP_VALUES the finiteness verdict is QUEUED, not synced — it raises
    at the round's next :func:`flush_values` (CommRequest.wait/test).
    """
    if lvl is None:
        lvl = level()
    if lvl == CHKP_OFF:
        return
    _record("checks")
    topo = desc.group.topology
    if not (hasattr(buf, "shape") and buf.ndim >= 5):
        _violation(
            "CHKP: buffer must be a distributed (R,D,S,M,n) array, got %r",
            type(buf).__name__,
        )
    if tuple(buf.shape[:4]) != topo.grid_shape:
        _violation(
            "CHKP: buffer grid %s does not match topology %s",
            tuple(buf.shape[:4]),
            topo.grid_shape,
        )
    want_elems = desc.count
    got_elems = int(np.prod(buf.shape[4:]))
    if got_elems < want_elems:
        _violation(
            "CHKP: buffer payload %d < descriptor count %d (OUT_OF_RANGE)",
            got_elems,
            want_elems,
        )
    want_dt = np.dtype(jnp_dtype(desc.data_type))
    if np.dtype(buf.dtype) != want_dt:
        _violation(
            "CHKP: buffer dtype %s != descriptor dtype %s", buf.dtype, want_dt
        )
    if isinstance(buf, jax.Array) and buf.sharding is not None:
        # the buffer must be laid out on this topology's mesh (UNKNOWN_PTR analog)
        try:
            buf_mesh = buf.sharding.mesh
            if not (
                tuple(buf_mesh.axis_names) == tuple(topo.mesh.axis_names)
                and buf_mesh.devices.shape == topo.mesh.devices.shape
            ):
                _violation(
                    "CHKP: buffer sharded over mesh %s, request targets mesh %s",
                    buf_mesh.devices.shape,
                    topo.mesh.devices.shape,
                )
        except AttributeError:
            pass
    if lvl >= CHKP_VALUES and jax.numpy.issubdtype(
        buf.dtype, jax.numpy.floating
    ):
        _queue_finite(
            "comm", f"{desc.kind}[{desc.count}]",
            jax.numpy.isfinite(buf).all(),
        )


def _queue_finite(domain: str, label: str, verdict) -> None:
    _record("value_checks")
    with _plock:
        _pending.append((domain, label, verdict))


def flush_values(domain: str = "comm") -> None:
    """Resolve the queued finiteness verdicts of ``domain`` with one device
    sync; raises MLSLError naming ALL offending buffers of the round. Called
    by CommRequest.wait/test at completion (the comm round boundary) and by
    check_feed_batch after queueing one batch's leaves — each drains only
    its own domain, so the error surfaces at the boundary whose recovery
    ladder owns it. No-op (one len check) when nothing is queued."""
    if not _pending:
        return
    with _plock:
        batch = [e for e in _pending if e[0] == domain]
        _pending[:] = [e for e in _pending if e[0] != domain]
    if not batch:
        return
    _record("value_syncs")
    verdicts = jax.device_get([v for _, _, v in batch])
    bad = [label for (_, label, _), ok in zip(batch, verdicts) if not bool(ok)]
    if bad:
        _record("violations", len(bad))
        raise MLSLError(
            "CHKP: buffer contains non-finite values: " + ", ".join(bad)
        )


def check_feed_batch(batch, lvl: int = None) -> None:
    """Validate one decoded feed batch (data/feed.py): every float leaf must
    be finite at CHKP_VALUES — a wire-codec or cache fault that produced
    garbage surfaces HERE, at the decode boundary, instead of poisoning the
    step. One device sync per batch (the leaves' verdicts are queued then
    flushed together, in the 'feed' domain so a concurrent comm wait never
    steals or mis-surfaces them)."""
    if lvl is None:
        lvl = level()
    if lvl < CHKP_VALUES:
        return
    leaves = jax.tree_util.tree_leaves(batch)
    n = 0
    for i, leaf in enumerate(leaves):
        # jnp.issubdtype: ml_dtypes bfloat16 is not np.floating, and a bf16
        # training dtype is exactly what the wire's bf16 path restores
        if hasattr(leaf, "dtype") and jax.numpy.issubdtype(
            leaf.dtype, jax.numpy.floating
        ):
            _record("checks")
            _queue_finite("feed", f"feed.decode[leaf{i}]",
                          jax.numpy.isfinite(leaf).all())
            n += 1
    if n:
        flush_values("feed")
