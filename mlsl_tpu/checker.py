"""Buffer checker: runtime validation of every buffer handed to a collective.

TPU-native analog of the reference's PointerChecker (src/pointer_checker.{hpp,cpp}:
a debug allocator-range tracker consulted before every MPI call under
ENABLE_CHKP_INT). Raw pointers don't exist here; the failure modes that do are wrong
global shape, wrong dtype, wrong sharding (buffer laid out for a different topology)
and non-finite payloads. Enabled via MLSL_CHKP=1 (off by default — it syncs the
device to inspect values when MLSL_CHKP=2).
"""

from __future__ import annotations

import numpy as np
import jax

from mlsl_tpu.config import _env_int
from mlsl_tpu.log import mlsl_assert
from mlsl_tpu.types import jnp_dtype

CHKP_OFF = 0
CHKP_SHAPE = 1   # shape/dtype/sharding checks (cheap, no sync)
CHKP_VALUES = 2  # + finiteness check (syncs the device)


def level() -> int:
    # read fresh each Start (tests toggle the env var at runtime); top-level
    # import keeps this per-Start hot path free of import machinery
    return _env_int("MLSL_CHKP", 0)


def check_buffer(buf, desc, lvl: int = None) -> None:
    """Validate a distributed buffer against its request descriptor.

    Raises MLSLError (like the reference's CHECK_RANGE failures) on mismatch.
    """
    if lvl is None:
        lvl = level()
    if lvl == CHKP_OFF:
        return
    topo = desc.group.topology
    mlsl_assert(
        hasattr(buf, "shape") and buf.ndim >= 5,
        "CHKP: buffer must be a distributed (R,D,S,M,n) array, got %r",
        type(buf).__name__,
    )
    mlsl_assert(
        tuple(buf.shape[:4]) == topo.grid_shape,
        "CHKP: buffer grid %s does not match topology %s",
        tuple(buf.shape[:4]),
        topo.grid_shape,
    )
    want_elems = desc.count
    got_elems = int(np.prod(buf.shape[4:]))
    mlsl_assert(
        got_elems >= want_elems,
        "CHKP: buffer payload %d < descriptor count %d (OUT_OF_RANGE)",
        got_elems,
        want_elems,
    )
    want_dt = np.dtype(jnp_dtype(desc.data_type))
    mlsl_assert(
        np.dtype(buf.dtype) == want_dt,
        "CHKP: buffer dtype %s != descriptor dtype %s",
        buf.dtype,
        want_dt,
    )
    if isinstance(buf, jax.Array) and buf.sharding is not None:
        # the buffer must be laid out on this topology's mesh (UNKNOWN_PTR analog)
        try:
            buf_mesh = buf.sharding.mesh
            mlsl_assert(
                tuple(buf_mesh.axis_names) == tuple(topo.mesh.axis_names)
                and buf_mesh.devices.shape == topo.mesh.devices.shape,
                "CHKP: buffer sharded over mesh %s, request targets mesh %s",
                buf_mesh.devices.shape,
                topo.mesh.devices.shape,
            )
        except AttributeError:
            pass
    if lvl >= CHKP_VALUES and np.issubdtype(buf.dtype, np.floating):
        mlsl_assert(
            bool(jax.device_get(jax.numpy.isfinite(buf).all())),
            "CHKP: buffer contains non-finite values",
        )
