"""Fault-tolerant training: checkpoint-backed recovery and fault injection.

The reference is fail-stop (SURVEY.md §5.3: MLSL_ASSERT -> Finalize + _exit(1),
signal handlers that kill the endpoint servers; no elasticity, no fault injection).
This module exceeds that: a supervisor loop that periodically checkpoints
(async, via mlsl_tpu.checkpoint), catches recoverable failures (runtime errors from
the accelerator, MLSLError from the buffer checker), tears the Environment down,
rebuilds the trainer through a user factory, restores the latest checkpoint and
resumes — plus a fault-injection hook so recovery paths are testable, which the
reference never had.
"""

from __future__ import annotations

import contextlib
import signal
import threading
import time as _time
from typing import Callable, Optional

import jax

from mlsl_tpu import supervisor
from mlsl_tpu.checkpoint import CheckpointManager, restore_trainer, save_trainer
from mlsl_tpu.log import (
    MLSLError,
    MLSLIntegrityError,
    log_error,
    log_info,
    log_warning,
)
from mlsl_tpu.obs import tracer as obs


# MLSLError subclasses RuntimeError; ValueError is deliberately NOT recoverable
# (caller bugs should surface, not trigger teardown/rebuild cycles).
# MLSLTimeoutError (the request watchdog) is RuntimeError too: a hung
# collective tears down and resumes like any other device fault.
RECOVERABLE = (RuntimeError,)

_NULL_GUARD = contextlib.nullcontext()


class PreemptionGuard:
    """SIGTERM -> graceful drain: the handler only sets a flag; the training
    loop checks it between steps, drains in-flight async saves, writes a final
    checkpoint, and returns — the TPU-pod preemption contract (the reference's
    signal handlers just killed the endpoint servers, SURVEY §5.3).

    Installed only on the main thread (CPython restricts signal.signal);
    elsewhere it degrades to an inert flag the embedder can set directly."""

    SIGNALS = (signal.SIGTERM,)

    def __init__(self):
        self.triggered = False
        self._old: dict = {}
        self._installed = False

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is threading.main_thread():
            for s in self.SIGNALS:
                self._old[s] = signal.signal(s, self._on_signal)
            self._installed = True
        return self

    def _on_signal(self, signum, frame) -> None:
        # async-signal context: flag only, no IO beyond the (line-buffered) log
        self.triggered = True
        log_warning(
            "received signal %d: draining saves and checkpointing before exit",
            signum,
        )

    def __exit__(self, *exc) -> None:
        if self._installed:
            for s, h in self._old.items():
                signal.signal(s, h)
            self._installed = False


class FaultTolerantLoop:
    """Supervised training loop with checkpoint/restore recovery.

    make_trainer: factory returning a fresh trainer (called at start and after
        every recovery — it must re-create the Environment/Session/Distribution).
    batch_fn(trainer, step) -> batch: MUST be step-deterministic — recovery
        replays every step since the last checkpoint (not just the failed one),
        and exact resume depends on replaying the same data.
    save_every: checkpoint cadence in steps (also the maximum replay window).
    max_retries: failures tolerated AT THE SAME STEP before re-raising (guards
        against deterministic poison even when the resume point is several steps
        behind the failure).
    max_total_recoveries: the restart budget — checkpoint recoveries across
        the WHOLE run (rung 4 of the supervisor ladder; the lower rungs —
        comm retries and circuit breakers, mlsl_tpu.supervisor — absorb what
        they can before a failure ever reaches this loop). None reads
        ``MLSL_RESTART_BUDGET`` (default 20). Exhausting it aborts with a
        flight record (tracing armed) and the breaker status in the log.
    on_step fires exactly once per step: replayed steps below the furthest
        reported step are recomputed silently.
    fault_hook(step, attempt): optional test hook, called before each step attempt;
        raise from it to inject a fault (the chaos layer, mlsl_tpu.chaos, injects
        at specific sites INSIDE the stack instead — use it for layer faults).
    handle_preemption: install a SIGTERM handler for the duration of run()
        (main thread only): on signal the loop finishes the current step,
        drains in-flight saves, writes a final checkpoint, and returns early
        with ``self.preempted`` set.
    elastic: an :class:`mlsl_tpu.elastic.ElasticCoordinator` (None constructs
        one when ``MLSL_ELASTIC`` arms it). With a coordinator, DEVICE_LOSS
        faults (preemption, the chaos ``device.lost`` site) take the reshard
        rung — shrink to the survivor mesh, re-shard ZeRO-1 state live, and
        CONTINUE at the interrupted step with no checkpoint restore and no
        recovery counted — and returned capacity is re-admitted between
        steps through the sentinel fingerprint admission audit
        (``maybe_grow``). A failed/refused reshard (capacity budget, drain
        failure) falls back to this loop's restart rung. Elastic factories
        must size their Distribution from ``env.get_process_count()``.
    """

    def __init__(
        self,
        make_trainer: Callable,
        ckpt_dir: str,
        save_every: int = 10,
        max_retries: int = 2,
        max_total_recoveries: Optional[int] = None,
        fault_hook: Optional[Callable] = None,
        handle_preemption: bool = True,
        elastic: Optional[object] = None,
    ):
        self.make_trainer = make_trainer
        self.ckpt = CheckpointManager(ckpt_dir)
        self.save_every = max(1, save_every)
        self.max_retries = max_retries
        # bound on recoveries across the whole run: a flaky fault that lands on a
        # DIFFERENT step each cycle resets the per-step count, and without this
        # cap the loop would recover/replay forever
        if max_total_recoveries is None:
            # through Config's parser/default so the knob is defined in
            # exactly one place, with the init-time MLSLError contract
            from mlsl_tpu.config import Config, _env_int

            try:
                max_total_recoveries = _env_int(
                    "MLSL_RESTART_BUDGET", Config.restart_budget
                )
            except ValueError as e:
                raise MLSLError(f"invalid MLSL_RESTART_BUDGET: {e}") from e
            if max_total_recoveries < 0:
                raise MLSLError(
                    f"MLSL_RESTART_BUDGET must be >= 0 "
                    f"(got {max_total_recoveries})"
                )
        self.max_total_recoveries = max_total_recoveries
        self.fault_hook = fault_hook
        self.handle_preemption = handle_preemption
        self.preempted = False
        self.recoveries = 0
        self.elastic = elastic
        self._arm_elastic_if_configured()

    def _arm_elastic_if_configured(self) -> None:
        """Auto-arm the coordinator from the live config (which Config.load
        folds MLSL_ELASTIC into) or, with no initialized Environment, from
        the env var alone. Called at __init__ AND again at run() after the
        factory built the trainer: the documented pattern constructs the
        loop BEFORE any Environment exists, so a programmatic
        Config(elastic=True) is only visible post-init."""
        if self.elastic is not None:
            return
        from mlsl_tpu import elastic as elastic_mod
        from mlsl_tpu.core.environment import Environment

        cfg = (Environment._instance.config
               if Environment.is_initialized() else None)
        if elastic_mod.armed(cfg):  # cfg None -> MLSL_ELASTIC fallback
            self.elastic = elastic_mod.ElasticCoordinator()

    def _recover(self, trainer, error) -> tuple:
        """Tear down, rebuild, restore. -> (trainer, resume_step)."""
        self.recoveries += 1
        tr = obs._tracer
        t0 = tr.now() if tr is not None else 0
        log_info("recovering from %s: %s", type(error).__name__, error)
        from mlsl_tpu.core import stats as stats_mod

        # rung-4 accounting: the recovery lands in the same DEGRADE record
        # as breaker trips, so mlsl_stats.log tells the whole ladder's story
        stats_mod.record_degrade(
            "loop", "recover",
            detail=f"#{self.recoveries}/{self.max_total_recoveries} "
                   f"{type(error).__name__}: {error}",
        )
        # drain in-flight async saves first: restoring from a half-committed step
        # (or re-saving a step whose original write is still in flight) corrupts
        # the resume point
        try:
            self.ckpt.wait()
        except Exception as e:
            # suppressed (the restore below decides what is usable) but logged:
            # an invisible drain failure makes the eventual double-fault
            # undiagnosable
            log_warning(
                "checkpoint drain during recovery failed: %s: %s",
                type(e).__name__, e,
            )
        from mlsl_tpu.core.environment import Environment

        try:
            Environment.get_env().finalize()
        except Exception as e:
            # teardown of an already-faulted environment may fail; continue to
            # the rebuild, but keep the evidence
            log_warning(
                "environment teardown during recovery failed "
                "(continuing with rebuild): %s: %s",
                type(e).__name__, e,
            )
        trainer = self.make_trainer()
        restored = restore_trainer(self.ckpt, trainer)
        # Post-restore re-audit (integrity sentinel): a rollback answering a
        # CORRUPTION fault must PROVE the restored state is the verified one
        # — replicas consistent AND the fingerprint bit-exact against what
        # the manifest recorded at save time. A failure here means even the
        # rollback substrate is compromised; surface it rather than resume.
        sent = getattr(trainer, "sentinel", None)
        if sent is not None and restored is not None:
            res = sent.audit_now(trainer, step=restored)
            stats_mod.record_sentinel("reaudits")
            want = self.ckpt.recorded_fingerprint(restored)
            if not res.equal or (want is not None and res.digest != want):
                raise MLSLIntegrityError(
                    f"post-restore re-audit failed at step {restored}: "
                    f"replicas_equal={res.equal}, digest "
                    f"{res.digest[:16]} vs recorded "
                    f"{(want or '<unverified>')[:16]} — the restored "
                    "checkpoint does not reproduce its verified state"
                ) from error
            log_info(
                "post-restore re-audit passed at step %d (digest %s%s)",
                restored, res.digest[:16],
                ", matches manifest" if want is not None else "",
            )
        if tr is not None:
            # one span per recovery cycle: drain + teardown + rebuild +
            # restore — on the timeline this is the gap a fault cost the run
            tr.complete("recover", "resilience", t0,
                        error=type(error).__name__, recovery=self.recoveries,
                        resumed_step=restored if restored is not None else -1)
        return trainer, (restored + 1 if restored is not None else 0)

    def _warn_if_sentinel_unwired(self, trainer) -> None:
        """MLSL_SENTINEL_* armed but the trainer type carries no sentinel
        (only DataParallelTrainer is wired today): say so LOUDLY — an
        operator who exported the knobs believes the integrity layer is on,
        and a silent no-op is exactly the failure mode this subsystem
        exists to eliminate."""
        if getattr(trainer, "sentinel", None) is not None:
            return
        from mlsl_tpu import sentinel as sentinel_mod
        from mlsl_tpu.core.environment import Environment

        env = Environment._instance
        cfg = env.config if env is not None else None
        if cfg is not None and sentinel_mod.armed(cfg):
            log_warning(
                "MLSL_SENTINEL_* is armed but %s carries no integrity "
                "sentinel — gates, audits, and verified checkpoints are "
                "INACTIVE for this run (sentinel wiring currently covers "
                "DataParallelTrainer only)", type(trainer).__name__,
            )

    def _maybe_shed_straggler(self, trainer, step: int):
        """Between-steps poll of the trainer's straggler sentinel
        (obs/straggler.py): a confirmed straggler with ``MLSL_STRAGGLER_SHED``
        armed is handed to the elastic coordinator as a synthetic device
        loss (``ElasticCoordinator.shed``) — measurement closed into action.
        A refused/failed shed (capacity budget, mapping failure) logs and
        keeps the full world: shedding a slow replica is an optimization,
        never worth availability. Returns the (possibly shrunk) trainer."""
        strag = getattr(trainer, "straggler", None)
        if strag is None or self.elastic is None:
            return trainer
        cand = strag.shed_candidate()
        if cand is None:
            return trainer
        try:
            new_trainer = self.elastic.shed(
                trainer, self.make_trainer, replica=cand, step=step
            )
        except Exception as e:
            log_warning(
                "straggler shed of replica %s refused (%s: %s); continuing "
                "on the full world", cand, type(e).__name__, e,
            )
            strag.clear_candidate()
            return trainer
        strag.clear_candidate()
        return new_trainer

    def _abort(self, step: int, error: BaseException, why: str) -> None:
        """The ladder's last rung is exhausted: every retry and breaker
        fallback failed to absorb this fault, and ``why`` names the bound
        that actually stopped the loop (same-step retry bound vs run-wide
        restart budget — a post-mortem must point at the right knob).
        Leave maximal evidence — the error's class, the breaker states, and
        (when tracing is armed) a flight record of the trailing timeline —
        then the caller re-raises. Never raises itself: the original error
        must surface, not an abort-path artifact."""
        try:
            cls = supervisor.classify(error)
            status = supervisor.status()
            states = {
                # breaker-shaped entries only: 'analysis' (verdict-shaped),
                # 'elastic' (mesh-shaped, 'full'/'shrunk'), 'straggler'
                # ('off'/'watching'/'flagged') and 'control' (membership-
                # shaped, 'off'/'member'/'leader') have their own stats
                # lines and their own fields below — not breakers
                name: st["state"]
                for name, st in status.items()
                if "state" in st
                and name not in ("elastic", "straggler", "control")
            }
            log_error(
                "recovery ladder exhausted at step %d (%s; %d/%d recoveries "
                "spent): %s: %s [class=%s] breakers=%s elastic=%s "
                "straggler=%s",
                step, why, self.recoveries, self.max_total_recoveries,
                type(error).__name__, error, cls.value, states,
                status.get("elastic", {}).get("state", "?"),
                status.get("straggler", {}).get("state", "?"),
            )
            if obs._tracer is not None:
                from mlsl_tpu.obs import export as obs_export

                path = obs_export.flight_record(
                    window_s=60.0,
                    reason=f"{why} at step {step}: "
                           f"{type(error).__name__}: {error}",
                )
                if path:
                    log_warning("abort flight record written: %s", path)
        except Exception as e:  # pragma: no cover - defensive (abort path)
            log_warning("abort diagnostics failed: %s: %s", type(e).__name__, e)

    def run(self, batch_fn: Callable, steps: int, on_step: Optional[Callable] = None):
        """Train for ``steps`` steps; returns the final trainer.

        Returns early (with ``self.preempted`` set and a final checkpoint on
        disk) when a handled preemption signal arrives mid-run."""
        trainer = self.make_trainer()
        self._arm_elastic_if_configured()  # the factory just ran env init
        self._warn_if_sentinel_unwired(trainer)
        restored = restore_trainer(self.ckpt, trainer)
        step = restored + 1 if restored is not None else 0
        # retry accounting is keyed to the step that failed: resuming several
        # steps behind the failure must not reset the count (deterministic
        # poison would otherwise livelock through recover/replay cycles)
        failed_step = None
        attempts = 0
        reported = step - 1  # on_step fires once per step, replays stay silent
        last_saved = restored
        self.preempted = False
        # pod control plane (mlsl_tpu.control): committed membership losses
        # surface HERE, on the dispatch thread, as the device-loss error the
        # reshard rung below absorbs — the control threads only queue (the
        # A202 contract). Pod-level elastic decisions (grow re-admission,
        # straggler shed) are re-homed behind the elected leader; followers
        # apply committed epochs instead of originating them.
        from mlsl_tpu import control as control_mod

        plane = control_mod.get_active()
        guard = PreemptionGuard() if self.handle_preemption else None
        with guard if guard is not None else _NULL_GUARD:
            while step < steps:
                try:
                    if plane is not None:
                        pod_fault = plane.take_loss()
                        if pod_fault is not None:
                            raise pod_fault
                    if self.elastic is not None and (
                        plane is None or plane.may_decide()
                    ):
                        # between-steps growth poll: returned capacity is
                        # re-admitted (through the fingerprint admission
                        # audit) before the step runs; failures route
                        # through the standard ladder below
                        trainer = self.elastic.maybe_grow(
                            trainer, self.make_trainer, step
                        )
                    if self.fault_hook is not None:
                        self.fault_hook(
                            step, attempts if step == failed_step else 0
                        )
                    _t0 = _time.monotonic()
                    loss = trainer.step(batch_fn(trainer, step))
                    jax.block_until_ready(trainer.params)
                    if plane is not None:
                        # publish this member's health + step clock for the
                        # next heartbeat frame (host-read scalars only)
                        plane.push_status(
                            supervisor.status(), step=step,
                            step_ms=(_time.monotonic() - _t0) * 1e3,
                        )
                    sent = getattr(trainer, "sentinel", None)
                    if sent is not None:
                        # cadence audit (MLSL_SENTINEL_EVERY): divergence
                        # raises MLSLIntegrityError -> the recovery path
                        # below, where restore prefers verified steps
                        sent.maybe_audit(trainer, step)
                    # straggler shed poll (obs/straggler.py): a confirmed
                    # slow replica becomes a synthetic DEVICE_LOSS through
                    # the elastic coordinator; failures keep the full world.
                    # Leader-only in a pod: a shed is a pod-level decision.
                    if plane is None or plane.may_decide():
                        trainer = self._maybe_shed_straggler(trainer, step)
                    if step % self.save_every == 0:
                        # inside the try: a device fault surfacing during the save's
                        # device read must take the recovery path too
                        fp = None
                        if sent is not None:
                            # audit at the checkpoint boundary: a passing
                            # digest marks the step VERIFIED in its manifest;
                            # divergence raises instead of poisoning the
                            # checkpoint history
                            fp = sent.checkpoint_fingerprint(trainer, step)
                        save_trainer(self.ckpt, trainer, step=step,
                                     fingerprint=fp)
                        last_saved = step
                except RECOVERABLE as e:
                    if (
                        self.elastic is not None
                        and supervisor.classify(e)
                        is supervisor.ErrorClass.DEVICE_LOSS
                    ):
                        # the reshard rung: shrink to the survivor mesh and
                        # CONTINUE at this very step — the failed step never
                        # applied its update, so the loss trajectory stays
                        # continuous with zero checkpoint restores and no
                        # recovery spent. A refused/failed shrink (capacity
                        # budget, drain failure) falls through to restart.
                        try:
                            trainer = self.elastic.shrink(
                                trainer, self.make_trainer, error=e,
                                step=step,
                            )
                        except Exception as ee:
                            log_warning(
                                "elastic reshard failed (%s: %s); device "
                                "loss falls back to the restart rung",
                                type(ee).__name__, ee,
                            )
                        else:
                            continue
                    if step == failed_step:
                        attempts += 1
                    else:
                        failed_step, attempts = step, 1
                    if (
                        attempts > self.max_retries
                        or self.recoveries >= self.max_total_recoveries
                    ):
                        self._abort(
                            step, e,
                            "same-step retry bound exceeded"
                            if attempts > self.max_retries
                            else "restart budget exhausted",
                        )
                        raise
                    trainer, step = self._recover(trainer, e)
                    last_saved = step - 1 if step > 0 else None
                    continue
                if on_step is not None and step > reported:
                    on_step(step, loss)
                    reported = step
                # pod drain decisions arrive out-of-band (the leader
                # broadcasts one verdict per noticed rank): consume any
                # pending one; a shrink aimed at ANOTHER rank is the
                # survivors' business (their loss event reshards the mesh)
                drain = plane.take_drain() if plane is not None else None
                if (
                    guard is not None and guard.triggered
                    and drain is None and plane is not None
                ):
                    # coordinated drain: submit the SIGTERM as a structured
                    # notice and wait (bounded) for the pod's ONE decision;
                    # a timeout (partitioned leader) falls back to the
                    # local drain below rather than hanging the grace window
                    drain = plane.coordinate_preemption("sigterm")
                if (guard is not None and guard.triggered) or (
                    drain is not None
                    and (drain["mode"] == "save"
                         or drain["rank"] == plane.rank)
                ):
                    # drain in-flight saves and leave a final resume point; a
                    # failure here must not abort the graceful exit — the last
                    # cadence checkpoint remains the resume point
                    self.preempted = True
                    if obs._tracer is not None:
                        obs._tracer.instant("preemption", "resilience",
                                            step=step)
                    try:
                        if last_saved != step:
                            log_info(
                                "preemption: writing final checkpoint at step %d",
                                step,
                            )
                            sent = getattr(trainer, "sentinel", None)
                            save_trainer(
                                self.ckpt, trainer, step=step, wait=True,
                                fingerprint=(
                                    sent.checkpoint_fingerprint(trainer, step)
                                    if sent is not None else None
                                ),
                            )
                        self.ckpt.wait()
                        log_info(
                            "preemption drain complete; stopping at step %d", step
                        )
                    except Exception as e:
                        log_warning(
                            "preemption drain failed (%s: %s); resume point is "
                            "the last committed checkpoint",
                            type(e).__name__, e,
                        )
                    if plane is not None:
                        plane.record_drain_executed(
                            step, drain["mode"] if drain is not None
                            else "local",
                        )
                    break
                step += 1
        self.ckpt.wait()
        return trainer
