"""Logging and assertion utilities.

TPU-native counterpart of the reference's MLSL_LOG/MLSL_ASSERT macros
(src/log.hpp:35-83): level-gated logging with timestamp/function/line, a backtrace on
ERROR, and an assert that finalizes the environment before raising. Unlike the
reference, failure raises ``MLSLError`` instead of calling ``_exit(1)`` — idiomatic for
a Python-driven runtime and testable.
"""

from __future__ import annotations

import enum
import os
import sys
import time
import traceback


class LogLevel(enum.IntEnum):
    ERROR = 0
    INFO = 1
    DEBUG = 2
    TRACE = 3


_level = LogLevel(int(os.environ.get("MLSL_LOG_LEVEL", "0")))


class MLSLError(RuntimeError):
    """Raised on MLSL_ASSERT failure (reference aborts via _exit; we raise)."""


class MLSLTimeoutError(MLSLError):
    """Raised by the request watchdog when an async request exceeds
    MLSL_WATCHDOG_TIMEOUT. Subclasses MLSLError (-> RuntimeError), so
    FaultTolerantLoop treats a hung collective as recoverable: tear down,
    rebuild, restore — instead of blocking forever."""


class MLSLCorruptionError(MLSLError):
    """Data-integrity failure: bitrot, a codec round-trip that does not
    verify, a checksum mismatch. Classified CORRUPTION by the recovery
    supervisor (mlsl_tpu.supervisor): the producing subsystem is suspect, so
    the failure counts against that subsystem's circuit breaker and degrades
    it to the always-correct path rather than retrying in place."""


class MLSLDeviceLossError(MLSLError):
    """A device (or whole slice) left the world: preemption notice, ICI
    neighbor loss, or an injected ``device.lost`` chaos fault. Classified
    DEVICE_LOSS by the recovery supervisor — it must never be retried in
    place or absorbed by a circuit breaker (the capacity is *gone*; a
    fallback dispatch on the same mesh only masks the loss). The elastic
    coordinator (mlsl_tpu.elastic) answers it by re-deriving the mesh among
    survivors and re-sharding optimizer state live; without one,
    FaultTolerantLoop falls back to checkpoint restart.

    ``devices``: the lost jax.Device set when the detector knows it (a
    preemption notice names its host); empty when only the loss itself is
    observed — the coordinator then applies its default shed policy."""

    def __init__(self, msg: str, devices=()):
        super().__init__(msg)
        self.devices = tuple(devices)


class MLSLIntegrityError(MLSLCorruptionError):
    """TRAINING-STATE integrity failure, raised by the integrity sentinel
    (mlsl_tpu.sentinel): a step-quality gate escalated to rollback, a
    cross-replica consistency audit found params/optimizer state diverged,
    or a post-restore re-audit did not reproduce the recorded fingerprint.
    Subclasses MLSLCorruptionError, so the supervisor taxonomy classifies it
    CORRUPTION; FaultTolerantLoop answers it with rollback to the newest
    VERIFIED checkpoint (one whose manifest carries a passing audit
    fingerprint) instead of the newest step."""


def set_log_level(level: int | LogLevel) -> None:
    global _level
    _level = LogLevel(int(level))


def get_log_level() -> LogLevel:
    return _level


def _emit(level: LogLevel, msg: str, *args, label: str | None = None) -> None:
    if level > _level:
        return
    frame = sys._getframe(2)  # cheap caller lookup; inspect.stack() walks everything
    text = msg % args if args else msg
    ts = time.strftime("%H:%M:%S", time.localtime())
    print(
        f"[{ts}] mlsl_tpu {label or level.name} "
        f"{frame.f_code.co_name}:{frame.f_lineno} {text}",
        file=sys.stderr,
        flush=True,
    )
    if level == LogLevel.ERROR and label is None:
        traceback.print_stack(file=sys.stderr)


def log_error(msg: str, *args) -> None:
    _emit(LogLevel.ERROR, msg, *args)


def log_warning(msg: str, *args) -> None:
    """Always surfaces (gated like ERROR) but without the backtrace dump —
    for suppressed-but-diagnosable conditions (teardown failures during
    recovery, threads outliving their join timeout, checkpoint fallbacks)."""
    _emit(LogLevel.ERROR, msg, *args, label="WARNING")


def log_info(msg: str, *args) -> None:
    _emit(LogLevel.INFO, msg, *args)


def log_debug(msg: str, *args) -> None:
    _emit(LogLevel.DEBUG, msg, *args)


def log_trace(msg: str, *args) -> None:
    _emit(LogLevel.TRACE, msg, *args)


def mlsl_assert(cond: bool, msg: str, *args) -> None:
    """Assert helper mirroring MLSL_ASSERT (src/log.hpp:72-83).

    The reference finalizes and _exit(1)s because C++ cannot unwind safely; a Python
    library raises instead — the Environment stays usable so a caller that catches the
    error (validation failures, bad wiring) can continue or finalize explicitly.
    """
    if cond:
        return
    raise MLSLError(msg % args if args else msg)
