"""Pipeline parallelism: GPipe-style microbatch schedule over the model axis.

The reference declares (but never implements) the point-to-point primitive a pipeline
needs — SendRecvList (src/comm.hpp:212-248). This module is that capability completed:
pipeline stages live on the 'model' mesh axis, microbatch activations flow stage->
stage+1 via lax.ppermute (the SendRecvList realization), and a fill-drain schedule
keeps every stage busy once the pipeline is full. Differentiating through the schedule
gives the reversed (drain-fill) backward automatically — JAX transposes ppermute to
the opposite shift — so training just calls jax.grad on the pipelined loss.

Usage (inside or outside shard_map via the provided driver):
    out = gpipe_forward(stage_fn, stage_params, x_micro, axis, n_stages)
with stage_fn(params, x) -> y applied at every stage (all stages share the fn shape;
per-stage weights differ — the usual homogeneous-blocks pipeline).
"""

from __future__ import annotations

from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mlsl_tpu.parallel.sequence import _pvary


def gpipe_forward(
    stage_fn: Callable,
    stage_params,
    x_micro: jax.Array,
    axis: str,
    n_stages: int,
    remat: bool = False,
):
    """SPMD body (call inside shard_map over ``axis`` of size n_stages).

    stage_params: this stage's weights (the caller shards them over ``axis``).
    x_micro: (M, mb, d_in) microbatches — the stage-0 input (replicated copies on
    other stages are ignored).
    remat: wrap the stage in jax.checkpoint so the backward replay recomputes
    stage internals instead of storing per-tick activations — bounds pipeline
    activation memory by the stage boundary size rather than the stage interior
    (the practical core of the 1F1B memory benefit).
    Returns (M, mb, d_out): the last stage's outputs (zeros elsewhere; reduce with
    a psum/select or read the last stage's shard).
    """
    if remat:
        # prevent_cse=False: XLA never CSEs across loop iterations, so inside the
        # fori/scan body the default's optimization barriers would only block fusion
        stage_fn = jax.checkpoint(stage_fn, prevent_cse=False)
    m_count, mb, _ = x_micro.shape
    me = lax.axis_index(axis)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    ticks = m_count + n_stages - 1

    probe = jax.eval_shape(stage_fn, stage_params, x_micro[0])
    d_out = probe.shape[-1]
    assert d_out == x_micro.shape[-1], (
        "pipeline boundary width mismatch: stage_fn maps wire width "
        f"{x_micro.shape[-1]} -> {d_out}; pad heterogeneous stages to a common "
        "wire width (see pad_stage_weights)"
    )

    outs = _pvary(jnp.zeros((m_count, mb, d_out), probe.dtype), axis)
    recv = _pvary(jnp.zeros((mb, d_out), probe.dtype), axis)

    def tick(t, state):
        recv, outs = state
        mb_idx = t - me                       # which microbatch this stage handles
        active = jnp.logical_and(mb_idx >= 0, mb_idx < m_count)
        safe_idx = jnp.clip(mb_idx, 0, m_count - 1)
        inp = jnp.where(
            me == 0,
            lax.dynamic_index_in_dim(x_micro, safe_idx, axis=0, keepdims=False),
            recv,
        )
        y = stage_fn(stage_params, inp)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # last stage banks its result for microbatch mb_idx (same clamped index
        # as the input selection)
        is_last = me == n_stages - 1
        banked = lax.dynamic_update_index_in_dim(
            outs,
            jnp.where(jnp.logical_and(is_last, active), y,
                      lax.dynamic_index_in_dim(outs, safe_idx, axis=0, keepdims=False)),
            safe_idx,
            axis=0,
        )
        # boundary transfer: stage s -> s+1 (the SendRecvList ring)
        recv_next = lax.ppermute(y, axis, perm)
        return recv_next, banked

    _, outs = lax.fori_loop(0, ticks, tick, (recv, outs))
    return outs


def pad_stage_weights(weights, biases, boundary_dims):
    """Make heterogeneous-width pipeline stages wire-uniform by zero-padding.

    ppermute moves fixed-shape buffers, so differing boundary widths ride a wire
    padded to d_wire = max(boundary_dims); padding a stage's (d_in, d_out) weight
    matrix into (d_wire, d_wire) with zeros makes the padded lanes self-annihilating
    — y_pad = [y, 0...] exactly, provided the stage activation maps 0 to 0 (tanh,
    relu, gelu do; add biases only on real lanes, which the padded bias guarantees).

    weights[s]: (d_in_s, d_out_s) with d_in_s = boundary_dims[s],
    d_out_s = boundary_dims[s+1]; biases[s]: (d_out_s,).
    -> (stacked (S, d_wire, d_wire), stacked (S, d_wire), d_wire), in the weights'
    own dtype. The caller pads its input to d_wire and slices the output to
    boundary_dims[-1].
    """
    d_wire = max(boundary_dims)
    s_count = len(weights)
    dtype = np.asarray(weights[0]).dtype
    w_pad = np.zeros((s_count, d_wire, d_wire), dtype)
    b_pad = np.zeros((s_count, d_wire), dtype)
    for s in range(s_count):
        d_in, d_out = boundary_dims[s], boundary_dims[s + 1]
        assert weights[s].shape == (d_in, d_out), (
            f"stage {s}: weight {weights[s].shape} != ({d_in}, {d_out})"
        )
        w_pad[s, :d_in, :d_out] = weights[s]
        b_pad[s, :d_out] = biases[s]
    return w_pad, b_pad, d_wire


def f1b_schedule(n_stages: int, m_count: int) -> dict:
    """Static 1F1B schedule facts (for tests/telemetry, no tracing).

    Tick model: stage s runs forward of microbatch i at tick 2i+s and backward of
    i at tick 2i+2S-1-s. F ticks have parity (t-s) even, B ticks odd, so each
    stage does exactly one op per tick in steady state (the 1F1B alternation).
    """
    S, M = n_stages, m_count
    ticks = 2 * M + 2 * S - 2
    busy = 2 * M * S  # one F + one B per (stage, microbatch)
    return {
        "ticks": ticks,
        "utilization": busy / (ticks * S),
        "bubble_fraction": 1.0 - busy / (ticks * S),
        # microbatches resident between their F and B at stage s: S - s, vs
        # GPipe's M at every stage — the 1F1B memory bound.
        "peak_in_flight": [S - s for s in range(S)],
        "gpipe_peak_in_flight": [M] * S,
    }


def one_f1b_step(
    stage_fn: Callable,
    loss_head: Callable,
    stage_params,
    x_micro: jax.Array,
    y_micro: jax.Array,
    axis: str,
    n_stages: int,
):
    """1F1B pipeline schedule: (loss, stage_grads) without O(M) activation memory.

    SPMD body (call inside shard_map over ``axis``). Unlike differentiating
    ``pipeline_loss`` (GPipe: full forward sweep, then the autodiff-transposed
    sweep, saving residuals for every one of the M microbatches), this interleaves
    each microbatch's backward one-forward-one-backward style, so a stage holds at
    most S - s in-flight boundary activations (f1b_schedule). The backward leg
    rematerializes the stage from its saved INPUT (explicit remat: only the (mb, d)
    boundary tensor is stored, stage internals are recomputed in the vjp), and the
    tick loop itself is never differentiated — gradients come from per-tick
    jax.vjp calls, accumulated directly.

    Wire realization of the reference's declared-but-unimplemented SendRecvList
    p2p primitive (src/comm.hpp:212-248): forward boundary rides ppermute(+1),
    gradient boundary rides ppermute(-1), both every tick.

    Requires stage_fn to preserve the wire width (see pad_stage_weights) and
    loss_head(y, target) -> scalar. Returns (psum'd scalar loss, grads for THIS
    stage's params).
    """
    m_count, mb, d = x_micro.shape
    S = n_stages
    me = lax.axis_index(axis)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]
    ticks = 2 * m_count + 2 * S - 2

    probe = jax.eval_shape(stage_fn, stage_params, x_micro[0])
    assert probe.shape[-1] == d, (
        f"pipeline boundary width mismatch: {d} -> {probe.shape[-1]}"
    )

    # In-flight boundary inputs: slot i % S is free again strictly before
    # microbatch i+S forwards (B_i at tick 2i+2S-1-2s < F_{i+S} at 2i+2S+s...
    # equality never holds since parities differ at s=0: 2i+2S-1 < 2i+2S).
    x_buf = _pvary(jnp.zeros((S, mb, d), probe.dtype), axis)
    recv_f = _pvary(jnp.zeros((mb, d), probe.dtype), axis)
    recv_b = _pvary(jnp.zeros((mb, d), probe.dtype), axis)
    grads0 = jax.tree.map(lambda p: jnp.zeros_like(p), stage_params)
    is_last = me == S - 1

    def tick(t, state):
        recv_f, recv_b, x_buf, grads, loss_acc = state
        rel = t - me
        f_idx = rel // 2                      # floor div: negative -> inactive
        f_active = jnp.logical_and(rel % 2 == 0,
                                   jnp.logical_and(f_idx >= 0, f_idx < m_count))
        b_idx = (t + me - (2 * S - 1)) // 2
        b_active = jnp.logical_and(rel % 2 != 0,
                                   jnp.logical_and(b_idx >= 0, b_idx < m_count))
        f_slot = jnp.clip(f_idx, 0, m_count - 1) % S
        b_slot = jnp.clip(b_idx, 0, m_count - 1) % S

        def f_branch(args):
            recv_f, recv_b, x_buf, grads, loss_acc = args
            inp = jnp.where(
                me == 0,
                lax.dynamic_index_in_dim(
                    x_micro, jnp.clip(f_idx, 0, m_count - 1), 0, keepdims=False
                ),
                recv_f,
            )
            y = stage_fn(stage_params, inp)
            x_buf = jnp.where(
                f_active,
                lax.dynamic_update_index_in_dim(x_buf, inp, f_slot, axis=0),
                x_buf,
            )
            send_f = jnp.where(f_active, y, jnp.zeros_like(y))
            return x_buf, grads, loss_acc, send_f, jnp.zeros((mb, d), probe.dtype)

        def b_branch(args):
            recv_f, recv_b, x_buf, grads, loss_acc = args
            x_saved = lax.dynamic_index_in_dim(x_buf, b_slot, 0, keepdims=False)
            y, vjp = jax.vjp(stage_fn, stage_params, x_saved)
            target = lax.dynamic_index_in_dim(
                y_micro, jnp.clip(b_idx, 0, m_count - 1), 0, keepdims=False
            )
            loss_val, dy_last = jax.value_and_grad(loss_head)(y, target)
            dy = jnp.where(is_last, dy_last, recv_b)
            dp, dx = vjp(dy)
            grads = jax.tree.map(
                lambda g, d_: g + jnp.where(b_active, d_, jnp.zeros_like(d_)),
                grads, dp,
            )
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(is_last, b_active), loss_val, 0.0
            )
            send_b = jnp.where(b_active, dx, jnp.zeros_like(dx))
            return x_buf, grads, loss_acc, jnp.zeros((mb, d), probe.dtype), send_b

        # F and B parities are disjoint, so exactly one branch runs per tick per
        # stage; the branches hold no collectives, so divergent per-device
        # control flow is safe (the ppermutes below are unconditional).
        x_buf, grads, loss_acc, send_f, send_b = lax.cond(
            rel % 2 == 0, f_branch, b_branch,
            (recv_f, recv_b, x_buf, grads, loss_acc),
        )
        recv_f = lax.ppermute(send_f, axis, fwd_perm)
        recv_b = lax.ppermute(send_b, axis, bwd_perm)
        return recv_f, recv_b, x_buf, grads, loss_acc

    _, _, _, grads, loss_acc = lax.fori_loop(
        0, ticks, tick, (recv_f, recv_b, x_buf, grads0, jnp.float32(0.0))
    )
    return lax.psum(loss_acc, axis), grads


def pipeline_loss(
    stage_fn: Callable,
    loss_head: Callable,
    stage_params,
    x_micro: jax.Array,
    y_micro: jax.Array,
    axis: str,
    n_stages: int,
    remat: bool = False,
):
    """Pipelined forward + loss on the last stage, psum'd so every stage holds the
    scalar (ready for jax.grad: the backward replays the schedule in reverse)."""
    outs = gpipe_forward(stage_fn, stage_params, x_micro, axis, n_stages, remat=remat)
    me = lax.axis_index(axis)
    per_micro = jax.vmap(loss_head)(outs, y_micro)          # (M,)
    local = jnp.where(me == n_stages - 1, jnp.sum(per_micro), 0.0)
    return lax.psum(local, axis)
