"""Pipeline parallelism: GPipe-style microbatch schedule over the model axis.

The reference declares (but never implements) the point-to-point primitive a pipeline
needs — SendRecvList (src/comm.hpp:212-248). This module is that capability completed:
pipeline stages live on the 'model' mesh axis, microbatch activations flow stage->
stage+1 via lax.ppermute (the SendRecvList realization), and a fill-drain schedule
keeps every stage busy once the pipeline is full. Differentiating through the schedule
gives the reversed (drain-fill) backward automatically — JAX transposes ppermute to
the opposite shift — so training just calls jax.grad on the pipelined loss.

Usage (inside or outside shard_map via the provided driver):
    out = gpipe_forward(stage_fn, stage_params, x_micro, axis, n_stages)
with stage_fn(params, x) -> y applied at every stage (all stages share the fn shape;
per-stage weights differ — the usual homogeneous-blocks pipeline).
"""

from __future__ import annotations

from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mlsl_tpu.comm import algos
from mlsl_tpu.parallel.sequence import _pvary

# The stage->stage boundary ppermutes below stay RAW in-graph collectives
# (per-site pragmas): they are this module's primitive — the SendRecvList
# realization — and must remain lax.ppermute so jax.grad transposes them
# into the drain-fill backward. Everything reduction-shaped (the microbatch
# loss sums and the data-parallel gradient reduction) routes through the
# collective engine instead (comm/algos inline helpers / overlap engine),
# so the selection table, breakers, and stats see it.


def gpipe_forward(
    stage_fn: Callable,
    stage_params,
    x_micro: jax.Array,
    axis: str,
    n_stages: int,
    remat: bool = False,
):
    """SPMD body (call inside shard_map over ``axis`` of size n_stages).

    stage_params: this stage's weights (the caller shards them over ``axis``).
    x_micro: (M, mb, d_in) microbatches — the stage-0 input (replicated copies on
    other stages are ignored).
    remat: wrap the stage in jax.checkpoint so the backward replay recomputes
    stage internals instead of storing per-tick activations — bounds pipeline
    activation memory by the stage boundary size rather than the stage interior
    (the practical core of the 1F1B memory benefit).
    Returns (M, mb, d_out): the last stage's outputs (zeros elsewhere; reduce with
    a psum/select or read the last stage's shard).
    """
    if remat:
        # prevent_cse=False: XLA never CSEs across loop iterations, so inside the
        # fori/scan body the default's optimization barriers would only block fusion
        stage_fn = jax.checkpoint(stage_fn, prevent_cse=False)
    m_count, mb, _ = x_micro.shape
    me = lax.axis_index(axis)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    ticks = m_count + n_stages - 1

    probe = jax.eval_shape(stage_fn, stage_params, x_micro[0])
    d_out = probe.shape[-1]
    assert d_out == x_micro.shape[-1], (
        "pipeline boundary width mismatch: stage_fn maps wire width "
        f"{x_micro.shape[-1]} -> {d_out}; pad heterogeneous stages to a common "
        "wire width (see pad_stage_weights)"
    )

    outs = _pvary(jnp.zeros((m_count, mb, d_out), probe.dtype), axis)
    recv = _pvary(jnp.zeros((mb, d_out), probe.dtype), axis)

    def tick(t, state):
        recv, outs = state
        mb_idx = t - me                       # which microbatch this stage handles
        active = jnp.logical_and(mb_idx >= 0, mb_idx < m_count)
        safe_idx = jnp.clip(mb_idx, 0, m_count - 1)
        inp = jnp.where(
            me == 0,
            lax.dynamic_index_in_dim(x_micro, safe_idx, axis=0, keepdims=False),
            recv,
        )
        y = stage_fn(stage_params, inp)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # last stage banks its result for microbatch mb_idx (same clamped index
        # as the input selection)
        is_last = me == n_stages - 1
        banked = lax.dynamic_update_index_in_dim(
            outs,
            jnp.where(jnp.logical_and(is_last, active), y,
                      lax.dynamic_index_in_dim(outs, safe_idx, axis=0, keepdims=False)),
            safe_idx,
            axis=0,
        )
        # boundary transfer: stage s -> s+1 (the SendRecvList ring)
        recv_next = lax.ppermute(y, axis, perm)  # mlsl-lint: disable=A201 -- boundary primitive
        return recv_next, banked

    _, outs = lax.fori_loop(0, ticks, tick, (recv, outs))
    return outs


def pad_stage_weights(weights, biases, boundary_dims):
    """Make heterogeneous-width pipeline stages wire-uniform by zero-padding.

    ppermute moves fixed-shape buffers, so differing boundary widths ride a wire
    padded to d_wire = max(boundary_dims); padding a stage's (d_in, d_out) weight
    matrix into (d_wire, d_wire) with zeros makes the padded lanes self-annihilating
    — y_pad = [y, 0...] exactly, provided the stage activation maps 0 to 0 (tanh,
    relu, gelu do; add biases only on real lanes, which the padded bias guarantees).

    weights[s]: (d_in_s, d_out_s) with d_in_s = boundary_dims[s],
    d_out_s = boundary_dims[s+1]; biases[s]: (d_out_s,).
    -> (stacked (S, d_wire, d_wire), stacked (S, d_wire), d_wire), in the weights'
    own dtype. The caller pads its input to d_wire and slices the output to
    boundary_dims[-1].
    """
    d_wire = max(boundary_dims)
    s_count = len(weights)
    dtype = np.asarray(weights[0]).dtype
    w_pad = np.zeros((s_count, d_wire, d_wire), dtype)
    b_pad = np.zeros((s_count, d_wire), dtype)
    for s in range(s_count):
        d_in, d_out = boundary_dims[s], boundary_dims[s + 1]
        assert weights[s].shape == (d_in, d_out), (
            f"stage {s}: weight {weights[s].shape} != ({d_in}, {d_out})"
        )
        w_pad[s, :d_in, :d_out] = weights[s]
        b_pad[s, :d_out] = biases[s]
    return w_pad, b_pad, d_wire


def f1b_schedule(n_stages: int, m_count: int) -> dict:
    """Static 1F1B schedule facts (for tests/telemetry, no tracing).

    Tick model: stage s runs forward of microbatch i at tick 2i+s and backward of
    i at tick 2i+2S-1-s. F ticks have parity (t-s) even, B ticks odd, so each
    stage does exactly one op per tick in steady state (the 1F1B alternation).
    """
    S, M = n_stages, m_count
    ticks = 2 * M + 2 * S - 2
    busy = 2 * M * S  # one F + one B per (stage, microbatch)
    return {
        "ticks": ticks,
        "utilization": busy / (ticks * S),
        "bubble_fraction": 1.0 - busy / (ticks * S),
        # microbatches resident between their F and B at stage s: S - s, vs
        # GPipe's M at every stage — the 1F1B memory bound.
        "peak_in_flight": [S - s for s in range(S)],
        "gpipe_peak_in_flight": [M] * S,
    }


def one_f1b_step(
    stage_fn: Callable,
    loss_head: Callable,
    stage_params,
    x_micro: jax.Array,
    y_micro: jax.Array,
    axis: str,
    n_stages: int,
):
    """1F1B pipeline schedule: (loss, stage_grads) without O(M) activation memory.

    SPMD body (call inside shard_map over ``axis``). Unlike differentiating
    ``pipeline_loss`` (GPipe: full forward sweep, then the autodiff-transposed
    sweep, saving residuals for every one of the M microbatches), this interleaves
    each microbatch's backward one-forward-one-backward style, so a stage holds at
    most S - s in-flight boundary activations (f1b_schedule). The backward leg
    rematerializes the stage from its saved INPUT (explicit remat: only the (mb, d)
    boundary tensor is stored, stage internals are recomputed in the vjp), and the
    tick loop itself is never differentiated — gradients come from per-tick
    jax.vjp calls, accumulated directly.

    Wire realization of the reference's declared-but-unimplemented SendRecvList
    p2p primitive (src/comm.hpp:212-248): forward boundary rides ppermute(+1),
    gradient boundary rides ppermute(-1), both every tick.

    Requires stage_fn to preserve the wire width (see pad_stage_weights) and
    loss_head(y, target) -> scalar. Returns (psum'd scalar loss, grads for THIS
    stage's params).
    """
    m_count, mb, d = x_micro.shape
    S = n_stages
    me = lax.axis_index(axis)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]
    ticks = 2 * m_count + 2 * S - 2

    probe = jax.eval_shape(stage_fn, stage_params, x_micro[0])
    assert probe.shape[-1] == d, (
        f"pipeline boundary width mismatch: {d} -> {probe.shape[-1]}"
    )

    # In-flight boundary inputs: slot i % S is free again strictly before
    # microbatch i+S forwards (B_i at tick 2i+2S-1-2s < F_{i+S} at 2i+2S+s...
    # equality never holds since parities differ at s=0: 2i+2S-1 < 2i+2S).
    x_buf = _pvary(jnp.zeros((S, mb, d), probe.dtype), axis)
    recv_f = _pvary(jnp.zeros((mb, d), probe.dtype), axis)
    recv_b = _pvary(jnp.zeros((mb, d), probe.dtype), axis)
    grads0 = jax.tree.map(lambda p: jnp.zeros_like(p), stage_params)
    is_last = me == S - 1

    def tick(t, state):
        recv_f, recv_b, x_buf, grads, loss_acc = state
        rel = t - me
        f_idx = rel // 2                      # floor div: negative -> inactive
        f_active = jnp.logical_and(rel % 2 == 0,
                                   jnp.logical_and(f_idx >= 0, f_idx < m_count))
        b_idx = (t + me - (2 * S - 1)) // 2
        b_active = jnp.logical_and(rel % 2 != 0,
                                   jnp.logical_and(b_idx >= 0, b_idx < m_count))
        f_slot = jnp.clip(f_idx, 0, m_count - 1) % S
        b_slot = jnp.clip(b_idx, 0, m_count - 1) % S

        def f_branch(args):
            recv_f, recv_b, x_buf, grads, loss_acc = args
            inp = jnp.where(
                me == 0,
                lax.dynamic_index_in_dim(
                    x_micro, jnp.clip(f_idx, 0, m_count - 1), 0, keepdims=False
                ),
                recv_f,
            )
            y = stage_fn(stage_params, inp)
            x_buf = jnp.where(
                f_active,
                lax.dynamic_update_index_in_dim(x_buf, inp, f_slot, axis=0),
                x_buf,
            )
            send_f = jnp.where(f_active, y, jnp.zeros_like(y))
            return x_buf, grads, loss_acc, send_f, jnp.zeros((mb, d), probe.dtype)

        def b_branch(args):
            recv_f, recv_b, x_buf, grads, loss_acc = args
            x_saved = lax.dynamic_index_in_dim(x_buf, b_slot, 0, keepdims=False)
            y, vjp = jax.vjp(stage_fn, stage_params, x_saved)
            target = lax.dynamic_index_in_dim(
                y_micro, jnp.clip(b_idx, 0, m_count - 1), 0, keepdims=False
            )
            loss_val, dy_last = jax.value_and_grad(loss_head)(y, target)
            dy = jnp.where(is_last, dy_last, recv_b)
            dp, dx = vjp(dy)
            grads = jax.tree.map(
                lambda g, d_: g + jnp.where(b_active, d_, jnp.zeros_like(d_)),
                grads, dp,
            )
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(is_last, b_active), loss_val, 0.0
            )
            send_b = jnp.where(b_active, dx, jnp.zeros_like(dx))
            return x_buf, grads, loss_acc, jnp.zeros((mb, d), probe.dtype), send_b

        # F and B parities are disjoint, so exactly one branch runs per tick per
        # stage; the branches hold no collectives, so divergent per-device
        # control flow is safe (the ppermutes below are unconditional).
        x_buf, grads, loss_acc, send_f, send_b = lax.cond(
            rel % 2 == 0, f_branch, b_branch,
            (recv_f, recv_b, x_buf, grads, loss_acc),
        )
        recv_f = lax.ppermute(send_f, axis, fwd_perm)  # mlsl-lint: disable=A201 -- boundary primitive
        recv_b = lax.ppermute(send_b, axis, bwd_perm)  # mlsl-lint: disable=A201 -- boundary primitive
        return recv_f, recv_b, x_buf, grads, loss_acc

    _, _, _, grads, loss_acc = lax.fori_loop(
        0, ticks, tick, (recv_f, recv_b, x_buf, grads0, jnp.float32(0.0))
    )
    return algos.inline_allreduce(loss_acc, axis), grads


def interleaved_schedule(n_stages: int, v_chunks: int, m_count: int) -> dict:
    """Static interleaved-1F1B schedule (Megatron-style virtual stages), host-side.

    The model is split into v*S stages; device d holds chunks c=0..v-1 as global
    stages k = c*S + d, so every stage->stage+1 boundary is still a +1 ring hop
    (device S-1 wraps to device 0, chunk c+1) and the backward boundary a -1 hop.
    The schedule is built by greedy list-scheduling of the dependency DAG, one op
    per device per tick: backward ops take priority (the 1F1B memory discipline),
    remaining forward ops run deepest-chunk-first (depth-first fill, which is what
    shrinks the bubble by ~v: the last device starts after S-1 hops and then stays
    busy across its v chunks, instead of waiting for a v*S-deep fill).

    Returns numpy tables (ticks, S) describing each device's op per tick plus the
    receiver-side staging-store tables, and slot counts sized so no staged buffer
    is overwritten before consumption (verified by construction below).
    """
    S, V, M = int(n_stages), int(v_chunks), int(m_count)
    assert S >= 1 and V >= 1 and M >= 1
    K_tot = V * S

    # --- greedy list scheduling -> t_f[k, i], t_b[k, i] ---------------------
    t_f = np.full((K_tot, M), -1, dtype=np.int64)
    t_b = np.full((K_tot, M), -1, dtype=np.int64)
    done_f = np.zeros((K_tot, M), dtype=bool)
    done_b = np.zeros((K_tot, M), dtype=bool)
    # Each device follows a FIXED op sequence (Megatron's discipline): W warmup
    # forwards, then strict F/B alternation (1F1B steady state), then cooldown
    # backwards. Forwards walk microbatch groups of S with chunks ascending;
    # backwards walk the same groups with chunks descending (the deepest chunk
    # drains first). A device whose next op isn't ready idles that tick — the
    # schedule stays synchronous and the in-flight memory is bounded by W+1.
    def _group_order(desc):
        order = []
        for g in range(0, M, S):
            span = range(g, min(g + S, M))
            chunks = range(V - 1, -1, -1) if desc else range(V)
            for c in chunks:
                order.extend((c, i) for i in span)
        return order

    n_ops = V * M
    seqs = []
    for d in range(S):
        if V == 1:
            warm = min(S - d - 1, n_ops)
        else:
            warm = min((S - d - 1) * 2 + (V - 1) * S, n_ops)
        f_seq = _group_order(desc=False)
        b_seq = _group_order(desc=True)
        kinds = ["F"] * warm
        for _ in range(n_ops - warm):
            kinds += ["F", "B"]
        kinds += ["B"] * warm
        fi = bi = 0
        seq = []
        for kind in kinds:
            if kind == "F":
                c, i = f_seq[fi]
                fi += 1
            else:
                c, i = b_seq[bi]
                bi += 1
            seq.append((kind, c * S + d, i))
        seqs.append(seq)

    def _f_ready(k, i, t):
        # the upstream forward must have completed on an EARLIER tick (the
        # boundary rides a one-tick ppermute)
        return not done_f[k, i] and (
            k == 0 or (done_f[k - 1, i] and t_f[k - 1, i] < t)
        )

    def _b_ready(k, i, t):
        return (
            not done_b[k, i]
            and done_f[k, i]
            and t_f[k, i] < t
            and (k == K_tot - 1 or (done_b[k + 1, i] and t_b[k + 1, i] < t))
        )

    def _do(kind, k, i, t):
        if kind == "F":
            t_f[k, i] = t
            done_f[k, i] = True
        else:
            t_b[k, i] = t
            done_b[k, i] = True

    pos = [0] * S
    remaining = 2 * K_tot * M
    t = 0
    no_progress = 0
    while remaining > 0:
        progressed = False
        for d in range(S):
            if pos[d] >= len(seqs[d]):
                continue
            kind, k, i = seqs[d][pos[d]]
            ready = _f_ready(k, i, t) if kind == "F" else _b_ready(k, i, t)
            if ready:
                _do(kind, k, i, t)
                pos[d] += 1
                remaining -= 1
                progressed = True
        t += 1
        # Relief valve: arrivals matter for exactly one tick, so two consecutive
        # all-idle sweeps mean the fixed sequences deadlocked (possible only for
        # irregular M vs S); fall back to scheduling ANY ready op once, which
        # always exists for an unfinished DAG and restores progress.
        no_progress = 0 if progressed else no_progress + 1
        if no_progress >= 2:
            for d in range(S):
                pick = None
                for kk in range(d, K_tot, S):
                    for i in range(M):
                        if _f_ready(kk, i, t):
                            pick = ("F", kk, i)
                            break
                        if _b_ready(kk, i, t):
                            pick = ("B", kk, i)
                            break
                    if pick:
                        break
                if pick:
                    _do(*pick, t)
                    remaining -= 1
                    seqs[d].remove(pick)
            t += 1
            no_progress = 0
    ticks = t

    # --- minimal slot counts so slot reuse never clobbers live data ---------
    def _min_slots(write_t, read_t):
        # writing slot i%K at write_t[i+K] must not precede the read at read_t[i]
        for K in range(1, M + 1):
            ok = True
            for k in range(write_t.shape[0]):
                for i in range(M - K):
                    if write_t[k, i + K] < read_t[k, i]:
                        ok = False
                        break
                if not ok:
                    break
            if ok:
                return K
        return M

    # fwd staging at stage k (k>0): stored at end of t_f[k-1, i], read at t_f[k, i]
    k_f = _min_slots(t_f[:-1], t_f[1:]) if K_tot > 1 else 1
    # bwd staging at stage k (k<last): stored at end of t_b[k+1, i], read at t_b[k, i]
    k_b = _min_slots(t_b[1:], t_b[:-1]) if K_tot > 1 else 1
    # saved inputs at stage k: written during t_f[k, i], read at t_b[k, i]
    k_s = _min_slots(t_f, t_b)

    # --- per-tick tables ----------------------------------------------------
    kind_t = np.zeros((ticks, S), np.int32)          # 0 idle, 1 F, 2 B
    chunk_t = np.zeros((ticks, S), np.int32)
    micro_t = np.zeros((ticks, S), np.int32)
    first_t = np.zeros((ticks, S), np.int32)         # F reads x_micro (k == 0)
    last_t = np.zeros((ticks, S), np.int32)          # B computes loss grad (k == last)
    fstore_valid = np.zeros((ticks, S), np.int32)
    fstore_idx = np.zeros((ticks, S), np.int32)      # chunk*k_f + slot at receiver
    bstore_valid = np.zeros((ticks, S), np.int32)
    bstore_idx = np.zeros((ticks, S), np.int32)
    for k in range(K_tot):
        d, c = k % S, k // S
        for i in range(M):
            tf = t_f[k, i]
            kind_t[tf, d], chunk_t[tf, d], micro_t[tf, d] = 1, c, i
            first_t[tf, d] = int(k == 0)
            if k + 1 < K_tot:
                d2, c2 = (k + 1) % S, (k + 1) // S
                fstore_valid[tf, d2] = 1
                fstore_idx[tf, d2] = c2 * k_f + i % k_f
            tb = t_b[k, i]
            kind_t[tb, d], chunk_t[tb, d], micro_t[tb, d] = 2, c, i
            last_t[tb, d] = int(k == K_tot - 1)
            if k > 0:
                d2, c2 = (k - 1) % S, (k - 1) // S
                bstore_valid[tb, d2] = 1
                bstore_idx[tb, d2] = c2 * k_b + i % k_b
    busy = 2 * K_tot * M
    return {
        "tables": {
            "kind": kind_t, "chunk": chunk_t, "micro": micro_t,
            "first": first_t, "last": last_t,
            "fstore_valid": fstore_valid, "fstore_idx": fstore_idx,
            "bstore_valid": bstore_valid, "bstore_idx": bstore_idx,
        },
        "k_f": k_f, "k_b": k_b, "k_s": k_s,
        "ticks": ticks,
        "utilization": busy / (ticks * S),
        "bubble_fraction": 1.0 - busy / (ticks * S),
        "t_f": t_f, "t_b": t_b,
    }


def interleaved_1f1b_step(
    stage_fn: Callable,
    loss_head: Callable,
    chunk_params,
    x_micro: jax.Array,
    y_micro: jax.Array,
    axis: str,
    n_stages: int,
    v_chunks: int,
):
    """Interleaved (virtual-stage) 1F1B: (loss, per-chunk grads) for this device.

    SPMD body (call inside shard_map over ``axis`` of size n_stages).
    chunk_params: THIS device's v chunks stacked on axis 0 — chunk c is global
    stage c*S + d (reshape a (v*S, ...)-stacked model to (v, S, ...) and shard
    axis 1 over ``axis``). The whole schedule is precomputed host-side
    (interleaved_schedule) and baked into constant tables; the traced loop only
    gathers its per-tick op and runs it, so XLA sees a fixed-shape fori_loop with
    one stage eval (F) or one explicit-remat vjp (B) per tick — the same
    compute-per-tick as one_f1b_step, with the bubble cut ~v-fold.

    Reference anchor: the SendRecvList p2p primitive (src/comm.hpp:212-248);
    schedule shape follows Megatron-LM's interleaved 1F1B (PAPERS.md), rebuilt
    as a static table + ring ppermute pair for the TPU's fixed SPMD program.
    """
    m_count, mb, d_wire = x_micro.shape
    S, V = int(n_stages), int(v_chunks)
    sched = interleaved_schedule(S, V, m_count)
    tb = {k: jnp.asarray(v) for k, v in sched["tables"].items()}
    k_f, k_b, k_s = sched["k_f"], sched["k_b"], sched["k_s"]
    me = lax.axis_index(axis)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]

    probe = jax.eval_shape(
        stage_fn,
        jax.tree.map(lambda p: jax.ShapeDtypeStruct(p.shape[1:], p.dtype), chunk_params),
        x_micro[0],
    )
    assert probe.shape[-1] == d_wire, (
        f"pipeline boundary width mismatch: {d_wire} -> {probe.shape[-1]}"
    )
    dt = probe.dtype

    fwd_in = _pvary(jnp.zeros((V * k_f, mb, d_wire), dt), axis)
    bwd_in = _pvary(jnp.zeros((V * k_b, mb, d_wire), dt), axis)
    x_saved = _pvary(jnp.zeros((V * k_s, mb, d_wire), dt), axis)
    grads0 = jax.tree.map(lambda p: jnp.zeros_like(p), chunk_params)
    zero_wire = jnp.zeros((mb, d_wire), dt)

    def tick(t, state):
        fwd_in, bwd_in, x_saved, grads, loss_acc = state
        kind = tb["kind"][t, me]
        c = tb["chunk"][t, me]
        i = tb["micro"][t, me]
        params_c = jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, c, 0, keepdims=False), chunk_params
        )
        save_idx = c * k_s + i % k_s

        def f_branch(args):
            fwd_in, bwd_in, x_saved, grads, loss_acc = args
            active = kind == 1
            inp = jnp.where(
                tb["first"][t, me] == 1,
                lax.dynamic_index_in_dim(x_micro, i, 0, keepdims=False),
                lax.dynamic_index_in_dim(fwd_in, c * k_f + i % k_f, 0, keepdims=False),
            )
            y = stage_fn(params_c, inp)
            x_saved = jnp.where(
                active,
                lax.dynamic_update_index_in_dim(x_saved, inp, save_idx, axis=0),
                x_saved,
            )
            send_f = jnp.where(active, y, jnp.zeros_like(y))
            return x_saved, grads, loss_acc, send_f, zero_wire

        def b_branch(args):
            fwd_in, bwd_in, x_saved, grads, loss_acc = args
            active = kind == 2
            x_in = lax.dynamic_index_in_dim(x_saved, save_idx, 0, keepdims=False)
            y, vjp = jax.vjp(stage_fn, params_c, x_in)
            target = lax.dynamic_index_in_dim(y_micro, i, 0, keepdims=False)
            loss_val, dy_last = jax.value_and_grad(loss_head)(y, target)
            dy = jnp.where(
                tb["last"][t, me] == 1,
                dy_last,
                lax.dynamic_index_in_dim(bwd_in, c * k_b + i % k_b, 0, keepdims=False),
            )
            dp, dx = vjp(dy)
            grads = jax.tree.map(
                lambda G, dd: lax.dynamic_update_index_in_dim(
                    G,
                    lax.dynamic_index_in_dim(G, c, 0, keepdims=False)
                    + jnp.where(active, dd, jnp.zeros_like(dd)),
                    c,
                    axis=0,
                ),
                grads,
                dp,
            )
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(tb["last"][t, me] == 1, active),
                loss_val.astype(jnp.float32),
                0.0,
            )
            send_b = jnp.where(active, dx, jnp.zeros_like(dx))
            return x_saved, grads, loss_acc, zero_wire, send_b

        x_saved, grads, loss_acc, send_f, send_b = lax.cond(
            kind == 2, b_branch, f_branch,
            (fwd_in, bwd_in, x_saved, grads, loss_acc),
        )
        recv_f = lax.ppermute(send_f, axis, fwd_perm)  # mlsl-lint: disable=A201 -- boundary primitive
        recv_b = lax.ppermute(send_b, axis, bwd_perm)  # mlsl-lint: disable=A201 -- boundary primitive
        fwd_in = jnp.where(
            tb["fstore_valid"][t, me] == 1,
            lax.dynamic_update_index_in_dim(
                fwd_in, recv_f, tb["fstore_idx"][t, me], axis=0
            ),
            fwd_in,
        )
        bwd_in = jnp.where(
            tb["bstore_valid"][t, me] == 1,
            lax.dynamic_update_index_in_dim(
                bwd_in, recv_b, tb["bstore_idx"][t, me], axis=0
            ),
            bwd_in,
        )
        return fwd_in, bwd_in, x_saved, grads, loss_acc

    _, _, _, grads, loss_acc = lax.fori_loop(
        0, sched["ticks"], tick,
        (fwd_in, bwd_in, x_saved, grads0, jnp.float32(0.0)),
    )
    return algos.inline_allreduce(loss_acc, axis), grads


def pipeline_loss(
    stage_fn: Callable,
    loss_head: Callable,
    stage_params,
    x_micro: jax.Array,
    y_micro: jax.Array,
    axis: str,
    n_stages: int,
    remat: bool = False,
):
    """Pipelined forward + loss on the last stage, psum'd so every stage holds the
    scalar (ready for jax.grad: the backward replays the schedule in reverse)."""
    outs = gpipe_forward(stage_fn, stage_params, x_micro, axis, n_stages, remat=remat)
    me = lax.axis_index(axis)
    per_micro = jax.vmap(loss_head)(outs, y_micro)          # (M,)
    local = jnp.where(me == n_stages - 1, jnp.sum(per_micro), 0.0)
    return algos.inline_allreduce(local, axis)


def reduce_microbatch_grads(
    group,
    counts,
    *,
    config=None,
    compression=None,
    algo=None,
    stages=None,
    block=None,
):
    """Data-parallel reduction of pipeline stage gradients THROUGH the
    collective engine: -> (fn, plan) from comm/overlap.build_multi_reduce.

    After a 1F1B step each stage holds its microbatch-accumulated stage
    grads; replicating the pipeline across a data axis leaves one reduction
    to run — this builds it as the engine's staged multi-tensor program, so
    the selection table applies per tensor (on a two-tier world that is the
    hierarchical 'hier' lowering, with the compressed DCN hop when
    ``compression=QUANTIZATION``), the emission is staged newest-first, and
    error-feedback residuals ride the returned-state convention. ``fn``
    takes the flattened per-stage grad tensors as standard distributed
    buffers (reversed start order = backward emission order), exactly
    build_multi_reduce's contract."""
    from mlsl_tpu.comm import overlap
    from mlsl_tpu.types import CompressionType

    kw = {}
    if stages is not None:
        kw["stages"] = stages
    if block is not None:
        kw["block"] = block
    return overlap.build_multi_reduce(
        group, list(counts),
        compression=(compression if compression is not None
                     else CompressionType.NONE),
        algo=algo, config=config, **kw,
    )
