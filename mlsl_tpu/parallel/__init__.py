"""Parallelism schedules beyond the grid: sequence/context parallelism primitives.

The reference's Distribution grid + AlltoAll redistribution machinery
(src/mlsl_impl.cpp:203-226) generalizes to sequence scaling in exactly two schedules
(SURVEY.md §5.7): all-to-all head/sequence re-sharding (Ulysses) and neighbor-exchange
rings (ring attention — the implemented form of the reference's declared-but-unbuilt
SendRecvList primitive, src/comm.hpp:212-248).
"""

from mlsl_tpu.parallel.sequence import ring_attention, ulysses_attention

__all__ = ["ring_attention", "ulysses_attention"]
