"""Sequence-parallel attention: ppermute ring and all-to-all (Ulysses) schedules.

Both functions are SPMD bodies — call them inside ``shard_map`` over a mesh that has
the given sequence axis. Inputs are the device-local shards:
    q, k, v: (batch, heads_local, seq_local, head_dim)

ring_attention: k/v blocks rotate around the ring via lax.ppermute while each device
keeps its query block, accumulating with the numerically-stable online-softmax
(flash-attention) update. Wire cost per step: one k+v block over the neighbor link —
the TPU-native realization of the reference's unimplemented SendRecvList
neighbor-exchange CommOp (src/comm.hpp:212-248). Supports causal masking via global
position arithmetic.

ulysses_attention: two all-to-alls switch sharding seq->heads and back (the reference's
redistribution-AlltoAll pattern, src/mlsl_impl.cpp:203-226, applied to the sequence
axis): attention itself runs with the full sequence but a head subset per device.
Requires heads_local divisible by the axis size.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from mlsl_tpu.log import mlsl_assert

_NEG = -1e30


def _pvary(x, axis):
    """Mark x as device-varying over axis (no-op on JAX versions without VMA)."""
    try:
        return lax.pcast(x, (axis,), to="varying")
    except (AttributeError, TypeError):  # pragma: no cover - older jax
        try:
            return lax.pvary(x, (axis,))
        except AttributeError:
            return x


def _attn_block_update(q, k_blk, v_blk, acc, m, l, q_pos, k_pos, causal, scale):
    """One online-softmax accumulation step.

    q: (B, H, Sq, D); k_blk/v_blk: (B, H, Sk, D); acc: (B, H, Sq, D);
    m, l: (B, H, Sq); q_pos: (Sq,), k_pos: (Sk,) global positions.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
    if causal:
        valid = (k_pos[None, :] <= q_pos[:, None])  # (Sq, Sk)
        s = jnp.where(valid[None, None], s, _NEG)
    s_max = jnp.max(s, axis=-1)                      # (B, H, Sq)
    m_new = jnp.maximum(m, s_max)
    # exp of masked entries: s = _NEG << m_new -> exp underflows to 0 exactly
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(s <= _NEG / 2, 0.0, p)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
    return acc_new, m_new, l_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str,
    axis_size: int,
    causal: bool = False,
    use_flash: Optional[bool] = None,
) -> jax.Array:
    """Exact attention over the full (sharded) sequence via a k/v ring.

    use_flash: None = auto (fused Pallas block kernel on TPU when the tiling
    admits); True/False forces the choice (True uses interpret mode off-TPU)."""
    if axis_size == 1:
        return _dense_attention(q, k, v, causal, 0)
    b, h, sl, d = q.shape
    if use_flash is None:
        use_flash = _use_flash(sl, sl, d)
    if use_flash:
        from mlsl_tpu.ops.attention_kernels import supports

        mlsl_assert(
            supports(sl, sl, d),
            "flash ring requires local seq %% 128 == 0 and head_dim %% 8 == 0 "
            "(got seq=%d, head_dim=%d); use use_flash=False",
            sl, d,
        )
        return _ring_flash(q, k, v, axis, axis_size, causal)
    scale = 1.0 / jnp.sqrt(d).astype(q.dtype)
    me = lax.axis_index(axis)
    q_pos = me * sl + jnp.arange(sl)

    init = (
        _pvary(jnp.zeros((b, h, sl, d), jnp.float32), axis),
        _pvary(jnp.full((b, h, sl), _NEG, jnp.float32), axis),
        _pvary(jnp.zeros((b, h, sl), jnp.float32), axis),
    )

    def step_fn(carry, k_cur, v_cur, src):
        acc, m, l = carry
        k_pos = src * sl + jnp.arange(sl)
        return _attn_block_update(
            q.astype(jnp.float32), k_cur.astype(jnp.float32),
            v_cur.astype(jnp.float32), acc, m, l, q_pos, k_pos, causal, scale
        )

    acc, m, l = _ring_schedule(k, v, axis, axis_size, init, step_fn)
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def _ring_schedule(k, v, axis: str, axis_size: int, init_carry, step_fn):
    """The shared k/v rotation loop: at hop t every device folds the block
    originally owned by rank (me - t) into its carry, then passes it right."""
    me = lax.axis_index(axis)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(t, state):
        carry, k_cur, v_cur = state
        src = (me - t) % axis_size          # original owner of the current block
        carry = step_fn(carry, k_cur, v_cur, src)
        # mlsl-lint: disable=A201 -- the ring-attention KV rotation is the
        # algorithm itself (per-hop ppermute fused with the attention math),
        # not a request collective the engine could serve
        return carry, lax.ppermute(k_cur, axis, perm), lax.ppermute(v_cur, axis, perm)

    carry, _, _ = lax.fori_loop(0, axis_size, step, (init_carry, k, v))
    return carry


def _ring_flash(q, k, v, axis: str, axis_size: int, causal: bool) -> jax.Array:
    """Ring attention with the fused Pallas block kernel as the inner step: each
    hop folds the visiting k/v block into the carried (acc, m, l) state without
    materializing scores (mlsl_tpu.ops.attention_kernels.flash_block_update)."""
    from mlsl_tpu.ops.attention_kernels import NEG, flash_block_update

    b, h, sl, d = q.shape
    bh = b * h
    interpret = jax.default_backend() != "tpu"
    qf = q.reshape(bh, sl, d)
    # The scalar-prefetch offsets only matter for the causal mask / DMA-skip
    # maps. Non-causal, feed constants: an axis_index-derived operand that the
    # kernel never reads still lowers to a PartitionId instruction, which
    # XLA:CPU's SPMD partitioner rejects (the interpret-mode CI path).
    if causal:
        me = lax.axis_index(axis)
        q_off = jnp.full((1,), me * sl, jnp.int32)
    else:
        q_off = jnp.zeros((1,), jnp.int32)

    init = (
        _pvary(jnp.zeros((bh, sl, d), jnp.float32), axis),
        _pvary(jnp.full((bh, sl, 128), NEG, jnp.float32), axis),
        _pvary(jnp.zeros((bh, sl, 128), jnp.float32), axis),
    )

    def step_fn(carry, k_cur, v_cur, src):
        acc, m, l = carry
        k_off = (jnp.full((1,), src * sl, jnp.int32) if causal
                 else jnp.zeros((1,), jnp.int32))
        return flash_block_update(
            qf, k_cur, v_cur, acc, m, l, q_off, k_off, causal, interpret
        )

    acc, m, l = _ring_schedule(
        k.reshape(bh, sl, d), v.reshape(bh, sl, d), axis, axis_size, init, step_fn
    )
    out = acc / jnp.maximum(l[:, :, :1], 1e-30)
    return out.reshape(b, h, sl, d).astype(q.dtype)


def zigzag_perm(seq_len: int, axis_size: int):
    """Permutation putting a sequence into ZIGZAG layout: device r's contiguous
    shard holds global chunks r and 2G-1-r (chunk = seq_len / (2G)).

    Returns ``perm`` with ``x_zigzag = x[..., perm, :]``; invert with
    ``x[..., inv, :] = x_zigzag`` where ``inv = zigzag_perm_inverse(...)``.
    """
    import numpy as np

    g = axis_size
    mlsl_assert(
        seq_len % (2 * g) == 0,
        "zigzag needs seq_len %% (2 * axis_size) == 0 (got %d, %d)",
        seq_len, g,
    )
    c = seq_len // (2 * g)
    chunks = np.arange(seq_len).reshape(2 * g, c)
    order = [x for r in range(g) for x in (r, 2 * g - 1 - r)]
    return chunks[order].reshape(-1)


def zigzag_perm_inverse(seq_len: int, axis_size: int):
    import numpy as np

    perm = zigzag_perm(seq_len, axis_size)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(seq_len)
    return inv


def zigzag_ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str,
    axis_size: int,
    use_flash: Optional[bool] = None,
) -> jax.Array:
    """Load-balanced CAUSAL ring attention over zigzag-sharded sequences.

    With contiguous block sharding, causal ring attention computes the full
    (2c x 2c) score block every hop and masks half of it away on average —
    ~2x wasted MXU work at large ring sizes, and SPMD lockstep means nobody
    can skip ahead. Zigzag layout (device r holds global chunks r and
    2G-1-r; see zigzag_perm) makes every hop exactly TWO unmasked (c x c)
    block updates on every device:

      - visiting kv from an earlier rank (src < me): both my chunks see the
        visitor's first chunk -> (q0, k0), (q1, k0);
      - visiting kv from a later rank (src > me): my second chunk sees both
        visitor chunks -> (q1, k0), (q1, k1);

    and chunk-level visibility is all-or-nothing, so the off-diagonal
    updates need NO mask at all. Only the self-hop touches masked diagonals.
    Total block-FLOPs: ~2Gc^2 vs the contiguous schedule's 4Gc^2 — the
    schedule used by production context-parallel trainers, absent from the
    reference (its sequence dimension does not exist; SURVEY §5.7).

    Inputs are zigzag-sharded device-local (B, H, 2c, D) shards; call inside
    shard_map like ring_attention. Non-causal attention gains nothing from
    zigzag — use ring_attention for it.

    use_flash: None = auto (the fused Pallas block kernel on TPU when the
    chunk tiling admits — no (c x c) score materialization); True forces it
    (interpret mode off-TPU), False forces the einsum fallback.
    """
    if axis_size == 1:
        return _dense_attention(q, k, v, True, 0)
    b, h, sl, d = q.shape
    mlsl_assert(sl % 2 == 0, "zigzag shard length must be even (got %d)", sl)
    c = sl // 2
    g = axis_size
    bh = b * h
    me = lax.axis_index(axis)
    if use_flash is None:
        use_flash = _use_flash(c, c, d)

    # Both modes share the schedule below on (bh, 2, c, ...) chunked carries;
    # they differ only in the per-chunk update and the m/l carry layout.
    if use_flash:
        from mlsl_tpu.ops.attention_kernels import (
            NEG, flash_block_update, supports,
        )

        mlsl_assert(
            supports(c, c, d),
            "flash zigzag requires chunk length (local seq / 2) %% 128 == 0 "
            "and head_dim %% 8 == 0 (got chunk=%d, head_dim=%d); use "
            "use_flash=False",
            c, d,
        )
        interpret = jax.default_backend() != "tpu"
        zoff = jnp.zeros((1,), jnp.int32)

        def _update(causal):
            # causal=False: chunk fully visible (no mask, offsets irrelevant);
            # causal=True: equal offsets = within-chunk lower triangle
            def u(qc, kc, vc, ac, mc, lc):
                return flash_block_update(
                    qc, kc, vc, ac, mc, lc, zoff, zoff, causal, interpret
                )
            return u

        full_update, diag_update = _update(False), _update(True)
        as_chunks = lambda x: x.reshape(bh, 2, c, d)
        qz = as_chunks(q)
        m = _pvary(jnp.full((bh, 2, c, 128), NEG, jnp.float32), axis)
        l = _pvary(jnp.zeros((bh, 2, c, 128), jnp.float32), axis)
        denom = lambda l: jnp.maximum(l[..., :1], 1e-30)
    else:
        scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)

        def _update(causal):
            """(c x c) online-softmax update; causal=True applies the
            within-chunk lower triangle (self-hop diagonals only)."""
            def u(qc, kc, vc, ac, mc, lc):
                s = jnp.einsum("bqd,bkd->bqk", qc, kc) * scale
                if causal:
                    tri = jnp.arange(c)[None, :] <= jnp.arange(c)[:, None]
                    s = jnp.where(tri[None], s, _NEG)
                s_max = jnp.max(s, axis=-1)
                m_new = jnp.maximum(mc, s_max)
                p = jnp.exp(s - m_new[..., None])
                if causal:
                    p = jnp.where(s <= _NEG / 2, 0.0, p)
                corr = jnp.exp(mc - m_new)
                l_new = lc * corr + jnp.sum(p, axis=-1)
                a_new = ac * corr[..., None] + jnp.einsum("bqk,bkd->bqd", p, vc)
                return a_new, m_new, l_new
            return u

        full_update, diag_update = _update(False), _update(True)
        as_chunks = lambda x: x.astype(jnp.float32).reshape(bh, 2, c, d)
        qz = as_chunks(q)
        m = _pvary(jnp.full((bh, 2, c), _NEG, jnp.float32), axis)
        l = _pvary(jnp.zeros((bh, 2, c), jnp.float32), axis)
        denom = lambda l: jnp.maximum(l[..., None], 1e-30)

    acc = _pvary(jnp.zeros((bh, 2, c, d), jnp.float32), axis)

    # self hop: q0*k0 (diag), q1*k0 (full: chunk 2G-1-me is after chunk me),
    # q1*k1 (diag)
    kz, vz = as_chunks(k), as_chunks(v)
    a0, m0, l0 = diag_update(
        qz[:, 0], kz[:, 0], vz[:, 0], acc[:, 0], m[:, 0], l[:, 0]
    )
    a1, m1, l1 = full_update(
        qz[:, 1], kz[:, 0], vz[:, 0], acc[:, 1], m[:, 1], l[:, 1]
    )
    a1, m1, l1 = diag_update(qz[:, 1], kz[:, 1], vz[:, 1], a1, m1, l1)
    acc = jnp.stack([a0, a1], axis=1)
    m = jnp.stack([m0, m1], axis=1)
    l = jnp.stack([l0, l1], axis=1)

    perm = [(i, (i + 1) % g) for i in range(g)]

    def hop(t, state):
        (acc, m, l), k_cur, v_cur = state
        src = (me - t) % g          # original owner of the visiting kv
        early = src < me            # visitor's chunks precede mine
        qsel = (jnp.where(early, 0, 1), jnp.int32(1))
        ksel = (jnp.int32(0), jnp.where(early, 0, 1))
        for u in range(2):
            qi, ki = qsel[u], ksel[u]
            qc = lax.dynamic_index_in_dim(qz, qi, axis=1, keepdims=False)
            kc = lax.dynamic_index_in_dim(k_cur, ki, axis=1, keepdims=False)
            vc = lax.dynamic_index_in_dim(v_cur, ki, axis=1, keepdims=False)
            ac = lax.dynamic_index_in_dim(acc, qi, axis=1, keepdims=False)
            mc = lax.dynamic_index_in_dim(m, qi, axis=1, keepdims=False)
            lc = lax.dynamic_index_in_dim(l, qi, axis=1, keepdims=False)
            ac, mc, lc = full_update(qc, kc, vc, ac, mc, lc)
            acc = lax.dynamic_update_index_in_dim(acc, ac, qi, axis=1)
            m = lax.dynamic_update_index_in_dim(m, mc, qi, axis=1)
            l = lax.dynamic_update_index_in_dim(l, lc, qi, axis=1)
        return (
            (acc, m, l),
            lax.ppermute(k_cur, axis, perm),  # mlsl-lint: disable=A201
            lax.ppermute(v_cur, axis, perm),  # mlsl-lint: disable=A201
        )

    (acc, m, l), _, _ = lax.fori_loop(
        1, g, hop,
        # mlsl-lint: disable=A201 -- zigzag ring rotation, as above
        ((acc, m, l), lax.ppermute(kz, axis, perm), lax.ppermute(vz, axis, perm)),
    )
    out = acc / denom(l)
    return out.reshape(b, h, sl, d).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str,
    axis_size: int,
    causal: bool = False,
) -> jax.Array:
    """Exact attention by re-sharding seq->heads with all-to-all, attending, and
    re-sharding back."""
    b, h, sl, d = q.shape
    if axis_size == 1:
        return _dense_attention(q, k, v, causal, 0)
    assert h % axis_size == 0, (
        f"heads_local {h} must be divisible by seq axis size {axis_size}"
    )

    def to_heads(x):  # (B, H, Sl, D) -> (B, H/G, S, D)
        # mlsl-lint: disable=A201 -- head/sequence re-sharding transposes
        # inside the attention body (DeepSpeed-Ulysses layout), in-graph
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    def to_seq(x):    # (B, H/G, S, D) -> (B, H, Sl, D)
        # mlsl-lint: disable=A201 -- as above
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = _dense_attention(qh, kh, vh, causal, 0)
    return to_seq(out)


def _dense_attention(q, k, v, causal: bool, pos_offset: int) -> jax.Array:
    b, h, s, d = q.shape
    if _use_flash(s, s, d):
        from mlsl_tpu.ops.attention_kernels import flash_attention

        off = jnp.full((1,), pos_offset, jnp.int32)
        out = flash_attention(
            q.reshape(b * h, s, d), k.reshape(b * h, s, d), v.reshape(b * h, s, d),
            off, off, causal, False,
        )
        return out.reshape(b, h, s, d)
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    s_mat = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        pos = jnp.arange(s) + pos_offset
        s_mat = jnp.where((pos[None, :] <= pos[:, None])[None, None], s_mat, _NEG)
    p = jax.nn.softmax(s_mat, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _use_flash(sq: int, sk: int, d: int) -> bool:
    """Route through the fused Pallas kernel on TPU when the tiling admits it
    (1.3x over the XLA einsum at S=2048 on v5e, and O(S*D) HBM instead of O(S^2))."""
    try:
        if jax.default_backend() != "tpu":
            return False
        from mlsl_tpu.ops.attention_kernels import supports

        return supports(sq, sk, d)
    except Exception:  # pragma: no cover
        return False
