"""Sequence-parallel attention: ppermute ring and all-to-all (Ulysses) schedules.

Both functions are SPMD bodies — call them inside ``shard_map`` over a mesh that has
the given sequence axis. Inputs are the device-local shards:
    q, k, v: (batch, heads_local, seq_local, head_dim)

ring_attention: k/v blocks rotate around the ring via lax.ppermute while each device
keeps its query block, accumulating with the numerically-stable online-softmax
(flash-attention) update. Wire cost per step: one k+v block over the neighbor link —
the TPU-native realization of the reference's unimplemented SendRecvList
neighbor-exchange CommOp (src/comm.hpp:212-248). Supports causal masking via global
position arithmetic.

ulysses_attention: two all-to-alls switch sharding seq->heads and back (the reference's
redistribution-AlltoAll pattern, src/mlsl_impl.cpp:203-226, applied to the sequence
axis): attention itself runs with the full sequence but a head subset per device.
Requires heads_local divisible by the axis size.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30


def _pvary(x, axis):
    """Mark x as device-varying over axis (no-op on JAX versions without VMA)."""
    try:
        return lax.pcast(x, (axis,), to="varying")
    except (AttributeError, TypeError):  # pragma: no cover - older jax
        try:
            return lax.pvary(x, (axis,))
        except AttributeError:
            return x


def _attn_block_update(q, k_blk, v_blk, acc, m, l, q_pos, k_pos, causal, scale):
    """One online-softmax accumulation step.

    q: (B, H, Sq, D); k_blk/v_blk: (B, H, Sk, D); acc: (B, H, Sq, D);
    m, l: (B, H, Sq); q_pos: (Sq,), k_pos: (Sk,) global positions.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
    if causal:
        valid = (k_pos[None, :] <= q_pos[:, None])  # (Sq, Sk)
        s = jnp.where(valid[None, None], s, _NEG)
    s_max = jnp.max(s, axis=-1)                      # (B, H, Sq)
    m_new = jnp.maximum(m, s_max)
    # exp of masked entries: s = _NEG << m_new -> exp underflows to 0 exactly
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(s <= _NEG / 2, 0.0, p)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
    return acc_new, m_new, l_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str,
    axis_size: int,
    causal: bool = False,
) -> jax.Array:
    """Exact attention over the full (sharded) sequence via a k/v ring."""
    if axis_size == 1:
        return _dense_attention(q, k, v, causal, 0)
    b, h, sl, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(q.dtype)
    me = lax.axis_index(axis)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    q_pos = me * sl + jnp.arange(sl)

    acc = jnp.zeros((b, h, sl, d), jnp.float32)
    m = jnp.full((b, h, sl), _NEG, jnp.float32)
    l = jnp.zeros((b, h, sl), jnp.float32)
    # mark the carry as device-varying over the ring axis (shard_map VMA rules:
    # the loop body mixes in ppermute'd values, so the carry type must be varying)
    acc, m, l = (_pvary(x, axis) for x in (acc, m, l))

    def step(t, carry):
        acc, m, l, k_cur, v_cur = carry
        src = (me - t) % axis_size          # original owner of the current k/v block
        k_pos = src * sl + jnp.arange(sl)
        acc, m, l = _attn_block_update(
            q.astype(jnp.float32), k_cur.astype(jnp.float32),
            v_cur.astype(jnp.float32), acc, m, l, q_pos, k_pos, causal, scale
        )
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        return acc, m, l, k_nxt, v_nxt

    acc, m, l, _, _ = lax.fori_loop(0, axis_size, step, (acc, m, l, k, v))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str,
    axis_size: int,
    causal: bool = False,
) -> jax.Array:
    """Exact attention by re-sharding seq->heads with all-to-all, attending, and
    re-sharding back."""
    b, h, sl, d = q.shape
    if axis_size == 1:
        return _dense_attention(q, k, v, causal, 0)
    assert h % axis_size == 0, (
        f"heads_local {h} must be divisible by seq axis size {axis_size}"
    )

    def to_heads(x):  # (B, H, Sl, D) -> (B, H/G, S, D)
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    def to_seq(x):    # (B, H/G, S, D) -> (B, H, Sl, D)
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = _dense_attention(qh, kh, vh, causal, 0)
    return to_seq(out)


def _dense_attention(q, k, v, causal: bool, pos_offset: int) -> jax.Array:
    b, h, s, d = q.shape
    if _use_flash(s, s, d):
        from mlsl_tpu.ops.attention_kernels import flash_attention

        off = jnp.full((1,), pos_offset, jnp.int32)
        out = flash_attention(
            q.reshape(b * h, s, d), k.reshape(b * h, s, d), v.reshape(b * h, s, d),
            off, off, causal, False,
        )
        return out.reshape(b, h, s, d)
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    s_mat = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        pos = jnp.arange(s) + pos_offset
        s_mat = jnp.where((pos[None, :] <= pos[:, None])[None, None], s_mat, _NEG)
    p = jax.nn.softmax(s_mat, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _use_flash(sq: int, sk: int, d: int) -> bool:
    """Route through the fused Pallas kernel on TPU when the tiling admits it
    (1.3x over the XLA einsum at S=2048 on v5e, and O(S*D) HBM instead of O(S^2))."""
    try:
        if jax.default_backend() != "tpu":
            return False
        from mlsl_tpu.ops.attention_kernels import supports

        return supports(sq, sk, d)
    except Exception:  # pragma: no cover
        return False
