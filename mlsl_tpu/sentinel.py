"""Training integrity sentinel: silent-corruption detection and response.

Every defense below this layer fires on a *loud* fault — the watchdog needs a
hang, the supervisor ladder needs an exception, checkpoint fallback needs a
checksum mismatch at read time. A silent fault (a bit-flip in a parameter or
optimizer slot, a NaN gradient from a bad batch, SPMD replicas drifting apart
after an SDC) passes through untouched, poisons the model, and gets
*checkpointed* — so even a restart replays the corruption. The reference's
PointerChecker (src/pointer_checker.{hpp,cpp}, ENABLE_CHKP_INT) is the
paper's acknowledgment that payload integrity needs runtime verification;
``checker.py`` reproduces it at the request boundary, and this module guards
the training STATE itself with three detection layers and a verified-good
response:

1. **Step quality gate** (:meth:`Sentinel.gate`): cheap fused on-device
   screens between the gradient program and the gradient comm — nonfinite
   count over the local grads, global grad-norm vs an EMA spike threshold,
   loss z-score (a corrupt PARAM poisons the loss/grads it produces the
   step it is read, so these screens cover it without a per-step scan of
   the replicated state) — with a configurable response ladder
   ``MLSL_SENTINEL_GATE``:
   ``warn`` logs and continues, ``skip_step`` discards the poisoned update
   (the step behaves exactly as if it never ran: no comm started, so
   error-feedback residuals and data-order bookkeeping stay consistent —
   pinned by lockstep-twin parity tests), ``rollback`` raises
   :class:`MLSLIntegrityError`.
2. **Cross-replica consistency audit** (:meth:`Sentinel.audit_now`): every
   ``MLSL_SENTINEL_EVERY`` steps, a blockwise int32 fingerprint of params +
   optimizer state is reduced via pmin/pmax equality ON DEVICE (no host
   gather) — replicas that drifted apart after an SDC disagree in some
   block, and ``pmin != pmax`` exposes it. Sharded (ZeRO-1) optimizer state
   contributes an exact integer psum to the fingerprint instead (each rank's
   shard is unique — divergence does not apply, but identity does).
3. **Verified-good checkpoints + rollback** (checkpoint.py + resilience.py):
   ``CheckpointManager.save`` records the passing audit fingerprint in the
   step manifest, ``restore_trainer`` prefers the newest *verified* step,
   and ``FaultTolerantLoop`` answers :class:`MLSLIntegrityError` with
   rollback-to-last-verified plus a post-restore re-audit, counted against
   ``MLSL_RESTART_BUDGET``.

The fingerprint is integer math end to end (float bits bitcast to int32,
blockwise wraparound sums): any reduction order gives the same result, so
the same logical state fingerprints identically through the plain, bucketed,
and quantized comm paths (pinned by tests/test_sentinel.py), and a single
flipped bit changes its block's checksum.

``corrupt_silent`` is the proof harness: it applies a chaos ``silent`` plan
(mlsl_tpu.chaos — flip/perturb one element of one replica's copy, never
raising) so soak tests can assert the sentinel catches exactly the class of
fault every other rung misses.

Knobs (docs/TUNING.md §13): MLSL_SENTINEL_GATE, MLSL_SENTINEL_EVERY,
MLSL_SENTINEL_SPIKE, MLSL_SENTINEL_ZMAX, MLSL_SENTINEL_WARMUP,
MLSL_SENTINEL_BLOCK — validated in Config.validate(); the audit interval is
tuner-tunable (tuner.KNOB_RANGES).
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from mlsl_tpu import chaos
from mlsl_tpu.core import stats as stats_mod
from mlsl_tpu.log import MLSLIntegrityError, log_warning
from mlsl_tpu.obs import tracer as obs

#: EMA decay for the spike/z-score screens: ~last 10 healthy steps dominate.
EMA_DECAY = 0.9

#: gate responses, mildest first (Config.validate pins the legal set)
GATE_RESPONSES = ("warn", "skip_step", "rollback")

# last completed audit, process-wide (like the watchdog event record): the
# supervisor dashboard and post-mortems need "when did we last KNOW the
# state was consistent" even after the trainer that ran it is gone
_last_audit: Optional[dict] = None


def armed(config) -> bool:
    """Does this Config arm any sentinel layer?"""
    return bool(
        config is not None
        and (getattr(config, "sentinel_gate", "")
             or getattr(config, "sentinel_every", 0) > 0)
    )


def status() -> dict:
    """Sentinel summary for ``supervisor.status()`` dashboards: counters plus
    the last completed audit. ``state`` mirrors the breaker vocabulary:
    'idle' (never ran), 'armed' (screening/auditing, nothing found),
    'tripped' (a gate escalated or an audit found divergence)."""
    c = dict(stats_mod.SENTINEL_COUNTERS)
    if c["gate_rollback"] or c["audit_mismatch"]:
        state = "tripped"
    elif c["screened"] or c["audits"]:
        state = "armed"
    else:
        state = "idle"
    out = {"state": state, **c}
    if _last_audit is not None:
        out["last_audit"] = dict(_last_audit)
    return out


@dataclasses.dataclass
class AuditResult:
    """One consistency audit: ``equal`` is the on-device pmin/pmax verdict
    over the replicated state's fingerprint; ``digest`` identifies the state
    (sha256 of the fingerprint vector) and is what checkpoint manifests
    record / post-restore re-audits compare."""

    equal: bool
    digest: str
    step: int
    blocks: int


class Sentinel:
    """Per-trainer integrity sentinel (construct via :meth:`from_config`).

    The screen and audit programs are built lazily against the trainer's
    actual tree structure and cached; a rebuilt trainer (recovery cycle)
    carries a fresh Sentinel with cold caches — correctness never depends on
    cross-trainer cache reuse."""

    def __init__(self, mesh, gate: str = "", every: int = 0,
                 spike: float = 10.0, zmax: float = 8.0, warmup: int = 5,
                 block: int = 4096, codec_guard_window: int = 3):
        self.mesh = mesh
        self.gate_response = gate
        self.every = int(every)
        self.spike = float(spike)
        self.zmax = float(zmax)
        self.warmup = int(warmup)
        self.block = int(block)
        # consecutive loss-outlier screens before a calibrated codec demotes
        # to int8 (MLSL_CODEC_GUARD_BREACHES; mlsl_tpu.codecs.guard_note)
        self.codec_guard_window = int(codec_guard_window)
        # EMA state for the history-armed screens (healthy steps only)
        self._n = 0
        self._ema_norm: Optional[float] = None
        self._loss_mean: Optional[float] = None
        self._loss_var = 0.0
        # program caches
        self._screen_fn = None
        self._count_fn = None
        self._screen_key: Optional[Tuple] = None
        self._audit_fn = None
        self._audit_key: Optional[Tuple] = None
        self._last: Optional[AuditResult] = None

    @classmethod
    def from_config(cls, config, mesh) -> "Sentinel":
        return cls(
            mesh,
            gate=config.sentinel_gate,
            every=config.sentinel_every,
            spike=config.sentinel_spike,
            zmax=config.sentinel_zmax,
            warmup=config.sentinel_warmup,
            block=config.sentinel_block,
            codec_guard_window=getattr(config, "codec_guard_breaches", 3),
        )

    @property
    def gate_armed(self) -> bool:
        return bool(self.gate_response)

    @property
    def audit_armed(self) -> bool:
        return self.every > 0

    # -- layer 1: the step quality gate -----------------------------------

    def _build_screen_fns(self, grads, loss):
        # THE gate cost model. Healthy path: ONE fused pass over the
        # (sharded) gradient buffers computing the per-device squared-norm
        # partial — Σg² alone detects nonfinite payloads (NaN/Inf propagate
        # through the sum; a finite overflow lands Inf, which also deserves
        # the gate), so no separate isfinite pass is paid per step. Partials
        # come back as tiny (R,D,S,M) arrays the HOST sums — zero in-program
        # collectives, because a psum pays a cross-device rendezvous per
        # step. The loss value rides through the same program so the gate's
        # one host sync reads two tiny arrays instead of gathering the
        # sharded loss buffer. The nonfinite COUNT (the diagnostic the log
        # line reports) runs as a second program ONLY after a verdict fires.
        # The PARAMS are deliberately not scanned per step — a corrupt
        # parameter poisons the loss/gradients it produces the very step it
        # is read, so the grads+loss screens already catch it, while a
        # replicated-params scan would pay the full parameter footprint per
        # device per step; parameter *state* integrity is the audit's layer.
        from jax.sharding import PartitionSpec as P

        from mlsl_tpu.comm.collectives import smap
        from mlsl_tpu.comm.mesh import GRID_AXES, NUM_GRID_AXES

        grid1 = (1,) * len(GRID_AXES)
        specs = jax.tree.map(
            lambda l: P(*GRID_AXES, *([None] * (l.ndim - NUM_GRID_AXES))),
            grads,
        )
        loss_spec = P(*GRID_AXES, *([None] * (loss.ndim - NUM_GRID_AXES)))
        out = P(*GRID_AXES)

        def float_leaves(g):
            return [l for l in jax.tree.leaves(g)
                    if jnp.issubdtype(l.dtype, jnp.floating)]

        def sq_body(g, lv):
            sq = jnp.float32(0.0)
            for leaf in float_leaves(g):
                sq += jnp.sum(jnp.square(leaf.astype(jnp.float32)))
            return (
                sq.reshape(grid1),
                lv.reshape(-1)[0].astype(jnp.float32).reshape(grid1),
            )

        def nf_body(g):
            nf = jnp.int32(0)
            for leaf in float_leaves(g):
                nf += jnp.sum(~jnp.isfinite(leaf), dtype=jnp.int32)
            return nf.reshape(grid1)

        screen = jax.jit(smap(sq_body, self.mesh,
                              in_specs=(specs, loss_spec),
                              out_specs=(out, out), check=False))
        count = jax.jit(smap(nf_body, self.mesh, in_specs=(specs,),
                             out_specs=out, check=False))
        return screen, count

    def gate(self, loss, grads, params, step: int) -> bool:
        """Screen one step's (loss, local grads) before any gradient comm
        starts. Returns True to proceed with the update, False to skip it
        (response ``skip_step``); raises :class:`MLSLIntegrityError` under
        ``rollback``. Healthy steps feed the EMA state; fired steps never do
        (a poisoned norm must not drag the threshold up to meet the next
        poisoned step). ``params`` rides along for response context only —
        see _build_screen_fns for why the screen never scans it."""
        if not self.gate_response:
            return True
        tr = obs._tracer
        t0 = tr.now() if tr is not None else 0
        key = (
            jax.tree.structure(grads),
            tuple((l.shape, str(l.dtype)) for l in jax.tree.leaves(grads)),
        )
        if self._screen_fn is None or key != self._screen_key:
            self._screen_fn, self._count_fn = self._build_screen_fns(
                grads, loss
            )
            self._screen_key = key
        # one host sync for the whole verdict (the gate's entire step cost):
        # two tiny per-device partial arrays, summed on the host
        sq_a, lv_a = jax.device_get(self._screen_fn(grads, loss))
        sq = float(np.sum(sq_a, dtype=np.float64))
        lv = float(np.asarray(lv_a).reshape(-1)[0])
        norm = math.sqrt(sq) if math.isfinite(sq) and sq >= 0 else float("inf")
        stats_mod.record_sentinel("screened")

        reason = None
        if not math.isfinite(sq) or not np.isfinite(lv_a).all():
            # lv_a carries every device's LOCAL loss, so a single replica's
            # poisoned forward pass is caught even when device 0 looks fine.
            # The element count is diagnostics for the fired path only (a
            # second pass the healthy path never pays).
            nf_g = int(np.sum(jax.device_get(self._count_fn(grads))))
            reason = (f"nonfinite: {nf_g} grad elements, "
                      f"sqnorm={sq!r}, loss={lv!r}")
        elif self._n >= self.warmup:
            if (self._ema_norm is not None and self._ema_norm > 0
                    and norm > self.spike * self._ema_norm):
                reason = (f"grad-norm spike: {norm:.4g} > {self.spike:g} x "
                          f"EMA {self._ema_norm:.4g}")
            elif self._loss_mean is not None:
                sd = math.sqrt(max(self._loss_var, 0.0))
                if sd > 0 and abs(lv - self._loss_mean) > self.zmax * sd:
                    reason = (f"loss outlier: {lv:.4g} vs EMA "
                              f"{self._loss_mean:.4g} +- {self.zmax:g} x "
                              f"{sd:.4g}")

        if tr is not None:
            tr.complete("sentinel.gate", "sentinel", t0, step=step,
                        grad_norm=round(norm, 6) if math.isfinite(norm)
                        else None,
                        fired=reason)
        # codec-lab online guardrail (mlsl_tpu.codecs): the loss z-score
        # screen doubles as the convergence monitor for calibrated codecs —
        # sustained outliers demote the guarded sets to int8. Healthy
        # screens reset the streak; spike/nonfinite firings are hardware-
        # attributable and neither advance nor reset it.
        loss_outlier = reason is not None and reason.startswith("loss outlier")
        if reason is None or loss_outlier:
            from mlsl_tpu import codecs as codecs_mod

            if codecs_mod.guard_active():
                codecs_mod.guard_note(loss_outlier,
                                      window=self.codec_guard_window,
                                      step=step)
        if reason is None:
            self._observe(norm, lv)
            return True
        resp = self.gate_response
        short = {"warn": "warn", "skip_step": "skip",
                 "rollback": "rollback"}[resp]
        stats_mod.record_sentinel(f"gate_{short}")
        log_warning("sentinel gate fired at step %d (%s): %s", step, resp,
                    reason)
        if tr is not None:
            tr.instant("integrity.gate", "sentinel", step=step,
                       response=resp, reason=reason)
        if resp == "rollback":
            raise MLSLIntegrityError(
                f"step quality gate at step {step}: {reason} "
                "(response=rollback)"
            )
        return resp != "skip_step"

    def _observe(self, norm: float, loss: float) -> None:
        self._n += 1
        if self._ema_norm is None:
            self._ema_norm = norm
        else:
            self._ema_norm = EMA_DECAY * self._ema_norm + (1 - EMA_DECAY) * norm
        if self._loss_mean is None:
            self._loss_mean = loss
        else:
            dev = loss - self._loss_mean
            self._loss_mean += (1 - EMA_DECAY) * dev
            self._loss_var = (EMA_DECAY * self._loss_var
                              + (1 - EMA_DECAY) * dev * dev)

    # -- layer 2: the cross-replica consistency audit ----------------------

    def _leaf_blocks(self, x):
        """One leaf's local view -> its blockwise int32 checksum vector.
        Integer math end to end: bitcast (not cast) preserves every payload
        bit, and int32 wraparound addition is exact and order-independent,
        so the fingerprint is deterministic across comm paths and reduction
        orders — and any single flipped bit changes its block's sum."""
        flat = x.reshape(-1)
        if flat.dtype == jnp.float32:
            v = jax.lax.bitcast_convert_type(flat, jnp.int32)
        elif flat.dtype in (jnp.bfloat16, jnp.float16):
            v = jax.lax.bitcast_convert_type(flat, jnp.int16).astype(jnp.int32)
        elif flat.dtype == jnp.float64:
            # x64 mode: bitcast to int64 and XOR-fold the halves — a cast to
            # f32 would round away low-mantissa bit flips and fingerprint a
            # corrupted replica as clean
            v64 = jax.lax.bitcast_convert_type(flat, jnp.int64)
            v = (v64 ^ (v64 >> 32)).astype(jnp.int32)
        elif flat.dtype in (jnp.int64, jnp.uint64):
            v64 = flat.astype(jnp.int64)
            v = (v64 ^ (v64 >> 32)).astype(jnp.int32)
        else:
            v = flat.astype(jnp.int32)
        pad = (-v.shape[0]) % self.block
        if pad:
            v = jnp.pad(v, (0, pad))
        return v.reshape(-1, self.block).sum(axis=1, dtype=jnp.int32)

    def _build_audit_fn(self, rep_tree, sh_tree):
        from jax.sharding import PartitionSpec as P

        from mlsl_tpu.comm.collectives import smap
        from mlsl_tpu.comm.mesh import GRID_AXES, NUM_GRID_AXES

        axes = tuple(GRID_AXES)
        rep_specs = jax.tree.map(lambda _: P(), rep_tree)
        sh_specs = jax.tree.map(
            lambda l: P(*GRID_AXES, *([None] * (l.ndim - NUM_GRID_AXES))),
            sh_tree,
        )

        def body(rep, sh):
            rep_fp = jnp.concatenate(
                [self._leaf_blocks(l) for l in jax.tree.leaves(rep)]
            )
            # mlsl-lint: disable=A201 -- the audit's integer fingerprint
            # comparison must stay exact-math in-graph primitives; routing
            # through the engine would subject it to the very degrade/quant
            # paths it audits
            mn = jax.lax.pmin(rep_fp, axes)
            mx = jax.lax.pmax(rep_fp, axes)  # mlsl-lint: disable=A201
            equal = jnp.all(mn == mx)
            parts = [mn]
            sh_leaves = jax.tree.leaves(sh)
            if sh_leaves:
                parts.append(jax.lax.psum(  # mlsl-lint: disable=A201
                    jnp.concatenate([self._leaf_blocks(l) for l in sh_leaves]),
                    axes,
                ))
            return equal, jnp.concatenate(parts)

        sm = smap(body, self.mesh, in_specs=(rep_specs, sh_specs),
                  out_specs=(P(), P()), check=False)
        return jax.jit(sm)

    @staticmethod
    def _audit_state(trainer) -> Tuple[dict, dict]:
        """(replicated, sharded) state trees the audit covers: params + the
        replicated optax state go through pmin/pmax replica comparison; the
        ZeRO-1 owned-shard state (per-rank unique) joins the fingerprint via
        an exact integer psum instead."""
        rep: Dict[str, object] = {"params": trainer.params}
        if getattr(trainer, "_opt_state", None) is not None:
            rep["opt_state"] = trainer._opt_state
        sh: Dict[str, object] = {}
        if getattr(trainer, "_du_opt_state", None):
            sh["du_opt_state"] = trainer._du_opt_state
        return rep, sh

    def audit_now(self, trainer, step: int) -> AuditResult:
        """Run the consistency audit immediately (no cadence check) and
        return the verdict + state digest. Never raises on mismatch — the
        policy (raise, log, prefer another checkpoint) belongs to the
        caller; :meth:`maybe_audit` applies the standard one."""
        global _last_audit
        tr = obs._tracer
        t0 = tr.now() if tr is not None else 0
        rep, sh = self._audit_state(trainer)
        key = (
            jax.tree.structure((rep, sh)),
            tuple((l.shape, str(l.dtype))
                  for l in jax.tree.leaves((rep, sh))),
        )
        if self._audit_fn is None or key != self._audit_key:
            self._audit_fn = self._build_audit_fn(rep, sh)
            self._audit_key = key
        equal_dev, fp_dev = self._audit_fn(rep, sh)
        equal = bool(jax.device_get(equal_dev))
        fp = np.asarray(jax.device_get(fp_dev), dtype="<i4")
        digest = hashlib.sha256(fp.tobytes()).hexdigest()
        res = AuditResult(equal=equal, digest=digest, step=step,
                          blocks=int(fp.size))
        stats_mod.record_sentinel("audits")
        if not equal:
            stats_mod.record_sentinel("audit_mismatch")
        self._last = res
        _last_audit = {"step": step, "equal": equal, "digest": digest}
        if tr is not None:
            tr.complete("sentinel.audit", "sentinel", t0, step=step,
                        equal=equal, blocks=res.blocks,
                        digest=digest[:16])
            if not equal:
                tr.instant("integrity.violation", "sentinel", step=step,
                           digest=digest[:16])
        if not equal:
            log_warning(
                "sentinel audit at step %d: replica fingerprints DIVERGE "
                "(digest %s) — params/optimizer state is corrupt on at "
                "least one replica", step, digest[:16],
            )
        return res

    def maybe_audit(self, trainer, step: int) -> Optional[AuditResult]:
        """Cadence-gated audit (every ``MLSL_SENTINEL_EVERY`` steps); raises
        :class:`MLSLIntegrityError` on divergence so FaultTolerantLoop rolls
        back to the newest verified checkpoint."""
        if self.every <= 0 or step % self.every:
            return None
        res = self.audit_now(trainer, step)
        if not res.equal:
            raise MLSLIntegrityError(
                f"cross-replica consistency audit failed at step {step}: "
                f"params/optimizer fingerprints diverge across replicas "
                f"(digest {res.digest[:16]})"
            )
        return res

    def checkpoint_fingerprint(self, trainer, step: int) -> str:
        """Audit at a checkpoint boundary and return the digest the manifest
        records. Raises on divergence — corrupt state must NEVER be saved as
        a verified resume point (the raise takes the standard recovery
        path instead of poisoning the checkpoint history)."""
        res = self._last
        if res is None or res.step != step:
            res = self.audit_now(trainer, step)
        if not res.equal:
            raise MLSLIntegrityError(
                f"refusing to checkpoint step {step}: consistency audit "
                f"found replica divergence (digest {res.digest[:16]})"
            )
        stats_mod.record_sentinel("verified_saves")
        return res.digest


# -- the proof harness: seeded silent corruption ------------------------------


def corrupt_silent(tree, plan):
    """Apply one chaos ``silent`` plan to a pytree of arrays WITHOUT raising
    — the fault class every loud-path defense misses, injected so soaks can
    prove the sentinel catches it. Seeded by the chaos RNG
    (``MLSL_CHAOS_SEED`` / ``chaos.seed``), so a soak schedule replays.

    One float leaf, one element, ONE addressable shard: for a replicated
    array (trainer params / optax state) that corrupts a single replica's
    copy — exactly the divergence the consistency audit hunts; for a
    distributed buffer it perturbs one device's payload slice.

    ``plan.mag`` is None for a random single-bit flip; nan/inf overwrite
    the element; a finite value adds ``mag * (|x| + 1)``. Returns a new
    tree (inputs are never mutated in place — jax arrays cannot be)."""
    leaves, treedef = jax.tree.flatten(tree)
    # jnp.issubdtype, not np: ml_dtypes bfloat16 is NOT np.floating, and a
    # bf16 model's "silent fault" must actually corrupt something rather
    # than burn the plan's budget as a no-op
    float_idx = [
        i for i, l in enumerate(leaves)
        if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)
    ]
    if not float_idx:
        return tree
    rng = chaos._rng
    li = float_idx[rng.randrange(len(float_idx))]
    leaf = leaves[li]
    if not isinstance(leaf, jax.Array):
        leaves[li] = _corrupt_host(np.array(leaf), plan, rng)
        return jax.tree.unflatten(treedef, leaves)
    shards = leaf.addressable_shards
    si = rng.randrange(len(shards))
    datas = [np.array(s.data) for s in shards]
    datas[si] = _corrupt_host(datas[si], plan, rng)
    new_leaf = jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding,
        [jax.device_put(d, s.device) for d, s in zip(datas, shards)],
    )
    leaves[li] = new_leaf
    return jax.tree.unflatten(treedef, leaves)


def corrupt_replica(tree, devices, plan):
    """Targeted variant of :func:`corrupt_silent` for the elastic admission
    proof: corrupt one element of one float leaf's copy ON A SPECIFIC device
    set — the REJOINING replica's copy after an elastic grow — so the
    admission audit (the cross-replica fingerprint) has exactly the
    divergence it must reject. Leaves whose sharding places no addressable
    shard on ``devices`` are skipped; returns the tree unchanged when no
    leaf is corruptible there."""
    targets = set(devices)
    leaves, treedef = jax.tree.flatten(tree)
    rng = chaos._rng
    cand = []
    for i, l in enumerate(leaves):
        if not (isinstance(l, jax.Array)
                and jnp.issubdtype(l.dtype, jnp.floating)):
            continue
        hit = [si for si, s in enumerate(l.addressable_shards)
               if s.device in targets]
        if hit:
            cand.append((i, hit))
    if not cand:
        return tree
    li, hit = cand[rng.randrange(len(cand))]
    leaf = leaves[li]
    shards = leaf.addressable_shards
    si = hit[rng.randrange(len(hit))]
    datas = [np.array(s.data) for s in shards]
    datas[si] = _corrupt_host(datas[si], plan, rng)
    leaves[li] = jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding,
        [jax.device_put(d, s.device) for d, s in zip(datas, shards)],
    )
    return jax.tree.unflatten(treedef, leaves)


def _corrupt_host(arr: np.ndarray, plan, rng) -> np.ndarray:
    flat = arr.reshape(-1)
    if flat.size == 0:
        return arr
    idx = rng.randrange(flat.size)
    mag = getattr(plan, "mag", None)
    if mag is None:
        # single-bit flip in the element's raw representation (the classic
        # SDC); the uint view width follows the dtype's byte size
        width = flat.dtype.itemsize
        uview = flat[idx:idx + 1].view({1: np.uint8, 2: np.uint16,
                                        4: np.uint32, 8: np.uint64}[width])
        uview[0] = int(uview[0]) ^ (1 << rng.randrange(width * 8))
    elif not math.isfinite(mag):
        flat[idx] = mag
    else:
        v = float(flat[idx])
        flat[idx] = flat.dtype.type(v + mag * (abs(v) + 1.0))
    return arr
