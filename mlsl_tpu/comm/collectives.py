"""The collective library: cached jit-compiled shard_map programs over the mesh.

This replaces the reference's per-backend collective dispatch (src/comm_ep.cpp:768-1378,
src/comm_handoff.cpp:491-564). Design:

- A "distributed buffer" is one global jax.Array of shape (R, D, S, M, n): the
  (r, d, s, m) slice is that rank's local buffer (what each MPI rank would hold;
  S = sequence-parallel axis, 1 unless seq_parts is used). Collectives are
  pure functions global-buffer -> global-buffer, built with ``shard_map`` so XLA sees
  the per-device program and lowers group operations onto ICI collectives.

- Axis-aligned groups use native XLA collective ops (psum / psum_scatter / all_gather /
  all_to_all) — the fast path, equivalent to how the reference leans on MPI's optimized
  collectives rather than hand-rolling (eplib routes to PMPI_I* in cqueue.c:1906-2026).

- Color groups (arbitrary MPI_Comm_split-style subgroups, reference
  src/mlsl.cpp:620-647) and exotic shapes (AlltoAllv) fall back to a gather+mask
  emulation: correct everywhere, efficient enough for cold paths.

- Every built program is cached per (kind, group, count(s), dtype, op, root) — the
  analog of the reference caching CommRequests per graph edge, and the key to the perf
  target: the hot loop re-dispatches an already-compiled XLA executable with zero
  retracing.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:  # JAX >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from mlsl_tpu.comm.mesh import GRID_AXES, NUM_GRID_AXES, ProcessGroup
from mlsl_tpu.log import mlsl_assert
from mlsl_tpu.types import ReductionType

ALL_AXES = GRID_AXES
_BUF_SPEC = P(*GRID_AXES, None)


def smap(f, mesh, in_specs, out_specs, check: bool = True):
    """shard_map with a version-compatible way to disable VMA/replication checking
    (needed when out_specs claim replication the compiler can't prove, or when the
    body contains pallas_call, whose outputs carry no vma annotation)."""
    if check:
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    for kw in ({"check_vma": False}, {"check_rep": False}):
        try:
            return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
        except TypeError:
            continue
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def _axis_sizes(mesh) -> dict:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def _group_rank(axes: Sequence[str], sizes: dict):
    """Flattened member index over ``axes`` (major -> minor), as a traced value."""
    idx = jnp.int32(0)
    for a in axes:
        idx = idx * sizes[a] + lax.axis_index(a)
    return idx


def _gather_group(x, axes: Sequence[str]):
    """Local (n, ...) -> (G, n, ...): every member's block, in group-rank order.

    Built from nested tiled all_gathers (minor axis first) so multi-axis groups work on
    every JAX version; XLA fuses the nest into one gather on a single axis.
    """
    y = x[None]
    for a in reversed(tuple(axes)):
        y = lax.all_gather(y, a, axis=0, tiled=True)
    return y


def _reduce_local(vals, op: ReductionType, axis=0):
    if op == ReductionType.SUM:
        return jnp.sum(vals, axis=axis)
    if op == ReductionType.MIN:
        return jnp.min(vals, axis=axis)
    return jnp.max(vals, axis=axis)


def _preduce(x, axes, op: ReductionType):
    axes = tuple(axes)
    if op == ReductionType.SUM:
        return lax.psum(x, axes)
    if op == ReductionType.MIN:
        return lax.pmin(x, axes)
    return lax.pmax(x, axes)


# ---------------------------------------------------------------------------
# Local (per-shard) collective bodies. Each takes the squeezed local buffer
# (shape (n,)) and returns the squeezed local result.
# ---------------------------------------------------------------------------


def _body_allreduce(x, *, axes, sizes, op, **_):
    return _preduce(x, axes, op)


def _body_reduce(x, *, axes, sizes, op, root, **_):
    # MPI semantics: result meaningful only at root. Returning the reduction on
    # every member is a strict superset AND the faster program on a ring
    # interconnect — rooted trees cost more link-bytes than the pipelined
    # psum (hop-count argument: docs/DESIGN.md "Rooted collectives").
    return _preduce(x, axes, op)


def _body_bcast(x, *, axes, sizes, root, **_):
    # One-to-all in O(n) wire: only the root contributes to a group psum (lowered
    # by XLA as reduce-scatter + all-gather over the ICI ring), instead of every
    # member materializing the full (G, n) gather just to index the root's row.
    # The reference uses true MPI_Ibcast (src/comm_ep.cpp:773-807).
    me = _group_rank(axes, sizes)
    contrib = jnp.where(me == root, x, jnp.zeros_like(x))
    return lax.psum(contrib, tuple(axes))


def _body_allgather(x, *, axes, sizes, **_):
    g = _gather_group(x, axes)           # (G, n)
    return g.reshape((-1,) + x.shape[1:])


def _body_allgatherv(x, *, axes, sizes, recv_counts, **_):
    g = _gather_group(x, axes)           # (G, maxcount)
    parts = [g[i, : recv_counts[i]] for i in range(len(recv_counts))]
    return jnp.concatenate(parts, axis=0)


def _body_gather(x, *, axes, sizes, root, **_):
    # Root-only semantics; full concatenation returned on every member (superset).
    return _body_allgather(x, axes=axes, sizes=sizes)


def _body_scatter(x, *, axes, sizes, root, recv_count, **_):
    # Masked reduce-scatter: only root's buffer survives the sum, and the scatter
    # hands member i root's segment i — O(n) total wire (vs the (G, G*recv_count)
    # gather a naive emulation needs). Reference uses true MPI_Iscatter
    # (src/comm_ep.cpp:1011-1120).
    me = _group_rank(axes, sizes)
    contrib = jnp.where(me == root, x, jnp.zeros_like(x))
    if len(axes) == 1:
        return lax.psum_scatter(contrib, axes[0], scatter_dimension=0, tiled=True)
    red = lax.psum(contrib, tuple(axes))
    return lax.dynamic_slice_in_dim(red, me * recv_count, recv_count, axis=0)


def _body_reduce_scatter(x, *, axes, sizes, op, recv_count, **_):
    if op == ReductionType.SUM and len(axes) == 1:
        return lax.psum_scatter(x, axes[0], scatter_dimension=0, tiled=True)
    red = _preduce(x, axes, op)          # (G*recv_count,)
    me = _group_rank(axes, sizes)
    return lax.dynamic_slice_in_dim(red, me * recv_count, recv_count, axis=0)


def _body_sendrecv(x, *, axes, sizes, pairs, **_):
    """Neighbor/point-to-point exchange list: member src -> member dst for each
    (src, dst) pair; members not receiving get zeros.

    Implements the reference's declared-but-unimplemented SendRecvList CommOp
    (src/comm.hpp:212-248) — on TPU this IS lax.ppermute, whose transfers ride the
    ICI neighbor links directly.
    """
    if len(axes) == 1:
        return lax.ppermute(x, axes[0], [(int(s), int(d)) for s, d in pairs])
    g = _gather_group(x, axes)           # (G, n)
    me = _group_rank(axes, sizes)
    out = jnp.zeros_like(x)
    for s, d in pairs:
        out = jnp.where(me == d, g[int(s)], out)
    return out


def _body_alltoall(x, *, axes, sizes, send_count, **_):
    if len(axes) == 1:
        return lax.all_to_all(x, axes[0], split_axis=0, concat_axis=0, tiled=True)
    g = sizes_prod(axes, sizes)
    blocks = _gather_group(x.reshape(g, send_count), axes)  # (G, G, send_count)
    me = _group_rank(axes, sizes)
    mine = lax.dynamic_index_in_dim(blocks, me, axis=1, keepdims=False)  # (G, count)
    return mine.reshape(g * send_count)


def _body_alltoallv(x, *, axes, sizes, S=None, Soff=None, Roff=None, recv_len=None,
                    S_tab=None, Soff_tab=None, Roff_tab=None, lmax=None, **_):
    """Emulated AlltoAllv with full static count matrices (MPI semantics).

    Instance-uniform mode (S/Soff/Roff given): S[i][j] = elements member i sends to
    member j; Soff[i][j] = offset of that segment in i's send buffer; Roff[i][j] =
    offset in i's receive buffer where data from j lands — the same matrix for every
    group instance.

    Per-rank mode (S_tab/Soff_tab/Roff_tab given): (W, G, G) tables, row w = the
    instance matrices seen by world rank w (each rank supplies its OWN count/offset
    vectors, full MPI generality — different group instances may exchange different
    geometries). The reference expresses this with per-rank count arrays passed to
    pairwise Isend/Irecv (src/comm_ep.cpp:1188-1265); SPMD needs the matrices
    statically, selected per rank by a traced world-rank index. Segment lengths vary
    per (j, me) pair, so slices use a static max length with a validity mask.
    """
    if S_tab is not None:
        return _alltoallv_per_rank(
            _gather_group(x, axes), _group_rank(ALL_AXES, sizes),
            _group_rank(axes, sizes), x.dtype,
            S_tab, Soff_tab, Roff_tab, recv_len, lmax,
        )
    return _alltoallv_core(
        _gather_group(x, axes), _group_rank(axes, sizes), x.dtype,
        S, Soff, Roff, recv_len,
    )


def sizes_prod(axes, sizes) -> int:
    g = 1
    for a in axes:
        g *= sizes[a]
    return g


# ---------------------------------------------------------------------------
# Subgroup bodies: XLA-native arbitrary subgroups via axis_index_groups.
#
# Color groups (MPI_Comm_split partitions, reference src/comm_ep.cpp:1821-1827)
# and multi-axis alltoall/sendrecv compile against the flattened single-axis
# "world" mesh (Topology.flat_mesh): lax collectives take axis_index_groups
# there, which lowers to HLO replica_groups — true subgroup collectives on the
# wire, not a world-gather emulation. Equal-size groups only (XLA's replica
# groups are rectangular); ragged color groups use _make_ragged_body below.
# ---------------------------------------------------------------------------


def _subgroup_tables(groups: Tuple[Tuple[int, ...], ...]):
    """pos[p] = p's member index within its group row."""
    w = sum(len(g) for g in groups)
    pos = np.zeros((w,), dtype=np.int32)
    for row in groups:
        for i, p in enumerate(row):
            pos[p] = i
    return pos


def _color_groups_tbl(group: ProcessGroup) -> Tuple[Tuple[int, ...], ...]:
    """Member rows (world ranks, in world-rank order — MPI_Comm_split member
    ordering) per color, colors ascending."""
    return tuple(
        group.member_world_ranks(c) for c in sorted(set(group.colors))
    )


def _axis_groups_tbl(group: ProcessGroup) -> Tuple[Tuple[int, ...], ...]:
    """Member rows for an axis-aligned group: one row per instance (product of the
    complementary axes), members in group-rank order (group.axes major->minor)."""
    import itertools

    topo = group.topology
    shape = dict(zip(GRID_AXES, topo.grid_shape))
    comp = [a for a in GRID_AXES if a not in group.axes]
    rows = []
    for comp_coords in itertools.product(*(range(shape[a]) for a in comp)):
        fixed = dict(zip(comp, comp_coords))
        row = []
        for g_coords in itertools.product(*(range(shape[a]) for a in group.axes)):
            c = {**fixed, **dict(zip(group.axes, g_coords))}
            row.append(topo.global_idx(c[GRID_AXES[0]], c[GRID_AXES[1]],
                                       c[GRID_AXES[2]], c[GRID_AXES[3]]))
        rows.append(tuple(row))
    return tuple(rows)


def _member_world_table(group: ProcessGroup) -> np.ndarray:
    """(W, G) table: row w = the world ranks of w's group-instance members, in
    group-rank order. Uniform groups only (axis-aligned or equal color groups)."""
    if group.colors is not None:
        rows = _color_groups_tbl(group)
    elif not group.axes:
        return np.arange(group.topology.world_size, dtype=np.int32)[:, None]
    else:
        rows = _axis_groups_tbl(group)
    tbl = np.zeros((group.topology.world_size, len(rows[0])), dtype=np.int32)
    for row in rows:
        for p in row:
            tbl[p] = row
    return tbl


def _per_rank_alltoallv_tables(group: ProcessGroup, kw: dict) -> dict:
    """Expand per-world-rank count/offset rows (Sw/Swoff/Rwoff, each (W, G)) into
    the (W, G, G) per-instance matrix tables the bodies select by world rank.

    Row w of each table holds the instance matrices as seen by world rank w:
    S_tab[w][i][j] = elements the member at group position i of w's instance
    sends to position j. Footprint is W*G*G i32 — for subgroups (G << W, the
    only case where tables differ from the instance-uniform (G, G) matrix)
    this stays small (e.g. W=256, G=16 -> 256 KiB)."""
    M = _member_world_table(group)                       # (W, G)
    Sw = np.asarray(kw.pop("Sw"), dtype=np.int32)        # (W, G)
    Swoff = np.asarray(kw.pop("Swoff"), dtype=np.int32)
    Rwoff = np.asarray(kw.pop("Rwoff"), dtype=np.int32)
    to3 = lambda t: tuple(tuple(tuple(int(v) for v in r) for r in m) for m in t)
    out = dict(kw)
    out["S_tab"] = to3(Sw[M])
    out["Soff_tab"] = to3(Swoff[M])
    out["Roff_tab"] = to3(Rwoff[M])
    out["lmax"] = max(int(Sw.max()), 1) if Sw.size else 1
    return out


def _alltoallv_per_rank(g_members, me_w, me_pos, x_dtype,
                        S_tab, Soff_tab, Roff_tab, recv_len, lmax):
    """Select this world rank's instance matrices from the (W, G, G) tables by
    the traced index ``me_w`` and run the shared merge — the one helper behind
    the axis-aligned, flat-subgroup, and single-member per-rank paths."""
    sel = lambda t: jnp.take(jnp.asarray(t, dtype=jnp.int32), me_w, axis=0)
    return _alltoallv_core(
        g_members, me_pos, x_dtype,
        sel(S_tab), sel(Soff_tab), sel(Roff_tab), recv_len, lmax=lmax,
    )


def _alltoallv_core(g_members, me_pos, x_dtype, S, Soff, Roff, recv_len, lmax=None):
    """Shared AlltoAllv scatter/merge math over an already-gathered (G, send_len)
    member block; see _body_alltoallv for the semantics. The matrices may be
    static tuples or traced (G, G) arrays (the per-rank table path); ``lmax``
    (the static max segment length) must be supplied in the traced case."""
    g = len(S)
    s_m = jnp.asarray(S, dtype=jnp.int32)
    soff_m = jnp.asarray(Soff, dtype=jnp.int32)
    roff_m = jnp.asarray(Roff, dtype=jnp.int32)
    if lmax is None:
        lmax = int(np.max(S)) if np.max(S) > 0 else 1
    lmax = max(int(lmax), 1)
    pos = jnp.arange(lmax)
    pad = jnp.zeros((lmax,), dtype=x_dtype)
    out = jnp.zeros((recv_len + lmax,), dtype=x_dtype)
    for j in range(g):
        cnt = s_m[j, me_pos]
        src = lax.dynamic_slice_in_dim(
            jnp.concatenate([g_members[j], pad]), soff_m[j, me_pos], lmax, axis=0
        )
        roff = roff_m[me_pos, j]
        window = lax.dynamic_slice_in_dim(out, roff, lmax, axis=0)
        merged = jnp.where(pos < cnt, src, window)
        out = lax.dynamic_update_slice_in_dim(out, merged, roff, axis=0)
    return out[:recv_len]


def _make_subgroup_body(kind: str, groups: Tuple[Tuple[int, ...], ...], *,
                        op=None, root=None, recv_count=None, recv_counts=None,
                        pairs=None, S=None, Soff=None, Roff=None, recv_len=None,
                        S_tab=None, Soff_tab=None, Roff_tab=None, lmax=None,
                        **_):
    """(n,) -> (out_n,) body over the single 'world' axis, using axis_index_groups."""
    gsize = len(groups[0])
    gl = [list(row) for row in groups]
    pos_t = jnp.asarray(_subgroup_tables(groups))

    def mypos():
        return jnp.take(pos_t, lax.axis_index("world"))

    def gather_group(v):                           # (n,) -> (G, n)
        return lax.all_gather(
            v[None], "world", axis=0, tiled=True, axis_index_groups=gl
        )

    def rs_ag_sum(v):
        # subgroup allreduce(SUM) = reduce-scatter + all-gather, O(n) wire;
        # pad so the scatter dimension divides the group size
        n = v.shape[0]
        r = (-n) % gsize
        if r:
            v = jnp.concatenate([v, jnp.zeros((r,), v.dtype)])
        piece = lax.psum_scatter(
            v, "world", scatter_dimension=0, tiled=True, axis_index_groups=gl
        )
        out = lax.all_gather(
            piece, "world", axis=0, tiled=True, axis_index_groups=gl
        )
        return out[:n]

    if kind in ("allreduce", "reduce"):
        if op == ReductionType.SUM:
            return rs_ag_sum
        return lambda v: _reduce_local(gather_group(v), op)
    if kind == "bcast":
        # masked reduce-scatter + all-gather: only the root contributes, so the
        # group reassembles exactly the root's buffer in O(n) wire
        return lambda v: rs_ag_sum(jnp.where(mypos() == root, v, jnp.zeros_like(v)))
    if kind in ("allgather", "gather"):
        return lambda v: gather_group(v).reshape(-1)
    if kind == "allgatherv":
        def body_agv(v):
            g = gather_group(v)
            return jnp.concatenate(
                [g[i, : recv_counts[i]] for i in range(gsize)], axis=0
            )
        return body_agv
    if kind == "scatter":
        # masked reduce-scatter: member i receives root's segment i directly
        return lambda v: lax.psum_scatter(
            jnp.where(mypos() == root, v, jnp.zeros_like(v)),
            "world", scatter_dimension=0, tiled=True, axis_index_groups=gl,
        )
    if kind == "reduce_scatter":
        if op == ReductionType.SUM:
            return lambda v: lax.psum_scatter(
                v, "world", scatter_dimension=0, tiled=True, axis_index_groups=gl
            )
        def body_rs(v):
            red = _reduce_local(gather_group(v), op)
            return lax.dynamic_slice_in_dim(
                red, mypos() * recv_count, recv_count, axis=0
            )
        return body_rs
    if kind == "alltoall":
        return lambda v: lax.all_to_all(
            v, "world", split_axis=0, concat_axis=0, tiled=True,
            axis_index_groups=gl,
        )
    if kind == "sendrecv":
        # group-relative (src, dst) member pairs -> one world ppermute across all
        # group instances; non-receivers get zeros (ppermute semantics), matching
        # the axis-aligned body
        world_pairs = [(row[int(s)], row[int(d)]) for row in groups for s, d in pairs]
        return lambda v: lax.ppermute(v, "world", world_pairs)
    if kind == "alltoallv":
        if S_tab is not None:
            # per-rank tables: select this world rank's instance matrices
            return lambda v: _alltoallv_per_rank(
                gather_group(v), lax.axis_index("world"), mypos(), v.dtype,
                S_tab, Soff_tab, Roff_tab, recv_len, lmax,
            )
        return lambda v: _alltoallv_core(
            gather_group(v), mypos(), v.dtype, S, Soff, Roff, recv_len
        )
    raise NotImplementedError(kind)  # pragma: no cover - kinds are closed above


# ---------------------------------------------------------------------------
# Ragged color groups: world-gather + padded member tables. XLA replica groups
# must be rectangular, so unequal MPI_Comm_split partitions
# (reference src/comm_ep.cpp:1821-1827) fall back to the gather+mask emulation
# with a PADDED buffer contract: every rank's buffer is laid out for Gmax (the
# largest color group's size) members. Outputs whose length depends on the
# group size (allgather/gather) pad absent members with zeros; scatter/
# reduce_scatter segments beyond a group's g*recv_count are ignored; alltoall
# blocks from absent positions arrive as zeros. Only alltoallv is rejected:
# its count matrix already expresses per-pair raggedness, so ragged
# partitions are spelled with v-counts on an equal-size group instead
# (docs/DESIGN.md "Ragged color groups").
# ---------------------------------------------------------------------------


def _ragged_tables(group: ProcessGroup):
    """(member (W, Gmax) padded with 0, valid (W, Gmax) mask, pos (W,), gsize (W,))."""
    w = group.topology.world_size
    gmax = group.size
    member = np.zeros((w, gmax), dtype=np.int32)
    valid = np.zeros((w, gmax), dtype=bool)
    pos = np.zeros((w,), dtype=np.int32)
    gsz = np.zeros((w,), dtype=np.int32)
    for p in range(w):
        ranks = group.member_world_ranks(group.colors[p])
        member[p, : len(ranks)] = ranks
        valid[p, : len(ranks)] = True
        pos[p] = ranks.index(p)
        gsz[p] = len(ranks)
    return member, valid, pos, gsz


def _make_ragged_body(kind: str, group: ProcessGroup, *, op=None, root=None,
                      pairs=None, recv_count=None, send_count=None, **_):
    if kind == "alltoallv":
        mlsl_assert(
            False,
            "alltoallv is not supported on unequal-sized color groups: its "
            "count matrix already expresses per-pair raggedness — spell the "
            "exchange with zero counts on an equal-size group instead "
            "(rationale in docs/DESIGN.md, 'Ragged color groups')",
        )
    mlsl_assert(
        kind in ("allreduce", "reduce", "bcast", "allgather", "gather",
                 "sendrecv", "scatter", "reduce_scatter", "alltoall"),
        "%s is not supported on unequal-sized color groups (per-rank result "
        "sizes would be ragged, but SPMD buffers are rank-uniform)", kind,
    )
    member_np, valid_np, pos_np, gsz_np = _ragged_tables(group)
    sizes = _axis_sizes(group.topology.mesh)
    gmax = int(group.size)
    if root is not None:
        mlsl_assert(
            root < int(gsz_np.min()),
            "root member index %d out of range for the smallest group (size %d)",
            root, int(gsz_np.min()),
        )
    if pairs:
        mlsl_assert(
            max(max(int(s), int(d)) for s, d in pairs) < int(gsz_np.min()),
            "sendrecv pair member index out of range for the smallest group",
        )
    if kind in ("scatter", "reduce_scatter"):
        mlsl_assert(recv_count is not None,
                    "%s on color groups needs recv_count", kind)
    if kind == "alltoall":
        mlsl_assert(send_count is not None, "alltoall needs send_count")

    def body(x):
        if kind in ("scatter", "reduce_scatter"):
            # Padded-buffer contract: every rank's buffer spans Gmax blocks.
            # XLA clamps out-of-range dynamic_slice starts, which would hand
            # large-group members a silent duplicate of the last in-range
            # chunk — reject loudly at trace time instead.
            mlsl_assert(
                x.size >= gmax * recv_count,
                "%s on unequal color groups needs a buffer spanning the "
                "largest group: count %d < Gmax (%d) * recv_count (%d)",
                kind, int(x.size), gmax, int(recv_count),
            )
        full = _gather_group(x, ALL_AXES)                       # (W, n)
        me = _group_rank(ALL_AXES, sizes)                       # world rank
        members = jnp.take(jnp.asarray(member_np), me, axis=0)  # (Gmax,)
        valid = jnp.take(jnp.asarray(valid_np), me, axis=0)     # (Gmax,)
        vals = jnp.take(full, members, axis=0)                  # (Gmax, n)
        vmask = valid[:, None]

        def masked_reduce():
            if op == ReductionType.MIN:
                neutral = jnp.full_like(vals, _dtype_max(vals.dtype))
            elif op == ReductionType.MAX:
                neutral = jnp.full_like(vals, _dtype_min(vals.dtype))
            else:
                neutral = jnp.zeros_like(vals)
            return _reduce_local(jnp.where(vmask, vals, neutral), op)

        if kind in ("allreduce", "reduce"):
            return masked_reduce()
        if kind == "bcast":
            return vals[root]
        if kind in ("allgather", "gather"):
            # padded semantics: members beyond this rank's group size are zeros
            return jnp.where(vmask, vals, jnp.zeros_like(vals)).reshape(-1)
        if kind == "sendrecv":
            mypos = jnp.take(jnp.asarray(pos_np), me)
            out = jnp.zeros_like(x)
            for s, d in pairs:
                out = jnp.where(mypos == d, vals[int(s)], out)
            return out
        # Padded buffer contract for the remaining kinds (the allgather
        # precedent, with Gmax = the LARGEST color group): every rank's buffer
        # is laid out for Gmax members; a group of size g < Gmax uses member
        # positions < g, and segments belonging to absent positions are
        # ignored (scatter/reduce_scatter) or zero (alltoall receive side).
        mypos = jnp.take(jnp.asarray(pos_np), me)
        if kind == "scatter":
            # root's buffer = Gmax blocks of recv_count; member at position i
            # receives block i
            return lax.dynamic_slice_in_dim(
                vals[root], mypos * recv_count, recv_count, axis=0
            )
        if kind == "reduce_scatter":
            # group sum (buffer = Gmax*recv_count), member i gets chunk i;
            # chunks beyond g*recv_count are not delivered to anyone
            return lax.dynamic_slice_in_dim(
                masked_reduce(), mypos * recv_count, recv_count, axis=0
            )
        if kind == "alltoall":
            # sender j's buffer = Gmax blocks; I receive each member's block
            # at my position; blocks from absent positions arrive as zeros
            blocks = vals.reshape(gmax, gmax, send_count)
            mine = lax.dynamic_index_in_dim(blocks, mypos, axis=1, keepdims=False)
            return jnp.where(vmask, mine, jnp.zeros_like(mine)).reshape(-1)
        raise NotImplementedError(kind)  # pragma: no cover - guarded above

    return body


def _dtype_max(dt):
    return jnp.inf if jnp.issubdtype(dt, jnp.floating) else jnp.iinfo(dt).max


def _dtype_min(dt):
    return -jnp.inf if jnp.issubdtype(dt, jnp.floating) else jnp.iinfo(dt).min


_AXIS_BODIES = {
    "sendrecv": _body_sendrecv,
    "allreduce": _body_allreduce,
    "reduce": _body_reduce,
    "bcast": _body_bcast,
    "allgather": _body_allgather,
    "allgatherv": _body_allgatherv,
    "gather": _body_gather,
    "scatter": _body_scatter,
    "reduce_scatter": _body_reduce_scatter,
    "alltoall": _body_alltoall,
    "alltoallv": _body_alltoallv,
}


# ---------------------------------------------------------------------------
# Builder + cache
# ---------------------------------------------------------------------------

_cache: dict = {}

# AOT plan cache (Session.precompile_collectives, MLSL_PRECOMPILE): records
# which collective programs were already warm-executed at commit, keyed by the
# same (kind, group key, dtype/count, compression) identity the program caches
# use, so a second session over the same graph shapes skips the replay. Must
# clear together with _cache: a cleared program cache means fresh jitted fns
# whose dispatch caches are cold again, so a stale plan entry would silently
# skip re-warming them — any caller of clear_cache() gets both or neither.
_plan_cache: dict = {}


def clear_cache() -> None:
    _cache.clear()
    _plan_cache.clear()


class _ChaosDispatch:
    """Wraps a compiled collective so every invocation passes the
    'collective.dispatch' chaos site (one armed-check when idle — the
    injection point for hangs/faults at the XLA launch layer, which the
    request watchdog and FaultTolerantLoop must survive). Attribute access
    (lower/compile/...) delegates to the underlying jitted fn."""

    __slots__ = ("_fn", "_kind")

    def __init__(self, fn: Callable, kind: str):
        self._fn = fn
        self._kind = kind

    def __call__(self, *bufs):
        from mlsl_tpu import chaos

        if chaos._plans:
            chaos.inject("collective.dispatch", kind=self._kind)
            # elastic-mesh fault: an armed device.lost plan raises
            # MLSLDeviceLossError here — the dispatch is where a vanished
            # peer actually surfaces (the collective cannot complete), and
            # the supervisor routes it to the reshard rung, never a breaker.
            # 'silent' plans are elastic grow's (the rejoiner corruption);
            # firing them here would burn their budget before grow polls
            chaos.inject("device.lost", kinds=("error", "delay", "hang"),
                         kind=self._kind)
        return self._fn(*bufs)

    @property
    def _mlsl_inner(self):
        """The wrapped jit fn, for the precompile warm (request._unwrap_chaos):
        warming must not pass the chaos site."""
        return self._fn

    def __getattr__(self, name):
        return getattr(self._fn, name)


def _chaos_dispatch(fn: Callable, kind: str) -> Callable:
    return _ChaosDispatch(fn, kind)


def build_plain_fallback(kind: str, group: ProcessGroup, count: int) -> Callable:
    """The always-correct float32 program a degraded compressed request falls
    back to (supervisor rung 3): the same cached ``build_collective`` SUM
    program the uncompressed path would have used — bit-for-bit the plain
    request's program, which is what the degraded-path parity contract pins
    against. float32 because the compressed families deliver float32
    regardless of the entry dtype (the ring casts at entry), so the degraded
    result dtype matches the healthy one."""
    kw = {"op": ReductionType.SUM}
    if kind == "reduce_scatter":
        g = 1 if group.is_self else group.size
        kw["recv_count"] = count // g
    return build_collective(kind, group, np.float32, **kw)


def _group_key(group: ProcessGroup):
    # Stable identity: mesh shape + device ids (NOT id(mesh) — a GC'd mesh's address
    # can be reused by a different mesh, which would alias cache entries).
    mesh = group.topology.mesh
    dev_ids = tuple(int(d.id) for d in mesh.devices.flat)
    return (mesh.devices.shape, dev_ids, group.axes, group.colors)


def build_collective(kind: str, group: ProcessGroup, dtype, **kw) -> Callable:
    """Return a compiled fn: global buffer (R,D,M,n) -> global result buffer.

    Static kwargs per kind: op, root, recv_count, send_count, recv_counts (tuple),
    send_counts/send_offsets/recv_offsets/recv_len (alltoallv).
    """
    key = (kind, _group_key(group), np.dtype(dtype).str, tuple(sorted(kw.items())))
    fn = _cache.get(key)
    if fn is not None:
        return fn

    topo = group.topology
    mesh = topo.mesh
    sizes = _axis_sizes(mesh)

    if kind == "alltoallv" and "Sw" in kw and group.is_uniform:
        # per-world-rank count/offset rows -> per-instance (W, G, G) tables
        kw = _per_rank_alltoallv_tables(group, dict(kw))

    if group.is_self or (group.colors is None and sizes_prod(group.axes, sizes) == 1):
        # Single-member group: every collective is the identity (or local reshape).
        if kind == "alltoallv" and "S_tab" in kw:
            # per-rank mode on a 1-member group: a local repack (each rank moves
            # its own soff-segment to its roff slot)
            def body(x, _kw=kw):
                return _alltoallv_per_rank(
                    x[None], _group_rank(ALL_AXES, sizes), jnp.int32(0),
                    x.dtype, _kw["S_tab"], _kw["Soff_tab"], _kw["Roff_tab"],
                    _kw["recv_len"], _kw["lmax"],
                )
        else:
            def body(x, _kind=kind, _kw=kw):
                if _kind == "alltoallv":
                    return x[: _kw["recv_len"]]
                if _kind in ("scatter", "reduce_scatter"):
                    return x[: _kw["recv_count"]]
                if _kind == "allgatherv":
                    return x[: _kw["recv_counts"][0]]
                return x

    elif group.colors is not None:
        if group.is_uniform:
            fn = _chaos_dispatch(
                _build_flat(
                    _make_subgroup_body(kind, _color_groups_tbl(group), **kw),
                    topo, kind, "color",
                ),
                kind,
            )
            _cache[key] = fn
            return fn
        body = _make_ragged_body(
            kind, group, op=kw.get("op"), root=kw.get("root"),
            pairs=kw.get("pairs"), recv_count=kw.get("recv_count"),
            send_count=kw.get("send_count"),
        )
    elif kind in ("alltoall", "sendrecv") and len(group.axes) > 1:
        # multi-axis groups have no single named axis for the native op; compile
        # against the flat world mesh with explicit subgroup rows instead of the
        # O(G*n) gather+select emulation
        fn = _chaos_dispatch(
            _build_flat(
                _make_subgroup_body(kind, _axis_groups_tbl(group), **kw),
                topo, kind, group.axes,
            ),
            kind,
        )
        _cache[key] = fn
        return fn
    else:
        raw = _AXIS_BODIES[kind]
        body = functools.partial(raw, axes=group.axes, sizes=sizes, **kw)

    fn = _chaos_dispatch(
        _build_axis(body, mesh, kind, group.axes or "color"), kind
    )
    _cache[key] = fn
    return fn


def _build_axis(body, mesh, kind: str, tag) -> Callable:
    """Compile a squeezed-local (n,) -> (out_n,) body over the 4-axis grid mesh,
    accepting/returning the standard (R, D, S, M, n) distributed buffer — the
    axis-aligned counterpart of _build_flat, shared with the algorithm engine
    (comm/algos)."""

    def local_fn(x):  # x: (1, 1, 1, 1, n)
        # named_scope puts the collective's identity on the DEVICE timeline (the
        # host-side TraceAnnotation in CommRequest only covers the async enqueue)
        with jax.named_scope(f"mlsl_{kind}_{tag}"):
            out = body(x.reshape(x.shape[NUM_GRID_AXES:]))
        return out[None, None, None, None]

    sm = _shard_map(local_fn, mesh=mesh, in_specs=_BUF_SPEC, out_specs=_BUF_SPEC)
    return jax.jit(sm)


def _build_flat(body, topo, kind: str, tag) -> Callable:
    """Compile a (n,) -> (out_n,) body over the flattened single-axis world mesh,
    accepting/returning the standard (R, D, S, M, n) distributed buffer (the
    reshape is layout-compatible: device p holds rank p's row in both)."""
    w = topo.world_size
    grid = topo.grid_shape

    def local_fn(x):  # x: (1, n)
        with jax.named_scope(f"mlsl_{kind}_{tag}"):
            out = body(x.reshape(x.shape[1:]))
        return out[None]

    sm = _shard_map(
        local_fn, mesh=topo.flat_mesh,
        in_specs=P("world", None), out_specs=P("world", None),
    )

    def fn(buf):
        out = sm(buf.reshape(w, buf.shape[-1]))
        return out.reshape(*grid, out.shape[-1])

    return jax.jit(fn)


def build_stateful_collective(body, mesh) -> Callable:
    """Compile a (local_x, local_err) -> (local_out, local_new_err) body into a
    jitted shard_map over distributed buffers — the shared scaffolding for the
    error-feedback compressed collectives (int8 ring, top-k sparse).

    check=False: compressed bodies may contain pallas_call, whose outputs carry no
    VMA annotation."""
    from mlsl_tpu.comm.mesh import NUM_GRID_AXES

    def local_fn(x, e):
        out, new_err = body(
            x.reshape(x.shape[NUM_GRID_AXES:]), e.reshape(e.shape[NUM_GRID_AXES:])
        )
        return out[None, None, None, None], new_err[None, None, None, None]

    sm = smap(
        local_fn,
        mesh,
        in_specs=(_BUF_SPEC, _BUF_SPEC),
        out_specs=(_BUF_SPEC, _BUF_SPEC),
        check=False,
    )
    return jax.jit(sm)


def build_barrier(group: ProcessGroup) -> Callable:
    """A tiny psum over the group; Wait-ing its result is the barrier
    (reference Distribution::Barrier src/mlsl.cpp; EP backend uses MPI_Barrier)."""
    key = ("barrier", _group_key(group))
    fn = _cache.get(key)
    if fn is None:
        if group.colors is not None or not group.axes:
            axes = ALL_AXES
        else:
            axes = group.axes

        def local_fn(x):
            return lax.psum(x, axes)[None, None, None, None]

        topo = group.topology
        sm = _shard_map(
            lambda x: local_fn(x.reshape(x.shape[NUM_GRID_AXES:])),
            mesh=topo.mesh,
            in_specs=_BUF_SPEC,
            out_specs=_BUF_SPEC,
        )
        fn = _chaos_dispatch(jax.jit(sm), "barrier")
        _cache[key] = fn
    return fn
