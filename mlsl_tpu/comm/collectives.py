"""The collective library: cached jit-compiled shard_map programs over the mesh.

This replaces the reference's per-backend collective dispatch (src/comm_ep.cpp:768-1378,
src/comm_handoff.cpp:491-564). Design:

- A "distributed buffer" is one global jax.Array of shape (R, D, S, M, n): the
  (r, d, s, m) slice is that rank's local buffer (what each MPI rank would hold;
  S = sequence-parallel axis, 1 unless seq_parts is used). Collectives are
  pure functions global-buffer -> global-buffer, built with ``shard_map`` so XLA sees
  the per-device program and lowers group operations onto ICI collectives.

- Axis-aligned groups use native XLA collective ops (psum / psum_scatter / all_gather /
  all_to_all) — the fast path, equivalent to how the reference leans on MPI's optimized
  collectives rather than hand-rolling (eplib routes to PMPI_I* in cqueue.c:1906-2026).

- Color groups (arbitrary MPI_Comm_split-style subgroups, reference
  src/mlsl.cpp:620-647) and exotic shapes (AlltoAllv) fall back to a gather+mask
  emulation: correct everywhere, efficient enough for cold paths.

- Every built program is cached per (kind, group, count(s), dtype, op, root) — the
  analog of the reference caching CommRequests per graph edge, and the key to the perf
  target: the hot loop re-dispatches an already-compiled XLA executable with zero
  retracing.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:  # JAX >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from mlsl_tpu.comm.mesh import GRID_AXES, NUM_GRID_AXES, ProcessGroup
from mlsl_tpu.log import mlsl_assert
from mlsl_tpu.types import ReductionType

ALL_AXES = GRID_AXES
_BUF_SPEC = P(*GRID_AXES, None)


def smap(f, mesh, in_specs, out_specs, check: bool = True):
    """shard_map with a version-compatible way to disable VMA/replication checking
    (needed when out_specs claim replication the compiler can't prove, or when the
    body contains pallas_call, whose outputs carry no vma annotation)."""
    if check:
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    for kw in ({"check_vma": False}, {"check_rep": False}):
        try:
            return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
        except TypeError:
            continue
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def _axis_sizes(mesh) -> dict:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def _group_rank(axes: Sequence[str], sizes: dict):
    """Flattened member index over ``axes`` (major -> minor), as a traced value."""
    idx = jnp.int32(0)
    for a in axes:
        idx = idx * sizes[a] + lax.axis_index(a)
    return idx


def _gather_group(x, axes: Sequence[str]):
    """Local (n, ...) -> (G, n, ...): every member's block, in group-rank order.

    Built from nested tiled all_gathers (minor axis first) so multi-axis groups work on
    every JAX version; XLA fuses the nest into one gather on a single axis.
    """
    y = x[None]
    for a in reversed(tuple(axes)):
        y = lax.all_gather(y, a, axis=0, tiled=True)
    return y


def _reduce_local(vals, op: ReductionType, axis=0):
    if op == ReductionType.SUM:
        return jnp.sum(vals, axis=axis)
    if op == ReductionType.MIN:
        return jnp.min(vals, axis=axis)
    return jnp.max(vals, axis=axis)


def _preduce(x, axes, op: ReductionType):
    axes = tuple(axes)
    if op == ReductionType.SUM:
        return lax.psum(x, axes)
    if op == ReductionType.MIN:
        return lax.pmin(x, axes)
    return lax.pmax(x, axes)


# ---------------------------------------------------------------------------
# Local (per-shard) collective bodies. Each takes the squeezed local buffer
# (shape (n,)) and returns the squeezed local result.
# ---------------------------------------------------------------------------


def _body_allreduce(x, *, axes, sizes, op, **_):
    return _preduce(x, axes, op)


def _body_reduce(x, *, axes, sizes, op, root, **_):
    # MPI semantics: result meaningful only at root. Returning the reduction on every
    # member is a strict superset and lets XLA use the same allreduce lowering.
    return _preduce(x, axes, op)


def _body_bcast(x, *, axes, sizes, root, **_):
    members = _gather_group(x, axes)
    return members[root]


def _body_allgather(x, *, axes, sizes, **_):
    g = _gather_group(x, axes)           # (G, n)
    return g.reshape((-1,) + x.shape[1:])


def _body_allgatherv(x, *, axes, sizes, recv_counts, **_):
    g = _gather_group(x, axes)           # (G, maxcount)
    parts = [g[i, : recv_counts[i]] for i in range(len(recv_counts))]
    return jnp.concatenate(parts, axis=0)


def _body_gather(x, *, axes, sizes, root, **_):
    # Root-only semantics; full concatenation returned on every member (superset).
    return _body_allgather(x, axes=axes, sizes=sizes)


def _body_scatter(x, *, axes, sizes, root, recv_count, **_):
    members = _gather_group(x, axes)     # (G, G*recv_count)
    me = _group_rank(axes, sizes)
    return lax.dynamic_slice_in_dim(members[root], me * recv_count, recv_count, axis=0)


def _body_reduce_scatter(x, *, axes, sizes, op, recv_count, **_):
    if op == ReductionType.SUM and len(axes) == 1:
        return lax.psum_scatter(x, axes[0], scatter_dimension=0, tiled=True)
    red = _preduce(x, axes, op)          # (G*recv_count,)
    me = _group_rank(axes, sizes)
    return lax.dynamic_slice_in_dim(red, me * recv_count, recv_count, axis=0)


def _body_sendrecv(x, *, axes, sizes, pairs, **_):
    """Neighbor/point-to-point exchange list: member src -> member dst for each
    (src, dst) pair; members not receiving get zeros.

    Implements the reference's declared-but-unimplemented SendRecvList CommOp
    (src/comm.hpp:212-248) — on TPU this IS lax.ppermute, whose transfers ride the
    ICI neighbor links directly.
    """
    if len(axes) == 1:
        return lax.ppermute(x, axes[0], [(int(s), int(d)) for s, d in pairs])
    g = _gather_group(x, axes)           # (G, n)
    me = _group_rank(axes, sizes)
    out = jnp.zeros_like(x)
    for s, d in pairs:
        out = jnp.where(me == d, g[int(s)], out)
    return out


def _body_alltoall(x, *, axes, sizes, send_count, **_):
    if len(axes) == 1:
        return lax.all_to_all(x, axes[0], split_axis=0, concat_axis=0, tiled=True)
    g = sizes_prod(axes, sizes)
    blocks = _gather_group(x.reshape(g, send_count), axes)  # (G, G, send_count)
    me = _group_rank(axes, sizes)
    mine = lax.dynamic_index_in_dim(blocks, me, axis=1, keepdims=False)  # (G, count)
    return mine.reshape(g * send_count)


def _body_alltoallv(x, *, axes, sizes, S, Soff, Roff, recv_len, **_):
    """Emulated AlltoAllv with full static count matrices (MPI semantics).

    S[i][j] = elements rank i sends to member j; Soff[i][j] = offset of that segment in
    i's send buffer; Roff[i][j] = offset in i's receive buffer where data from j lands.
    The reference expresses this with per-rank count arrays passed to pairwise
    Isend/Irecv (src/comm_ep.cpp:1188-1265); SPMD needs the whole matrix statically.
    Segment lengths vary per (j, me) pair, so slices use a static max length with a
    validity mask.
    """
    g = len(S)
    g_members = _gather_group(x, axes)   # (G, send_len)
    me = _group_rank(axes, sizes)
    s_m = jnp.asarray(S, dtype=jnp.int32)
    soff_m = jnp.asarray(Soff, dtype=jnp.int32)
    roff_m = jnp.asarray(Roff, dtype=jnp.int32)
    lmax = int(np.max(S)) if np.max(S) > 0 else 1
    pos = jnp.arange(lmax)
    pad = jnp.zeros((lmax,), dtype=x.dtype)
    out = jnp.zeros((recv_len + lmax,), dtype=x.dtype)
    for j in range(g):
        cnt = s_m[j, me]
        src = lax.dynamic_slice_in_dim(
            jnp.concatenate([g_members[j], pad]), soff_m[j, me], lmax, axis=0
        )
        roff = roff_m[me, j]
        window = lax.dynamic_slice_in_dim(out, roff, lmax, axis=0)
        merged = jnp.where(pos < cnt, src, window)
        out = lax.dynamic_update_slice_in_dim(out, merged, roff, axis=0)
    return out[:recv_len]


def sizes_prod(axes, sizes) -> int:
    g = 1
    for a in axes:
        g *= sizes[a]
    return g


# ---------------------------------------------------------------------------
# Color-group (arbitrary subgroup) bodies: world-gather + static member tables.
# ---------------------------------------------------------------------------


def _color_tables(group: ProcessGroup):
    """(member_matrix (W,G): row p = world ranks of p's group in order;
    my_pos (W,): p's index within its group)."""
    w = group.topology.world_size
    g = group.size
    member = np.zeros((w, g), dtype=np.int32)
    pos = np.zeros((w,), dtype=np.int32)
    for p in range(w):
        ranks = group.member_world_ranks(group.colors[p])
        member[p] = ranks
        pos[p] = ranks.index(p)
    return member, pos


def _make_color_body(kind: str, group: ProcessGroup, *, op=None, root=None,
                     recv_count=None, pairs=None):
    member_np, pos_np = _color_tables(group)
    sizes = _axis_sizes(group.topology.mesh)

    def body(x):
        full = _gather_group(x, ALL_AXES)                      # (W, n)
        me = _group_rank(ALL_AXES, sizes)                      # world rank
        members = jnp.take(jnp.asarray(member_np), me, axis=0)  # (G,)
        vals = jnp.take(full, members, axis=0)                  # (G, n)
        if kind in ("allreduce", "reduce"):
            return _reduce_local(vals, op)
        if kind == "bcast":
            return vals[root]
        if kind in ("allgather", "gather"):
            return vals.reshape(-1)
        if kind == "scatter":
            mypos = jnp.take(jnp.asarray(pos_np), me)
            return lax.dynamic_slice_in_dim(
                vals[root], mypos * recv_count, recv_count, axis=0
            )
        if kind == "reduce_scatter":
            red = _reduce_local(vals, op)                      # (G*recv_count,)
            mypos = jnp.take(jnp.asarray(pos_np), me)
            return lax.dynamic_slice_in_dim(red, mypos * recv_count, recv_count, axis=0)
        if kind == "alltoall":
            g = member_np.shape[1]
            mypos = jnp.take(jnp.asarray(pos_np), me)
            blocks = vals.reshape(g, g, -1)                    # (G, G, count)
            mine = lax.dynamic_index_in_dim(blocks, mypos, axis=1, keepdims=False)
            return mine.reshape(-1)
        if kind == "sendrecv":
            mypos = jnp.take(jnp.asarray(pos_np), me)
            out = jnp.zeros_like(x)
            for s, d in pairs:
                out = jnp.where(mypos == d, vals[int(s)], out)
            return out
        raise NotImplementedError(kind)

    return body


_AXIS_BODIES = {
    "sendrecv": _body_sendrecv,
    "allreduce": _body_allreduce,
    "reduce": _body_reduce,
    "bcast": _body_bcast,
    "allgather": _body_allgather,
    "allgatherv": _body_allgatherv,
    "gather": _body_gather,
    "scatter": _body_scatter,
    "reduce_scatter": _body_reduce_scatter,
    "alltoall": _body_alltoall,
    "alltoallv": _body_alltoallv,
}


# ---------------------------------------------------------------------------
# Builder + cache
# ---------------------------------------------------------------------------

_cache: dict = {}


def clear_cache() -> None:
    _cache.clear()


def _group_key(group: ProcessGroup):
    # Stable identity: mesh shape + device ids (NOT id(mesh) — a GC'd mesh's address
    # can be reused by a different mesh, which would alias cache entries).
    mesh = group.topology.mesh
    dev_ids = tuple(int(d.id) for d in mesh.devices.flat)
    return (mesh.devices.shape, dev_ids, group.axes, group.colors)


def build_collective(kind: str, group: ProcessGroup, dtype, **kw) -> Callable:
    """Return a compiled fn: global buffer (R,D,M,n) -> global result buffer.

    Static kwargs per kind: op, root, recv_count, send_count, recv_counts (tuple),
    send_counts/send_offsets/recv_offsets/recv_len (alltoallv).
    """
    key = (kind, _group_key(group), np.dtype(dtype).str, tuple(sorted(kw.items())))
    fn = _cache.get(key)
    if fn is not None:
        return fn

    topo = group.topology
    mesh = topo.mesh
    sizes = _axis_sizes(mesh)

    if group.is_self or (group.colors is None and sizes_prod(group.axes, sizes) == 1):
        # Single-member group: every collective is the identity (or local reshape).
        def body(x, _kind=kind, _kw=kw):
            if _kind == "alltoallv":
                return x[: _kw["recv_len"]]
            if _kind in ("scatter", "reduce_scatter"):
                return x[: _kw["recv_count"]]
            if _kind == "allgatherv":
                return x[: _kw["recv_counts"][0]]
            return x

    elif group.colors is not None:
        body = _make_color_body(
            kind,
            group,
            op=kw.get("op"),
            root=kw.get("root"),
            recv_count=kw.get("recv_count"),
            pairs=kw.get("pairs"),
        )
    else:
        raw = _AXIS_BODIES[kind]
        body = functools.partial(raw, axes=group.axes, sizes=sizes, **kw)

    def local_fn(x):  # x: (1, 1, 1, 1, n)
        # named_scope puts the collective's identity on the DEVICE timeline (the
        # host-side TraceAnnotation in CommRequest only covers the async enqueue)
        with jax.named_scope(f"mlsl_{kind}_{group.axes or 'color'}"):
            out = body(x.reshape(x.shape[NUM_GRID_AXES:]))
        return out[None, None, None, None]

    sm = _shard_map(local_fn, mesh=mesh, in_specs=_BUF_SPEC, out_specs=_BUF_SPEC)
    fn = jax.jit(sm)
    _cache[key] = fn
    return fn


def build_stateful_collective(body, mesh) -> Callable:
    """Compile a (local_x, local_err) -> (local_out, local_new_err) body into a
    jitted shard_map over distributed buffers — the shared scaffolding for the
    error-feedback compressed collectives (int8 ring, top-k sparse).

    check=False: compressed bodies may contain pallas_call, whose outputs carry no
    VMA annotation."""
    from mlsl_tpu.comm.mesh import NUM_GRID_AXES

    def local_fn(x, e):
        out, new_err = body(
            x.reshape(x.shape[NUM_GRID_AXES:]), e.reshape(e.shape[NUM_GRID_AXES:])
        )
        return out[None, None, None, None], new_err[None, None, None, None]

    sm = smap(
        local_fn,
        mesh,
        in_specs=(_BUF_SPEC, _BUF_SPEC),
        out_specs=(_BUF_SPEC, _BUF_SPEC),
        check=False,
    )
    return jax.jit(sm)


def build_barrier(group: ProcessGroup) -> Callable:
    """A tiny psum over the group; Wait-ing its result is the barrier
    (reference Distribution::Barrier src/mlsl.cpp; EP backend uses MPI_Barrier)."""
    key = ("barrier", _group_key(group))
    fn = _cache.get(key)
    if fn is None:
        if group.colors is not None or not group.axes:
            axes = ALL_AXES
        else:
            axes = group.axes

        def local_fn(x):
            return lax.psum(x, axes)[None, None, None, None]

        topo = group.topology
        sm = _shard_map(
            lambda x: local_fn(x.reshape(x.shape[NUM_GRID_AXES:])),
            mesh=topo.mesh,
            in_specs=_BUF_SPEC,
            out_specs=_BUF_SPEC,
        )
        fn = jax.jit(sm)
        _cache[key] = fn
    return fn
