"""User-pluggable compression codecs for the quantized collectives.

The reference's quantization component is defined by its *pluggability*: the user
names a shared library and three symbols (compress / decompress / reduce_sum) and
MLSL dlopens it and wires the codec into the allreduce — quantize before the wire,
custom reduction on compressed blocks, dequantize after (reference
quant/quant.c:96-133, invoked around the reduce in eplib/cqueue.c:1977-1994).

Two TPU-native plug-in forms, registered via ``Environment.set_quantization_params``:

1. **Jittable Python callables** (`QuantParams.compress_fn/decompress_fn/
   reduce_sum_fn`) — traced into the compiled ring collective, so a user codec runs
   on-device at full speed. This is the idiomatic TPU form of "dlopen a codec".
2. **A shared library** (`QuantParams.lib_path` + symbol names, the reference's
   exact contract incl. the dl_comp-style ABI quant/quant.c:57-65) — loaded with
   ctypes and bridged into the collective via `jax.pure_callback`. Host codecs
   round-trip device->host per hop, so this path is for compatibility (ported
   programs, CPU mesh), not peak ICI bandwidth — the reference's codec is likewise
   host CPU code running in the endpoint servers.

Since the codec-lab PR this transport ALSO carries the registry codecs
(``mlsl_tpu.codecs``): a registered ``Codec`` wraps itself into a
:class:`CustomCodec` via ``Codec.as_custom()`` (compress=encode,
decompress=decode, reduce=the optional compressed-domain ``aggregate``), so
vq/prune/f32 ride the same compressed-ring programs, entry error feedback,
and chaos-roundtrip wrapper as a user dlopen codec — one wire family, three
front doors (registry name, Python callables, shared library).

Error feedback is functional and framework-owned in both forms: the residual
``err' = (x + err) - decompress(compress(x + err))`` is carried per request
(CommRequest._err), matching quant_quantize's per-buffer diff semantics
(quant/quant.c:153-211) without hidden codec state.
"""

from __future__ import annotations

import ctypes
import dataclasses
from typing import Callable, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mlsl_tpu.log import MLSLError, mlsl_assert
from mlsl_tpu.comm.mesh import ProcessGroup


@dataclasses.dataclass(frozen=True, eq=False)
class CustomCodec:
    """A pluggable codec: ``compress(f32[n]) -> payload`` (any pytree of arrays
    with shapes determined by n), ``decompress(payload, n) -> f32[n]``, and an
    optional compressed-domain ``reduce(a_payload, b_payload) -> payload`` (the
    reference's reduce_sum custom MPI op). Without ``reduce``, ring hops
    decompress-add — numerically identical to what dl_comp-style reduce_sum does
    internally.

    ``_programs`` caches the compiled collectives ON the codec instance, so
    replacing a registration (config.custom_codec reassigned) drops the old
    codec's traced executables with it — no process-lifetime growth."""

    compress: Callable
    decompress: Callable
    reduce: Optional[Callable] = None
    name: str = "custom"
    _programs: dict = dataclasses.field(default_factory=dict, repr=False)


# -- library (dlopen) codecs -------------------------------------------------

# dl_comp-style constants (reference quant/quant.c:43-55, passed at :199)
_DL_COMP_FLOAT32 = 2
_DL_COMP_DFP = 1
_COMP_RATIO = 4


def load_library_codec(params) -> CustomCodec:
    """dlopen `params.lib_path`, resolve the three symbols named in ``params``
    (reference quant_load, quant/quant.c:96-133), and wrap them as pure_callback
    host functions. Raises MLSLError loudly on any load/resolve failure — never
    silently ignores a requested codec."""
    mlsl_assert(params.lib_path, "QuantParams.lib_path is empty")
    names = (
        params.quant_buffer_func_name,
        params.dequant_buffer_func_name,
        params.reduce_sum_func_name,
    )
    mlsl_assert(
        all(names),
        "QuantParams with lib_path must name quant/dequant/reduce_sum functions",
    )
    try:
        lib = ctypes.CDLL(params.lib_path)
    except OSError as e:
        raise MLSLError(f"quantization library can't be opened: {e}") from e
    try:
        quant_c = getattr(lib, names[0])
        dequant_c = getattr(lib, names[1])
        reduce_c = getattr(lib, names[2])
    except AttributeError as e:
        raise MLSLError(f"quantization symbol can't be loaded: {e}") from e

    # reference ABI (quant/quant.c:57-65)
    quant_c.restype = ctypes.c_int
    quant_c.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_size_t, ctypes.c_int,
    ]
    dequant_c.restype = ctypes.c_int
    dequant_c.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    reduce_c.restype = ctypes.c_int
    reduce_c.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]

    elem = int(params.elem_in_block)
    bsz = int(params.block_size)
    mlsl_assert(elem > 0 and bsz > 0, "block geometry must be positive")

    def _nblocks(n: int) -> int:
        return -(-n // elem)

    # The staging buffers are sized from the DECLARED geometry (block_size
    # bytes written per elem_in_block-element block). The codec is opaque, so
    # a mismatched declaration would otherwise corrupt the heap silently.
    # Two defenses: a one-shot calibration probe at load time measures the
    # codec's actual output footprint on a single block against the declared
    # block_size (registration fails loudly on mismatch), and a sentinel guard
    # tail on every staging buffer catches small count-dependent spills.
    _GUARD = 64

    def _probe_geometry() -> None:
        # Slack is INPUT-derived (8 B/element covers a pathological 2x
        # expansion over raw f32), never declared-output-derived: an
        # optimistic declared block_size must not under-size the probe buffer
        # the probe exists to protect.
        slack = elem * 8 + bsz + 4096
        buf = np.linspace(-1.0, 1.0, elem, dtype=np.float32)
        diff = np.zeros(elem, np.float32)
        out = np.full(slack, 0xA5, np.uint8)
        rc = quant_c(
            buf.ctypes.data, out.ctypes.data, buf.size, diff.ctypes.data,
            _DL_COMP_FLOAT32, _COMP_RATIO, _DL_COMP_DFP,
        )
        if rc != 0:
            raise MLSLError(
                f"quantization library probe failed: error code {rc}"
            )
        touched = np.nonzero(out != 0xA5)[0]
        written = int(touched[-1]) + 1 if touched.size else 0
        if written > bsz:
            raise MLSLError(
                f"quantization library geometry mismatch: declared "
                f"block_size={bsz} bytes per {elem}-element block, but "
                f"{names[0]} wrote ~{written} bytes for one block — fix "
                f"QuantParams.block_size/elem_in_block to match the codec"
            )
        dout = np.full(elem * 4 + slack, 0xA5, np.uint8)
        rc = dequant_c(out.ctypes.data, dout.ctypes.data, elem)
        if rc != 0:
            raise MLSLError(
                f"dequantization library probe failed: error code {rc}"
            )
        dtouched = np.nonzero(dout != 0xA5)[0]
        dwritten = int(dtouched[-1]) + 1 if dtouched.size else 0
        if dwritten > elem * 4:
            raise MLSLError(
                f"quantization library geometry mismatch: {names[1]} wrote "
                f"~{dwritten} bytes decompressing one {elem}-element block "
                f"(expected at most {elem * 4})"
            )

    _probe_geometry()

    def _check_guard(arr: np.ndarray, payload_bytes: int, what: str) -> None:
        tail = arr.view(np.uint8)[payload_bytes:]
        if tail.size and not (tail == 0xA5).all():
            raise MLSLError(
                f"{what} wrote past the declared block geometry "
                f"(block_size={bsz}, elem_in_block={elem}): the codec must "
                f"write exactly block_size bytes per block of elem_in_block "
                f"elements"
            )

    def _host_compress(x: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, dtype=np.float32)
        n = x.size
        nb = _nblocks(n)
        buf = np.zeros(nb * elem, np.float32)
        buf[:n] = x
        # Feedback is framework-owned (applied to the input before this call),
        # so the codec's own diff buffer is zeroed per call.
        diff = np.zeros(nb * elem, np.float32)
        out = np.full(nb * bsz + _GUARD, 0xA5, np.uint8)
        out[: nb * bsz] = 0
        rc = quant_c(
            buf.ctypes.data, out.ctypes.data, buf.size, diff.ctypes.data,
            _DL_COMP_FLOAT32, _COMP_RATIO, _DL_COMP_DFP,
        )
        if rc != 0:
            raise MLSLError(f"quantization failed: error code {rc}")
        _check_guard(out, nb * bsz, f"compress ({names[0]})")
        return out[: nb * bsz]

    def _host_decompress(p: np.ndarray, n: int) -> np.ndarray:
        nb = _nblocks(n)
        out = np.zeros(nb * elem + _GUARD // 4, np.float32)
        out.view(np.uint8)[nb * elem * 4:] = 0xA5
        rc = dequant_c(
            np.ascontiguousarray(p).ctypes.data, out.ctypes.data, nb * elem
        )
        if rc != 0:
            raise MLSLError(f"dequantization failed: error code {rc}")
        _check_guard(out, nb * elem * 4, f"decompress ({names[1]})")
        return out[:n]

    def _host_reduce(a: np.ndarray, b: np.ndarray) -> np.ndarray:
        inout = np.ascontiguousarray(b).copy()
        rc = reduce_c(
            np.ascontiguousarray(a).ctypes.data, inout.ctypes.data,
            inout.size // bsz,
        )
        if rc != 0:
            raise MLSLError(f"compressed reduce failed: error code {rc}")
        return inout

    def compress(x):
        n = x.shape[-1]
        shape = jax.ShapeDtypeStruct((_nblocks(n) * bsz,), jnp.uint8)
        return jax.pure_callback(_host_compress, shape, x, vmap_method="sequential")

    def decompress(p, n: int):
        shape = jax.ShapeDtypeStruct((n,), jnp.float32)
        return jax.pure_callback(
            lambda q: _host_decompress(q, n), shape, p, vmap_method="sequential"
        )

    def reduce(a, b):
        shape = jax.ShapeDtypeStruct(a.shape, jnp.uint8)
        return jax.pure_callback(_host_reduce, shape, a, b, vmap_method="sequential")

    return CustomCodec(
        compress=compress, decompress=decompress, reduce=reduce,
        name=f"lib:{params.lib_path}",
    )


# -- the codec collective ----------------------------------------------------


# chunk placement shared with the built-in int8 ring — ONE copy of the
# ring-ownership math (slice j at the start of padded chunk j)
from mlsl_tpu.comm.quant_ring import _to_chunks  # noqa: E402


def _entry(codec, chunks, err2d, chunk):
    """Per-chunk compress/decompress with framework error feedback. Python loop
    over the (static) chunk count: host-callback codecs cannot be vmapped."""
    xhat_rows, err_rows = [], []
    for j in range(chunks.shape[0]):
        xq = chunks[j] + err2d[j]
        p = codec.compress(xq)
        xhat = codec.decompress(p, chunk)
        xhat_rows.append(xhat)
        err_rows.append(xq - xhat)
    return jnp.stack(xhat_rows), jnp.stack(err_rows)


def _ring_body(x, err, *, axis, G, rc, chunk, count, mode, codec):
    """Local shard body: ring reduce-scatter (+ all-gather) where every hop
    carries the codec's compressed payload (the wire-compression contract of the
    reference's MPI_QUANT_OP allreduce)."""
    chunks = _to_chunks(x.astype(jnp.float32), G, rc, chunk)
    chunks, new_err = _entry(codec, chunks, err.reshape(G, chunk), chunk)
    new_err = new_err.reshape(-1)

    me = lax.axis_index(axis)
    perm = [(i, (i + 1) % G) for i in range(G)]

    def send(payload):
        return jax.tree.map(lambda l: lax.ppermute(l, axis, perm), payload)

    # --- ring reduce-scatter over compressed wire ---
    partial = lax.dynamic_index_in_dim(chunks, (me - 1) % G, keepdims=False)
    for t in range(G - 1):
        local = lax.dynamic_index_in_dim(chunks, (me - 2 - t) % G, keepdims=False)
        p = send(codec.compress(partial))
        if codec.reduce is not None:
            # compressed-domain accumulation (the reference's reduce_sum op)
            p = codec.reduce(p, codec.compress(local))
            partial = codec.decompress(p, chunk)
        else:
            partial = codec.decompress(p, chunk) + local

    if mode == "reduce_scatter":
        return partial[:rc], new_err

    # --- ring all-gather over compressed wire ---
    own_p = codec.compress(partial)
    out = jnp.zeros((G, chunk), dtype=jnp.float32)
    out = lax.dynamic_update_index_in_dim(
        out, codec.decompress(own_p, chunk), me, axis=0
    )
    p = own_p
    for k in range(G - 1):
        p = send(p)
        val = codec.decompress(p, chunk)
        out = lax.dynamic_update_index_in_dim(out, val, (me - 1 - k) % G, axis=0)
    return out[:, :rc].reshape(-1)[:count], new_err


def build_custom_collective(
    kind: str, group: ProcessGroup, count: int, codec: CustomCodec
) -> Tuple[Callable, int]:
    """-> (compiled fn (buf, err) -> (result, new_err), error-feedback length).

    Same contract as quant_ring.build_quantized_collective, with the user codec
    on the wire. Single-axis groups ride the compressed ring; degenerate or
    multi-axis groups fall back to entry-compression + psum (feedback-identical
    numerics, uncompressed wire)."""
    from mlsl_tpu.comm.collectives import (
        _axis_sizes, _group_key, _group_rank, build_stateful_collective,
    )

    mlsl_assert(kind in ("allreduce", "reduce_scatter"),
                "custom codec supports allreduce/reduce_scatter (got %s)", kind)
    topo = group.topology
    mesh = topo.mesh
    sizes = _axis_sizes(mesh)
    g = 1 if group.is_self else group.size
    mlsl_assert(group.colors is None, "custom codec requires axis-aligned groups")

    if kind == "reduce_scatter":
        mlsl_assert(count % g == 0, "reduce_scatter count %d %% group %d != 0",
                    count, g)
        rc = count // g
    else:
        rc = -(-count // g)
    chunk = rc
    err_len = g * chunk

    per_codec = codec._programs
    key = (kind, _group_key(group), count)
    fn = per_codec.get(key)
    if fn is not None:
        return fn, err_len

    if g > 1 and len(group.axes) == 1:
        import functools

        body = functools.partial(
            _ring_body, axis=group.axes[0], G=g, rc=rc, chunk=chunk,
            count=count, mode=kind, codec=codec,
        )
    else:
        def body(x, err, _axes=group.axes, _g=g):
            chunks = _to_chunks(x.astype(jnp.float32), _g, rc, chunk)
            chunks, new_err = _entry(codec, chunks, err.reshape(_g, chunk), chunk)
            new_err = new_err.reshape(-1)
            red = lax.psum(chunks, _axes) if _axes and _g > 1 else chunks
            if kind == "reduce_scatter" and _g > 1:
                me = _group_rank(_axes, sizes)
                mine = lax.dynamic_index_in_dim(red, me, axis=0, keepdims=False)
                return mine[:rc], new_err
            if kind == "reduce_scatter":
                return red[0, :rc], new_err
            return red[:, :rc].reshape(-1)[:count], new_err

    fn = build_stateful_collective(body, mesh)
    per_codec[key] = fn
    return fn, err_len
