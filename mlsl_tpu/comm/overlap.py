"""Compiled overlap engine: in-graph per-layer gradient collectives.

The host per-layer path (models/train.py ``_sync_and_update``) dispatches one
XLA executable per layer collective and overlaps them with host polling —
which BENCH_r05 showed gains nothing over the fused monolithic jit on a real
chip (``per_layer_vs_fused: 1.0``): the comm schedule lives on the host,
where XLA's latency-hiding scheduler cannot see it. This module moves the
schedule INTO the compiled program (the PyTorch-DDP finding, PAPERS.md:
overlap only pays when the compiler/scheduler owns the comm stream):

- ONE single-dispatch, donation-enabled step program: local backward, then
  every layer's gradient collective emitted IN-GRAPH, newest-gradient-first,
  interleaved with the remaining layers' update work so XLA can overlap ICI
  DMA with compute instead of the host poll loop faking it.
- Each collective is composed from the existing algos-engine lowerings
  (comm/algos): ``lax`` psum, the ``rhd`` ppermute round sequence, the
  ``ring2d`` ring phases — via their staged ``steps``/``inline_plan`` forms,
  so the in-graph rounds are op-for-op the standalone programs (bit-exact
  parity on integer payloads, tests/test_overlap_compiled.py).
- The schedule is STAGED: a unit's reduce phases are spread over the next
  ``stages`` unit-starts (``MLSL_OVERLAP_STAGES``; tunable via the tuner
  profile's ``overlap_stages`` knob), and each stage boundary is pinned with
  ``lax.optimization_barrier`` so the emitted interleaving survives into the
  scheduled program instead of collapsing into one tail.
- Quantized sets ride an in-graph quantize -> int8 ring -> dequantize
  (quant_ring.inline_body — the same geometry/body as the host request) with
  the error-feedback residual threaded through the step carry: residual
  buffers are trainer state, donated every step.
- Small uncompressed layers coalesce into in-graph buckets under
  ``MLSL_GRAD_BUCKET_MB`` using the SAME packing policy as the host buckets
  (core/bucketing.pack_by_size).

Selection precedence per unit is the PR 4 table unchanged
(``MLSL_ALGO`` > tuned profile > ``lax`` baseline; comm/algos.select), with
an in-graph eligibility gate on top (color-group graphs cannot be served
in-graph at all — their axes are ``()`` — and ride the host path).

The host path stays the default and the parity oracle; the engine arms via
``MLSL_OVERLAP_COMPILED=1`` / ``DataParallelTrainer(overlap_compiled=True)``.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from mlsl_tpu import chaos
from mlsl_tpu.comm import algos
from mlsl_tpu.comm.collectives import (
    _BUF_SPEC, _axis_sizes, _body_allgather, _group_rank, smap,
)
from mlsl_tpu.comm.mesh import NUM_GRID_AXES, ProcessGroup
from mlsl_tpu.core import stats as stats_mod
from mlsl_tpu.log import log_debug, mlsl_assert
from mlsl_tpu.obs import tracer as obs
from mlsl_tpu.types import CompressionType, DataType, ReductionType

DEFAULT_STAGES = 2


# ---------------------------------------------------------------------------
# Plan: what gets reduced, how, in what order
# ---------------------------------------------------------------------------


class OverlapUnit:
    """One in-graph reduction unit: a single layer, or a bucket of small
    consecutive (newest-first) uncompressed layers coalesced into one
    collective. Phase closures are built once at plan time; they trace into
    the step program when the engine compiles."""

    def __init__(self, names: Tuple[str, ...], counts: Tuple[int, ...],
                 compression: CompressionType, algo: str,
                 group: ProcessGroup, *, index: int, block: int, dtype=None,
                 config=None):
        self.names = tuple(names)
        self.counts = tuple(int(c) for c in counts)
        self.total = sum(self.counts)
        self.compression = compression
        self.algo = algo
        self.index = index
        self.key: Optional[str] = None  # residual-state key (quant units)
        self.err_len = 0
        self.per_tick = 1  # phases advanced per scheduler tick (set by plan)
        self._quant_staged = False
        if compression == CompressionType.QUANTIZATION:
            if algo == "hier":
                # the table routed this unit's compressed wire through the
                # two-tier decomposition: staged phases (the ICI
                # reduce-scatter emits early, the compressed DCN hop is its
                # own phase — the natural stage boundary — the ICI
                # all-gather last), error feedback threaded through the
                # carry exactly like the flat inline body
                from mlsl_tpu.comm.algos import hier

                (self._qprep, self._phases,
                 self._qfinish, self.err_len) = hier.quant_steps(
                    group, self.total, block,
                    codec=getattr(config, "hier_dcn_codec", None),
                    topk_ratio=float(getattr(config, "topk_ratio", 0.01)),
                )
                self._quant_staged = True
                self.key = f"q{index}/{self.names[0]}"
                self.nphases = len(self._phases)
                self.algo = "hier"
                return
            from mlsl_tpu.comm import quant_ring

            self._body, self.err_len = quant_ring.inline_body(
                "allreduce", group, self.total, block
            )
            self.key = f"q{index}/{self.names[0]}"
            self.nphases = 1
            # attribution names the wire family, like the host request's
            # .algo — the ALGO stats line must not show quant rounds as lax
            self.algo = "quant_ring"
        else:
            self._prep, self._phases, self._finish = algos.inline_plan(
                "allreduce", group, algo, self.total, op=ReductionType.SUM,
                config=config,
            )
            # may be 0: a degenerate (single-member) group reduces nothing —
            # the unit retires at its first tick straight through finish()
            self.nphases = len(self._phases)

    # -- trace-time interface (called inside the shard_map body) -----------

    def prep(self, flat: Dict[str, jax.Array], mypos, err):
        x = (
            jnp.concatenate([flat[n] for n in self.names])
            if len(self.names) > 1
            else flat[self.names[0]]
        )
        if self._quant_staged:
            return self._qprep(x, mypos, err)
        if self.compression == CompressionType.QUANTIZATION:
            return (x, err)
        return self._prep(x, mypos)

    def advance(self, carry, i: int):
        if self.compression == CompressionType.QUANTIZATION \
                and not self._quant_staged:
            return self._body(*carry)
        return self._phases[i](carry)

    def finish(self, carry) -> Tuple[Dict[str, jax.Array], Optional[jax.Array]]:
        """-> ({member name -> reduced flat slice}, new residual or None)."""
        if self._quant_staged:
            out, new_err = self._qfinish(carry)
        elif self.compression == CompressionType.QUANTIZATION:
            out, new_err = carry
        else:
            out, new_err = self._finish(carry), None
        parts: Dict[str, jax.Array] = {}
        off = 0
        for n, c in zip(self.names, self.counts):
            parts[n] = out[off:off + c] if len(self.names) > 1 else out
            off += c
        return parts, new_err


class OverlapPlan:
    """The compiled-overlap schedule for one trainer/graph: units in
    newest-gradient-first start order, plus the bookkeeping stats/trace
    attribution reads."""

    def __init__(self, group: ProcessGroup, units: List[OverlapUnit],
                 stages: int, data_type: DataType = DataType.FLOAT):
        self.group = group
        self.units = units
        self.stages = max(int(stages), 1)
        self.data_type = data_type
        for u in units:
            # spread a unit's phases over the next `stages` unit starts
            u.per_tick = max(1, -(-u.nphases // self.stages))
        self.err_lens = {u.key: u.err_len for u in units if u.key}
        self.total_bytes = sum(u.total for u in units) * 4
        self.rounds = sum(u.nphases for u in units)
        breakdown: Dict[Tuple[str, str], int] = {}
        for u in units:
            k = ("allreduce", u.algo)
            breakdown[k] = breakdown.get(k, 0) + 1
        self.breakdown = breakdown

    @property
    def quant_units(self) -> int:
        return sum(1 for u in self.units if u.key)

    def algos_summary(self) -> str:
        return ",".join(
            f"{algo}:{n}" for (_, algo), n in sorted(self.breakdown.items())
        )

    def describe(self) -> List[str]:
        """One descriptor line per unit, in the CommRequest.describe()
        grammar (comm/request.in_graph_descriptor) — the in-graph rounds
        never construct a request, but tooling reads one format."""
        from mlsl_tpu.comm.request import in_graph_descriptor

        return [
            in_graph_descriptor(
                "allreduce", "+".join(u.names), u.algo,
                u.total, self.data_type, self.group,
            )
            for u in self.units
        ]


def _unit_algo(group: ProcessGroup, payload: int,
               compression: CompressionType, config, forced: Optional[str]):
    """Per-unit algorithm: a caller-forced name, else the PR 4 selection
    table (explicit MLSL_ALGO > tuned profile > 'lax'), then the in-graph
    eligibility gate on top — a selected algorithm the engine cannot embed
    falls back to the baseline with a debug log, mirroring algos.select's
    own fallback contract."""
    if compression != CompressionType.NONE:
        # compressed units carry their own wire family — except the
        # two-tier 'hier' route, whose codec lives on the DCN hop only: a
        # forced or tuned 'hier' stages the quantized unit hierarchically
        if compression == CompressionType.QUANTIZATION and config is not None:
            name = forced or algos.select(
                "allreduce", group, payload, compression, config,
                op=ReductionType.SUM,
            )
            if name == "hier" and algos._quant_hier_eligible(
                    "allreduce", group, config):
                return "hier"
        return algos.DEFAULT
    name = forced or algos.select(
        "allreduce", group, payload, compression, config, op=ReductionType.SUM
    )
    if name != algos.DEFAULT and not algos.inline_eligible(
        name, "allreduce", group, ReductionType.SUM
    ):
        log_debug(
            "overlap: algorithm %s not in-graph eligible on group %s; "
            "falling back to %s", name, algos.group_shape(group), algos.DEFAULT,
        )
        return algos.DEFAULT
    return name


def build_plan(
    group: ProcessGroup,
    layers: Sequence[Tuple[str, int, CompressionType]],
    config,
    *,
    stages: Optional[int] = None,
    bucket_mb: Optional[int] = None,
    block: Optional[int] = None,
    algo: Optional[str] = None,
) -> OverlapPlan:
    """Build the overlap schedule for ``layers`` (FORWARD order, as a
    trainer registers them: (name, flat element count, compression)). Units
    start newest-gradient-first — the reversed list — with small
    uncompressed neighbors coalesced under ``bucket_mb`` via the host
    buckets' own packing policy (core/bucketing.pack_by_size). ``algo``
    forces every dense unit's algorithm (tests/benches); None uses the
    selection table."""
    from mlsl_tpu.core.bucketing import pack_by_size

    mlsl_assert(layers, "overlap plan needs at least one layer")
    for _, _, comp in layers:
        mlsl_assert(
            comp in (CompressionType.NONE, CompressionType.QUANTIZATION),
            "compiled overlap supports NONE/QUANTIZATION compression "
            "(got %s — TOPK rides the host path)", comp,
        )
    stages = int(stages if stages is not None
                 else getattr(config, "overlap_stages", DEFAULT_STAGES))
    bucket_mb = int(bucket_mb if bucket_mb is not None
                    else getattr(config, "grad_bucket_mb", 0))
    block = int(block if block is not None
                else getattr(config, "quant_block_elems", 256))

    # bucket membership: the host packing policy over the uncompressed
    # layers (reverse order, singletons dropped, bandwidth-sized excluded)
    member_of: Dict[str, int] = {}
    plain = [(n, c) for n, c, comp in layers
             if comp == CompressionType.NONE]
    if bucket_mb > 0 and not group.is_self and group.size > 1:
        packs = pack_by_size(
            plain, bucket_mb * 1024 * 1024, lambda e: e[1] * 4
        )
        for gi, members in enumerate(packs):
            for n, _ in members:
                member_of[n] = gi
    counts = {n: c for n, c, _ in layers}
    comps = {n: comp for n, _, comp in layers}

    units: List[OverlapUnit] = []
    emitted: set = set()
    for name, _, comp in reversed(list(layers)):
        if name in emitted:
            continue
        if name in member_of:
            gi = member_of[name]
            members = tuple(
                n for n, _, _ in reversed(list(layers))
                if member_of.get(n) == gi
            )
            emitted.update(members)
            units.append(OverlapUnit(
                members, tuple(counts[n] for n in members),
                CompressionType.NONE,
                _unit_algo(group, sum(counts[n] for n in members) * 4,
                           CompressionType.NONE, config, algo),
                group, index=len(units), block=block, config=config,
            ))
            continue
        emitted.add(name)
        units.append(OverlapUnit(
            (name,), (counts[name],), comps[name],
            _unit_algo(group, counts[name] * 4, comps[name], config, algo),
            group, index=len(units), block=block, config=config,
        ))
    return OverlapPlan(group, units, stages)


# ---------------------------------------------------------------------------
# The staged in-graph scheduler
# ---------------------------------------------------------------------------


def _pin(entries: List[list]) -> None:
    """Pin a stage boundary: tie every in-flight carry together through ONE
    optimization_barrier so XLA cannot collapse the staged emission back
    into a single comm tail (the barrier constrains only the collective
    carries — backward compute upstream still floats freely for the
    latency-hiding scheduler to interleave)."""
    if not entries:
        return
    flat, treedefs = [], []
    for ent in entries:
        leaves, td = jax.tree.flatten(ent[1])
        flat.append(leaves)
        treedefs.append(td)
    all_leaves = [l for leaves in flat for l in leaves]
    if not all_leaves:
        return
    pinned = lax.optimization_barrier(tuple(all_leaves))
    off = 0
    for ent, leaves, td in zip(entries, flat, treedefs):
        ent[1] = jax.tree.unflatten(td, list(pinned[off:off + len(leaves)]))
        off += len(leaves)


def emit_schedule(
    plan: OverlapPlan,
    flat: Dict[str, jax.Array],
    residuals: Dict[str, jax.Array],
    on_ready: Optional[Callable[[str, jax.Array], None]] = None,
) -> Tuple[Dict[str, jax.Array], Dict[str, jax.Array]]:
    """Emit the staged schedule inside a traced shard_map body.

    ``flat``: per-layer local flat gradient arrays. ``residuals``: per-quant-
    unit local error-feedback arrays (threaded through, returned new).
    ``on_ready(name, reduced)`` is invoked the moment a unit's reduction
    completes (emission order — the per-layer fused-update hook). Returns
    (reduced dict, new residual dict)."""
    group = plan.group
    degenerate = group.is_self or group.size <= 1
    if degenerate:
        mypos = jnp.int32(0)
    else:
        sizes = _axis_sizes(group.topology.mesh)
        mypos = _group_rank(group.axes, sizes)

    inflight: List[list] = []  # [unit, carry, phase_idx]
    reduced: Dict[str, jax.Array] = {}
    new_res: Dict[str, jax.Array] = {}

    def retire(ent) -> None:
        parts, new_err = ent[0].finish(ent[1])
        if new_err is not None:
            new_res[ent[0].key] = new_err
        for n, r in parts.items():
            reduced[n] = r
            if on_ready is not None:
                on_ready(n, r)

    def tick() -> None:
        for ent in inflight:
            u = ent[0]
            for _ in range(u.per_tick):
                if ent[2] < u.nphases:
                    ent[1] = u.advance(ent[1], ent[2])
                    ent[2] += 1
        _pin([e for e in inflight if e[2] < e[0].nphases])
        for ent in [e for e in inflight if e[2] >= e[0].nphases]:
            inflight.remove(ent)
            retire(ent)

    for u in plan.units:
        inflight.append([u, u.prep(flat, mypos, residuals.get(u.key)), 0])
        tick()
    while inflight:
        tick()
    return reduced, new_res


# ---------------------------------------------------------------------------
# Standalone compiled multi-tensor reduce (parity suites, tuner sweep, bench)
# ---------------------------------------------------------------------------


def build_multi_reduce(
    group: ProcessGroup,
    counts: Sequence[int],
    *,
    compression: CompressionType = CompressionType.NONE,
    algo: Optional[str] = None,
    config=None,
    stages: Optional[int] = None,
    bucket_mb: int = 0,
    block: int = 256,
) -> Tuple[Callable, OverlapPlan]:
    """Compile the staged multi-tensor reduction standalone: -> (fn, plan).

    ``fn(bufs[, residuals]) -> (reduced list[, new residuals])`` over
    standard (R, D, S, M, n) distributed buffers, newest-first = the
    REVERSED list order (bufs[-1] starts first, like a backward pass).
    The lockstep-twin parity suites pin this against the host CommRequest
    path; the tuner sweep times it for the ``overlap_stages`` knob."""
    layers = [(f"t{i}", int(c), compression) for i, c in enumerate(counts)]
    plan = build_plan(group, layers, config, stages=stages,
                      bucket_mb=bucket_mb, block=block, algo=algo)
    topo = group.topology
    names = [n for n, _, _ in layers]
    res_keys = sorted(plan.err_lens)

    def body(bufs, res):
        flat = {
            n: b.reshape(b.shape[NUM_GRID_AXES:]) for n, b in zip(names, bufs)
        }
        res_l = {
            k: v.reshape(v.shape[NUM_GRID_AXES:]) for k, v in res.items()
        }
        reduced, new_res = emit_schedule(plan, flat, res_l)

        def lift(x):
            return x[None, None, None, None]

        return (
            [lift(reduced[n]) for n in names],
            {k: lift(v) for k, v in new_res.items()},
        )

    sm = smap(
        body, topo.mesh,
        in_specs=([_BUF_SPEC] * len(names), {k: _BUF_SPEC for k in res_keys}),
        out_specs=([_BUF_SPEC] * len(names), {k: _BUF_SPEC for k in res_keys}),
        check=False,
    )
    jitted = jax.jit(sm)

    def fn(bufs, residuals: Optional[dict] = None):
        if residuals is None and res_keys:
            residuals = zero_residuals(plan, topo)
        outs, new_res = jitted(list(bufs), residuals or {})
        if res_keys:
            return outs, new_res
        return outs

    return fn, plan


def zero_residuals(plan: OverlapPlan, topo) -> Dict[str, jax.Array]:
    """Fresh (zero) error-feedback residual buffers for the plan's quantized
    units — the same virgin state a host request's first round sees."""
    return {
        k: topo.shard_buffer(
            np.zeros((*topo.grid_shape, el), dtype=np.float32)
        )
        for k, el in plan.err_lens.items()
    }


# ---------------------------------------------------------------------------
# ZeRO-1 two-phase staged update (reduce-scatter -> owned update -> all-gather)
# ---------------------------------------------------------------------------


class _Zero1Unit:
    """One layer's ZeRO-1 exchange as staged phases: the reduce-scatter
    phases of the selected algorithm, the owned-shard SGD update, then the
    all-gather phases that reassemble the updated parameter. With the fused
    ring selected, BOTH wire phases are single Pallas kernel launches
    (ops/ring_kernels.py: ``kind='reduce_scatter'`` and the gather-only
    ``kind='all_gather'`` mode) — the host ZeRO-1 path's two lax programs
    (core/parameter_set.py grad/increment requests) collapsed into two
    kernel stages the overlap scheduler interleaves between layers."""

    def __init__(self, name: str, count: int, algo: str,
                 group: ProcessGroup, *, lr: float, denom: float,
                 config=None):
        self.name = name
        self.count = int(count)
        self.algo = algo
        g = max(int(group.size), 1)
        self.padded = -(-self.count // g) * g
        self.shard = self.padded // g
        self._lr, self._denom = float(lr), float(denom)
        self._degenerate = group.is_self or group.size <= 1
        if self._degenerate:
            self.nphases = 1
            return
        self._rs_prep, self._rs_phases, self._rs_finish = algos.inline_plan(
            "reduce_scatter", group, algo, self.padded,
            op=ReductionType.SUM, recv_count=self.shard, config=config,
        )
        if algo in ("pallas_ring", "pallas_ring2d"):
            # the gather phase rides the SAME kernel family as the reduce
            # phase: one fused all_gather launch over the same ring/snake
            from mlsl_tpu.ops import ring_kernels as rk_ops

            (self._ag_prep, self._ag_phases,
             self._ag_finish) = rk_ops.steps(
                "all_gather", group, self.shard,
                slots=getattr(config, "pallas_ring_slots", None),
                snake=(algo == "pallas_ring2d"),
            )
        else:
            sizes = _axis_sizes(group.topology.mesh)
            axes = group.axes

            def ag_phase(carry):
                cur, mypos = carry
                return _body_allgather(cur, axes=axes, sizes=sizes), mypos

            self._ag_prep = lambda x, mypos: (x, mypos)
            self._ag_phases = [ag_phase]
            self._ag_finish = lambda carry: carry[0]
        # reduce phases + the owned-shard update (its own stage: the
        # boundary between the two wire directions) + gather phases
        self.nphases = len(self._rs_phases) + 1 + len(self._ag_phases)
        self.per_tick = 1

    def prep(self, p_flat, g_flat, mypos):
        pad = self.padded - self.count
        p = jnp.pad(p_flat, (0, pad)) if pad else p_flat
        gr = jnp.pad(g_flat, (0, pad)) if pad else g_flat
        if self._degenerate:
            return {"p": p, "g": gr, "mypos": mypos}
        return {"p": p, "carry": self._rs_prep(gr, mypos), "mypos": mypos}

    def advance(self, state, i: int):
        if self._degenerate:
            state["p"] = state["p"] - self._lr * (state["g"] / self._denom)
            return state
        n_rs = len(self._rs_phases)
        if i < n_rs:
            state["carry"] = self._rs_phases[i](state["carry"])
        elif i == n_rs:
            # owned-shard update: this member updates ONLY its 1/G slice —
            # the ZeRO-1 contract; everyone else's slices arrive updated
            # through the gather phases
            gshard = self._rs_finish(state["carry"]) / self._denom
            owned = lax.dynamic_slice_in_dim(
                state["p"], state["mypos"] * self.shard, self.shard
            )
            state["carry"] = self._ag_prep(
                owned - self._lr * gshard, state["mypos"]
            )
        else:
            state["carry"] = self._ag_phases[i - n_rs - 1](state["carry"])
        return state

    def finish(self, state):
        if self._degenerate:
            return state["p"][: self.count]
        return self._ag_finish(state["carry"])[: self.count]


def _zero1_algo(group: ProcessGroup, payload: int, config,
                forced: Optional[str]) -> str:
    """Per-unit reduce-scatter algorithm for the ZeRO-1 plan: the same
    forced > table > in-graph-gate cascade as ``_unit_algo``, keyed on the
    reduce_scatter kind."""
    name = forced or algos.select(
        "reduce_scatter", group, payload, CompressionType.NONE, config,
        op=ReductionType.SUM,
    )
    if name and name != algos.DEFAULT and not algos.inline_eligible(
        name, "reduce_scatter", group, ReductionType.SUM
    ):
        log_debug(
            "zero1: algorithm %s not in-graph eligible on group %s; "
            "falling back to %s", name, algos.group_shape(group),
            algos.DEFAULT,
        )
        return algos.DEFAULT
    return name or algos.DEFAULT


def build_zero1_update(
    group: ProcessGroup,
    counts: Sequence[int],
    *,
    lr: float,
    denom: float = 1.0,
    algo: Optional[str] = None,
    config=None,
    stages: Optional[int] = None,
) -> Tuple[Callable, List[_Zero1Unit]]:
    """Compile the staged ZeRO-1 update standalone: -> (fn, units).

    ``fn(param_bufs, grad_bufs) -> new param bufs`` over standard
    (R, D, S, M, n) distributed buffers, newest-first (the reversed list
    starts first, like a backward pass). Each layer is ONE `_Zero1Unit`:
    reduce-scatter the gradient, update the owned 1/G shard with SGD
    (``p -= lr * g / denom``), all-gather the updated parameter — the
    optimizer-state-sharded schedule the host path runs as two separate
    request families, emitted here as in-graph stages with the phase
    boundaries pinned like the allreduce schedule. With the fused ring
    selected (forced/tuned ``pallas_ring``/``pallas_ring2d``), both wire
    phases are single Pallas kernel launches."""
    mlsl_assert(counts, "zero1 plan needs at least one layer")
    stages = int(stages if stages is not None
                 else getattr(config, "overlap_stages", DEFAULT_STAGES))
    units = [
        _Zero1Unit(
            f"p{i}", int(c),
            _zero1_algo(group, int(c) * 4, config, algo),
            group, lr=lr, denom=denom, config=config,
        )
        for i, c in enumerate(counts)
    ]
    for u in units:
        u.per_tick = max(1, -(-u.nphases // max(stages, 1)))
    topo = group.topology
    degenerate = group.is_self or group.size <= 1
    names = [u.name for u in units]

    def body(p_bufs, g_bufs):
        if degenerate:
            mypos = jnp.int32(0)
        else:
            sizes = _axis_sizes(group.topology.mesh)
            mypos = _group_rank(group.axes, sizes)
        flat_p = {n: b.reshape(b.shape[NUM_GRID_AXES:])
                  for n, b in zip(names, p_bufs)}
        flat_g = {n: b.reshape(b.shape[NUM_GRID_AXES:])
                  for n, b in zip(names, g_bufs)}
        inflight: List[list] = []  # [unit, state, phase_idx]
        out: Dict[str, jax.Array] = {}

        def tick() -> None:
            for ent in inflight:
                u = ent[0]
                for _ in range(u.per_tick):
                    if ent[2] < u.nphases:
                        ent[1] = u.advance(ent[1], ent[2])
                        ent[2] += 1
            _pin([e for e in inflight if e[2] < e[0].nphases])
            for ent in [e for e in inflight if e[2] >= e[0].nphases]:
                inflight.remove(ent)
                out[ent[0].name] = ent[0].finish(ent[1])

        for u in reversed(units):
            inflight.append([u, u.prep(flat_p[u.name], flat_g[u.name],
                                       mypos), 0])
            tick()
        while inflight:
            tick()
        return [out[n][None, None, None, None] for n in names]

    sm = smap(
        body, topo.mesh,
        in_specs=([_BUF_SPEC] * len(names), [_BUF_SPEC] * len(names)),
        out_specs=[_BUF_SPEC] * len(names),
        check=False,
    )
    jitted = jax.jit(sm)

    def fn(param_bufs, grad_bufs):
        return jitted(list(param_bufs), list(grad_bufs))

    return fn, units


# ---------------------------------------------------------------------------
# Trainer engine
# ---------------------------------------------------------------------------


class OverlapEngine:
    """The trainer-facing compiled overlap mode: owns the plan, the compiled
    step program(s), and the error-feedback residual state.

    Two program shapes, both single-dispatch for the comm segment:

    - fused: ``(params, residuals, batch) -> (loss, params', residuals')`` —
      backward + staged in-graph collectives + per-layer updates in ONE
      donation-enabled executable.
    - split: ``_grad_fn`` first (the trainer's existing program), then
      ``(params, residuals, grads) -> (params', residuals')`` — used when
      the sentinel quality gate is armed (the gate needs the gradient
      boundary on the host, before any comm starts; sentinel ``skip_step``
      then never dispatches the comm program, so residuals never advance —
      the same lockstep contract as the host path).

    Chaos: every engine step passes the ``collective.dispatch`` site ONCE at
    the step boundary (the whole comm segment is one dispatch), so armed
    budgets fire at the step they target. The precompile warm calls the
    jitted programs directly and never passes the site.
    """

    def __init__(self, trainer, plan: OverlapPlan):
        self.plan = plan
        self._trainer = trainer
        cfg_donate = trainer.donate_params
        mesh = trainer.mesh
        layers = trainer.layers
        counts = trainer.layer_counts
        padded = trainer.padded_counts
        get_layer = trainer.get_layer
        loss_fn = trainer.loss_fn
        lr, data_size = trainer.lr, trainer.data_size
        clip = trainer.clip_global_norm
        from mlsl_tpu.models.train import (  # lazy: avoid import cycle
            _clip_scale, _set_layer, _unflatten_like, build_local_grads,
        )

        res_keys = sorted(plan.err_lens)
        res_specs = {k: _BUF_SPEC for k in res_keys}
        # THE host _grad_fn's flatten/pad core — one implementation, so the
        # compiled twin can never drift from the parity oracle's grads
        grads_core = build_local_grads(loss_fn, layers, get_layer, padded)

        def local_grads(params, x, y):
            x = x.reshape(x.shape[NUM_GRID_AXES:])
            y = y.reshape(y.shape[NUM_GRID_AXES:])
            return grads_core(params, x, y)

        def reduce_and_update(params, res_l, flat):
            new_subs: Dict[str, object] = {}

            def apply(name, r):
                g = r[: counts[name]] / data_size
                sub = get_layer(params, name)
                new_subs[name] = jax.tree.map(
                    lambda p, gg: p - lr * gg, sub, _unflatten_like(sub, g)
                )

            # per-layer update fused at retirement (emission order) — except
            # under global-norm clipping, whose scale needs EVERY reduced
            # gradient before the first update
            on_ready = apply if clip is None else None
            reduced, new_res = emit_schedule(self.plan, flat, res_l, on_ready)
            if clip is not None:
                cscale = _clip_scale(
                    sum(
                        jnp.sum((reduced[n][: counts[n]] / data_size) ** 2)
                        for n in layers
                    ),
                    clip,
                )
                for name in layers:
                    g = reduced[name][: counts[name]] / data_size * cscale
                    sub = get_layer(params, name)
                    new_subs[name] = jax.tree.map(
                        lambda p, gg: p - lr * gg, sub,
                        _unflatten_like(sub, g),
                    )
            new_params = params
            for name in layers:
                new_params = _set_layer(new_params, name, new_subs[name])
            return new_params, new_res

        def lift(x):
            return x[None, None, None, None]

        def fused_body(params, res, x, y):
            loss, flat = local_grads(params, x, y)
            res_l = {
                k: v.reshape(v.shape[NUM_GRID_AXES:]) for k, v in res.items()
            }
            new_params, new_res = reduce_and_update(params, res_l, flat)
            return (
                loss[None, None, None, None, None],
                new_params,
                {k: lift(v) for k, v in new_res.items()},
            )

        def sync_body(params, res, flat_bufs):
            flat = {
                n: b.reshape(b.shape[NUM_GRID_AXES:])
                for n, b in flat_bufs.items()
            }
            res_l = {
                k: v.reshape(v.shape[NUM_GRID_AXES:]) for k, v in res.items()
            }
            new_params, new_res = reduce_and_update(params, res_l, flat)
            return new_params, {k: lift(v) for k, v in new_res.items()}

        fused_sm = smap(
            fused_body, mesh,
            in_specs=(P(), res_specs, _BUF_SPEC, _BUF_SPEC),
            out_specs=(_BUF_SPEC, P(), res_specs),
            check=False,
        )
        sync_sm = smap(
            sync_body, mesh,
            in_specs=(P(), res_specs, {n: _BUF_SPEC for n in layers}),
            out_specs=(P(), res_specs),
            check=False,
        )
        donate = (0, 1) if cfg_donate else (1,)
        self._step_fn = jax.jit(
            lambda p, r, b: fused_sm(p, r, b[0], b[1]), donate_argnums=donate
        )
        self._sync_fn = jax.jit(sync_sm, donate_argnums=donate)
        self.residuals = zero_residuals(plan, trainer.dist.topology)
        self._descr_logged = False
        log_debug(
            "compiled overlap plan: %d units (%s), stages=%d, %d phases",
            len(plan.units), plan.algos_summary(), plan.stages, plan.rounds,
        )

    # -- the step ----------------------------------------------------------

    def step(self, batch, *, grads=None, loss=None) -> jax.Array:
        """One compiled-overlap step. With ``grads`` (and ``loss``) given the
        split program runs (the sentinel-gated path: the caller already ran
        ``_grad_fn`` and the quality gate); otherwise the fused single
        program."""
        trainer = self._trainer
        tr = obs._tracer
        t0 = tr.now() if tr is not None else 0
        if chaos._plans:
            # the whole comm segment is ONE dispatch; armed budgets fire at
            # the step boundary they target
            chaos.inject("collective.dispatch", kind="overlap")
        split = grads is not None
        if split:
            new_params, self.residuals = self._sync_fn(
                trainer.params, self.residuals, grads
            )
        else:
            loss, new_params, self.residuals = self._step_fn(
                trainer.params, self.residuals, batch
            )
        trainer.params = new_params
        plan = self.plan
        stats_mod.record_overlap_step(
            len(plan.units), plan.rounds, plan.total_bytes,
            split=split, breakdown=plan.breakdown,
        )
        if tr is not None:
            tr.complete(
                "step.overlap", "step", t0, step=trainer._step_no,
                layers=len(trainer.layers), units=len(plan.units),
                stages=plan.stages, phases=plan.rounds,
                algos=plan.algos_summary(), quant_units=plan.quant_units,
                bytes=plan.total_bytes, split=split,
            )
        return loss

    # -- AOT warm-up (MLSL_PRECOMPILE) -------------------------------------

    def precompile(self, batch) -> None:
        """Warm the compiled program(s) on donation-safe copies so step 0 of
        the timed loop contains no compilation (the trainer.precompile
        contract). The warm calls the jitted fns directly — never the chaos
        site — so armed budgets survive to the step they target."""
        trainer = self._trainer
        copy = lambda tree: jax.tree.map(jnp.copy, tree)
        if trainer.sentinel is not None and trainer.sentinel.gate_armed:
            loss, grads = trainer._grad_fn(trainer.params, batch)
            out = self._sync_fn(copy(trainer.params), copy(self.residuals),
                                grads)
        else:
            out = self._step_fn(copy(trainer.params), copy(self.residuals),
                                batch)
        jax.block_until_ready(out)


def engine_for_trainer(trainer, config) -> Optional[OverlapEngine]:
    """Build the trainer's OverlapEngine, or None when the graph cannot ride
    the compiled path (the caller falls back to the host engine):
    custom codecs keep their host wire format, TOPK its sparse requests,
    color groups their flat-mesh programs. Contradictory *explicit* requests
    (optax / ZeRO-1 / overlap_updates with overlap_compiled) are asserted in
    the trainer ctor, not here."""
    group = trainer.dist.grad_group
    if getattr(config, "custom_codec", None) is not None:
        log_debug("overlap: custom codec rides the host path")
        return None
    if group.colors is not None:
        log_debug("overlap: color-group gradients ride the host path")
        return None
    layers = [
        (name, trainer.padded_counts[name],
         trainer.ops[name].get_parameter_set(0).compression)
        for name in trainer.layers
    ]
    if any(comp == CompressionType.TOPK for _, _, comp in layers):
        log_debug("overlap: TOPK compression rides the host path")
        return None
    plan = build_plan(group, layers, config)
    if getattr(config, "verify", False):
        # MLSL_VERIFY=1 covers the compiled-overlap plan too: the donated
        # carry/EF geometry is fixed here, before the step program traces —
        # the same commit-time gate contract as Session.commit
        # (analysis/plan.py A112/A120/A122; severity per
        # MLSL_VERIFY_SEVERITY, enforced by the shared plan.enforce gate)
        import time

        from mlsl_tpu.analysis import plan as plan_verifier

        t0 = time.perf_counter()
        plan_verifier.enforce(
            plan_verifier.verify_overlap_plan(
                plan, block=getattr(config, "quant_block_elems", None)
                if plan.quant_units else None,
            ),
            config, "compiled-overlap plan", t0,
        )
    return OverlapEngine(trainer, plan)
