"""The ``pallas_a2a`` algos-engine lowering: fused quantized all-to-all.

The first member of the NEW ``'alltoall'`` engine kind: MoE dispatch/combine
(models/moe.py) lowered to the fused Pallas exchange in ops/a2a_kernels.py —
int8 blockwise codec fused at the VMEM boundary (quantize on send-slot
write, dequantize on receive), double-buffered per-step RDMA, wire bytes
<= 1/3 of the f32 inline path. ``MLSL_PALLAS_A2A_QUANT=0`` selects the dense
(uncompressed) variant of the same kernel.

``build`` compiles the standalone host-dispatch program over the flat world
mesh (interpreter-executable off-TPU — the tier-1 parity vehicle; the
stateful ``ef=True`` form exposes the entry error-feedback residual for the
lockstep tests); ``steps`` exposes the in-graph form models/moe.py's
shard_map embeds (TPU only — a2a_kernels.inline_ok)."""

from __future__ import annotations

from typing import Callable

from mlsl_tpu.comm.mesh import ProcessGroup
from mlsl_tpu.log import mlsl_assert


def eligible(kind: str, group: ProcessGroup, op=None) -> bool:
    from mlsl_tpu.ops import a2a_kernels

    return a2a_kernels.eligible(kind, group, op=op)


def steps(kind: str, group: ProcessGroup, count: int, *, op=None,
          block=256, quantized=True, slots=None, **_):
    from mlsl_tpu.ops import a2a_kernels

    mlsl_assert(op is None, "alltoall carries no reduction op (got %s)", op)
    return a2a_kernels.steps(kind, group, count, block=block,
                             quantized=quantized, slots=slots)


def build(kind: str, group: ProcessGroup, *, op=None, block=256,
          quantized=True, slots=None, ef=False, **_) -> Callable:
    """Compile the standalone pallas_a2a program (build_collective calling
    convention). ``ef=True`` builds the stateful ``(buf, err) -> (out,
    new_err)`` error-feedback form; geometry resolves at trace time."""
    from mlsl_tpu.ops import a2a_kernels
    from mlsl_tpu.ops import ring_kernels as rk

    mlsl_assert(eligible(kind, group, op),
                "pallas_a2a cannot lower %s on this group/backend", kind)

    if ef:
        mlsl_assert(quantized, "the error-feedback form is quantized-only")

        def body_ef(x, err):
            inner, _ = a2a_kernels.alltoall_body_ef(
                group, int(x.shape[0]), block=block, quantized=True,
                slots=slots,
            )
            return inner(x, err)

        return rk.build_flat_program(body_ef, group, kind, stateful=True)

    def body(x):
        inner = a2a_kernels.alltoall_body(
            group, int(x.shape[0]), block=block, quantized=quantized,
            slots=slots,
        )
        return inner(x)

    return rk.build_flat_program(body, group, kind)
