"""The ``pallas_ring`` algos-engine lowering: hand-written fused ring kernels.

Fourth entry in the selection table (after ``lax``/``rhd``/``ring2d``): the
dense allreduce / reduce-scatter lowered to the Pallas ring kernel in
ops/ring_kernels.py — explicit double-buffered ``make_async_remote_copy``
RDMA per hop instead of ``lax.ppermute`` programs XLA schedules. The
quantized (int8-fused) variant of the same kernel is NOT built here — it is
a compressed *wire family* and rides quant_ring.build_quantized_collective
(``ring='pallas'``), which the request layer selects through the same table.

``build`` compiles the standalone host-dispatch program over the flat world
mesh (ring neighbors resolved per group instance through world-rank tables,
the rhd precedent — and the form the Pallas interpreter can execute off-TPU
for tier-1 parity); ``steps`` exposes the compiled-overlap phase form over
the group's own grid axes (TPU only — ring_kernels.inline_ok)."""

from __future__ import annotations

from typing import Callable

from mlsl_tpu.comm.mesh import ProcessGroup
from mlsl_tpu.log import mlsl_assert


def eligible(kind: str, group: ProcessGroup, op=None) -> bool:
    from mlsl_tpu.ops import ring_kernels

    return ring_kernels.eligible_dense(kind, group, op)


def steps(kind: str, group: ProcessGroup, count: int, *, op=None,
          recv_count=None, slots=None, bidir=None):
    from mlsl_tpu.ops import ring_kernels

    return ring_kernels.steps(kind, group, count, op=op,
                              recv_count=recv_count, slots=slots,
                              bidir=bidir)


def build(kind: str, group: ProcessGroup, *, op=None, recv_count=None,
          slots=None, bidir=None, **_) -> Callable:
    """Compile the pallas-ring program for ``kind`` over ``group``: global
    distributed buffer -> global result buffer (the build_collective
    convention). Geometry is resolved at trace time from the buffer length,
    so one cached program serves every payload size through jit's shape
    specialization — like the other engine lowerings."""
    from mlsl_tpu.ops import ring_kernels as rk

    mlsl_assert(eligible(kind, group, op),
                "pallas_ring cannot lower %s on this group/backend", kind)

    def body(x):
        inner = rk.dense_ring_body(
            kind, group, int(x.shape[0]), x.dtype,
            recv_count=recv_count, slots=slots, bidir=bidir,
        )
        return inner(x)

    return rk.build_flat_program(body, group, kind)
