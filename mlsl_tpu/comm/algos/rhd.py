"""Recursive halving/doubling allreduce & reduce-scatter (paper parity).

The reference's alternative allreduce (eplib/allreduce_pr.c) is the classic
Rabenseifner scheme: a reduce-scatter by recursive *halving* (log2(G) pairwise
exchanges, payload halving each round) followed by an all-gather by recursive
*doubling* (payload doubling back). Total wire is 2*(G-1)/G * n per member —
bandwidth-optimal — in 2*log2(G) latency rounds instead of the ring's 2*(G-1).

TPU translation: every pairwise exchange IS ``lax.ppermute`` (the same
primitive behind the sendrecv body, collectives._body_sendrecv), compiled as
ONE program over the flattened world mesh so a single implementation serves
single-axis rings, multi-axis sub-tori (flattened group rank order), and
uniform color groups. SPMD uniformity is kept with rank masks: every member
executes the same unrolled rounds; members outside a round's pair list
receive ppermute's zero fill and are masked out.

Non-power-of-two remainder (the classic pre/post fold):
  - c = 2^floor(log2(G)), r = G - c. The r "extra" members (group positions
    c..G-1) first fold their full vector into positions 0..r-1 (one
    ppermute + combine), then positions 0..c-1 run the power-of-two core.
  - allreduce: a post-fold ppermute hands the finished result back to the
    extras. reduce_scatter on non-2^k groups (or ragged counts) takes the
    fold + core + doubling path and slices each member's chunk from the full
    result — correct everywhere, wire-optimal only in the 2^k case.

The schedule is exposed in two forms sharing one implementation (``steps``):
``build`` compiles it standalone over the flat world mesh (the host-dispatch
engine program, comm/algos), and the compiled overlap engine
(comm/overlap.py) embeds the same phase sequence IN-GRAPH over the group's
own mesh axes — each phase is exactly one ppermute round, so the overlap
scheduler can interleave a layer's rounds between other layers' work and
XLA's latency-hiding scheduler sees the full comm schedule.
"""

from __future__ import annotations

from typing import Callable, List, Tuple

import jax.numpy as jnp
from jax import lax

from mlsl_tpu.comm.mesh import ProcessGroup
from mlsl_tpu.log import mlsl_assert
from mlsl_tpu.types import ReductionType


def _member_rows(group: ProcessGroup):
    """World-rank member rows, one per group instance (uniform groups only)."""
    from mlsl_tpu.comm import collectives

    if group.colors is not None:
        return collectives._color_groups_tbl(group)
    return collectives._axis_groups_tbl(group)


def _combine(op: ReductionType):
    if op == ReductionType.MIN:
        return jnp.minimum
    if op == ReductionType.MAX:
        return jnp.maximum
    return lambda a, b: a + b


def steps(
    kind: str,
    G: int,
    n: int,
    ax,
    pair_map: Callable[[list], list],
    *,
    op=None,
    recv_count=None,
) -> Tuple[Callable, List[Callable], Callable]:
    """The staged RHD schedule: ``(prep, phases, finish)``.

    ``prep(x, mypos) -> carry``; each ``phases[i](carry) -> carry`` performs
    exactly ONE ppermute round (the unit the compiled overlap engine
    interleaves); ``finish(carry) -> result``. ``ax`` is the mesh axis (or
    axis tuple) the ppermute rides; ``mypos`` is this member's group
    position as a traced value; ``pair_map`` expands group-position pairs
    into the mesh pair list — identity when ``ax`` spans exactly the group
    (positions ARE the linearized indices over the axis tuple), or the
    world-row expansion ``build`` uses for the standalone flat-mesh program.
    ``n`` is the static per-member element count.
    """
    op = ReductionType(op) if op is not None else ReductionType.SUM
    comb = _combine(op)
    k = G.bit_length() - 1
    c = 1 << k            # largest power of two <= G
    r = G - c             # remainder members folded in pre/post phases
    m = -(-n // c) * c
    round_pairs = [
        pair_map([(i, i ^ (c >> (t + 1))) for i in range(c)])
        for t in range(k)
    ]

    def prep(x, mypos):
        # pad lanes only ever combine with other members' pad lanes (same
        # positions), so zeros are safe for MIN/MAX too — they are stripped
        # before return.
        cur = jnp.pad(x, (0, m - n)) if m != n else x
        return (cur, mypos)

    phases: List[Callable] = []

    if r:
        pre_pairs = pair_map([(c + j, j) for j in range(r)])

        def pre_fold(carry):
            cur, mypos = carry
            got = lax.ppermute(cur, ax, pre_pairs)
            return jnp.where(mypos < r, comb(cur, got), cur), mypos

        phases.append(pre_fold)

    def halving(t):
        def phase(carry):
            cur, mypos = carry
            h = m >> (t + 1)
            lo, hi = cur[:h], cur[h:]
            bit = (mypos >> (k - 1 - t)) & 1
            send = jnp.where(bit == 0, hi, lo)
            got = lax.ppermute(send, ax, round_pairs[t])
            return comb(jnp.where(bit == 0, lo, hi), got), mypos

        return phase

    phases.extend(halving(t) for t in range(k))
    # after halving: cur = member mypos's fully reduced chunk
    # [mypos*m/c, (mypos+1)*m/c)

    if (kind == "reduce_scatter" and G == c and recv_count is not None
            and n == G * recv_count):
        # exact-placement fast exit when the chunking lines up: member pos's
        # halving chunk IS its MPI slice — no doubling phase needed
        return prep, phases, lambda carry: carry[0][:recv_count]

    def doubling(t):
        def phase(carry):
            cur, mypos = carry
            bit = (mypos >> (k - 1 - t)) & 1
            got = lax.ppermute(cur, ax, round_pairs[t])
            return (
                jnp.where(
                    bit == 0,
                    jnp.concatenate([cur, got]),
                    jnp.concatenate([got, cur]),
                ),
                mypos,
            )

        return phase

    phases.extend(doubling(t) for t in reversed(range(k)))

    if r:
        post_pairs = pair_map([(j, c + j) for j in range(r)])

        def post_fold(carry):
            cur, mypos = carry
            got = lax.ppermute(cur, ax, post_pairs)
            return jnp.where(mypos >= c, got, cur), mypos

        phases.append(post_fold)

    if kind == "reduce_scatter":
        mlsl_assert(
            recv_count is not None,
            "rhd reduce_scatter needs recv_count",
        )

        def finish_rs(carry):
            cur, mypos = carry
            return lax.dynamic_slice_in_dim(
                cur, mypos * recv_count, recv_count, axis=0
            )

        return prep, phases, finish_rs

    return prep, phases, lambda carry: carry[0][:n]


def build(kind: str, group: ProcessGroup, *, op=None, recv_count=None,
          **_) -> Callable:
    """Compile the RHD program for ``kind`` over ``group``: global distributed
    buffer -> global result buffer (same convention as build_collective)."""
    from mlsl_tpu.comm import collectives

    rows = _member_rows(group)
    G = len(rows[0])
    mlsl_assert(G > 1, "rhd needs a group with >1 member (got %d)", G)
    pos_t = jnp.asarray(collectives._subgroup_tables(rows))

    def pair_map(pairs):
        return [(row[s], row[d]) for row in rows for s, d in pairs]

    def body(x):
        mypos = jnp.take(pos_t, lax.axis_index("world"))
        prep, phases, finish = steps(
            kind, G, x.shape[0], "world", pair_map,
            op=op, recv_count=recv_count,
        )
        carry = prep(x, mypos)
        for phase in phases:
            carry = phase(carry)
        return finish(carry)

    return collectives._build_flat(body, group.topology, kind, "rhd")
