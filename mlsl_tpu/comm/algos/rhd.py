"""Recursive halving/doubling allreduce & reduce-scatter (paper parity).

The reference's alternative allreduce (eplib/allreduce_pr.c) is the classic
Rabenseifner scheme: a reduce-scatter by recursive *halving* (log2(G) pairwise
exchanges, payload halving each round) followed by an all-gather by recursive
*doubling* (payload doubling back). Total wire is 2*(G-1)/G * n per member —
bandwidth-optimal — in 2*log2(G) latency rounds instead of the ring's 2*(G-1).

TPU translation: every pairwise exchange IS ``lax.ppermute`` (the same
primitive behind the sendrecv body, collectives._body_sendrecv), compiled as
ONE program over the flattened world mesh so a single implementation serves
single-axis rings, multi-axis sub-tori (flattened group rank order), and
uniform color groups. SPMD uniformity is kept with rank masks: every member
executes the same unrolled rounds; members outside a round's pair list
receive ppermute's zero fill and are masked out.

Non-power-of-two remainder (the classic pre/post fold):
  - c = 2^floor(log2(G)), r = G - c. The r "extra" members (group positions
    c..G-1) first fold their full vector into positions 0..r-1 (one
    ppermute + combine), then positions 0..c-1 run the power-of-two core.
  - allreduce: a post-fold ppermute hands the finished result back to the
    extras. reduce_scatter on non-2^k groups (or ragged counts) takes the
    fold + core + doubling path and slices each member's chunk from the full
    result — correct everywhere, wire-optimal only in the 2^k case.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax

from mlsl_tpu.comm.mesh import ProcessGroup
from mlsl_tpu.log import mlsl_assert
from mlsl_tpu.types import ReductionType


def _member_rows(group: ProcessGroup):
    """World-rank member rows, one per group instance (uniform groups only)."""
    from mlsl_tpu.comm import collectives

    if group.colors is not None:
        return collectives._color_groups_tbl(group)
    return collectives._axis_groups_tbl(group)


def _combine(op: ReductionType):
    if op == ReductionType.MIN:
        return jnp.minimum
    if op == ReductionType.MAX:
        return jnp.maximum
    return lambda a, b: a + b


def build(kind: str, group: ProcessGroup, *, op=None, recv_count=None,
          **_) -> Callable:
    """Compile the RHD program for ``kind`` over ``group``: global distributed
    buffer -> global result buffer (same convention as build_collective)."""
    from mlsl_tpu.comm import collectives

    op = ReductionType(op) if op is not None else ReductionType.SUM
    rows = _member_rows(group)
    G = len(rows[0])
    mlsl_assert(G > 1, "rhd needs a group with >1 member (got %d)", G)
    comb = _combine(op)
    pos_t = jnp.asarray(collectives._subgroup_tables(rows))

    k = G.bit_length() - 1
    c = 1 << k            # largest power of two <= G
    r = G - c             # remainder members folded in pre/post phases
    pre_pairs = [(row[c + j], row[j]) for row in rows for j in range(r)]
    post_pairs = [(row[j], row[c + j]) for row in rows for j in range(r)]
    round_pairs = [
        [(row[i], row[i ^ (c >> (t + 1))]) for row in rows for i in range(c)]
        for t in range(k)
    ]

    def body(x):
        n = x.shape[0]
        mypos = jnp.take(pos_t, lax.axis_index("world"))
        m = -(-n // c) * c
        cur = jnp.pad(x, (0, m - n)) if m != n else x
        # pad lanes only ever combine with other members' pad lanes (same
        # positions), so zeros are safe for MIN/MAX too — they are stripped
        # before return.
        if r:
            got = lax.ppermute(cur, "world", pre_pairs)
            cur = jnp.where(mypos < r, comb(cur, got), cur)
        # --- recursive halving: log2(c) rounds, payload halves each round ---
        for t in range(k):
            h = m >> (t + 1)
            lo, hi = cur[:h], cur[h:]
            bit = (mypos >> (k - 1 - t)) & 1
            send = jnp.where(bit == 0, hi, lo)
            got = lax.ppermute(send, "world", round_pairs[t])
            cur = comb(jnp.where(bit == 0, lo, hi), got)
        # cur = member mypos's fully reduced chunk [mypos*m/c, (mypos+1)*m/c)
        if (kind == "reduce_scatter" and G == c and recv_count is not None
                and n == G * recv_count):
            # exact-placement fast exit when the chunking lines up: member
            # pos's halving chunk IS its MPI slice — no doubling phase needed
            return cur[:recv_count]
        # --- recursive doubling: payload doubles back to the full vector ---
        for t in reversed(range(k)):
            bit = (mypos >> (k - 1 - t)) & 1
            got = lax.ppermute(cur, "world", round_pairs[t])
            cur = jnp.where(
                bit == 0,
                jnp.concatenate([cur, got]),
                jnp.concatenate([got, cur]),
            )
        if r:
            got = lax.ppermute(cur, "world", post_pairs)
            cur = jnp.where(mypos >= c, got, cur)
        if kind == "reduce_scatter":
            mlsl_assert(
                recv_count is not None,
                "rhd reduce_scatter needs recv_count",
            )
            return lax.dynamic_slice_in_dim(
                cur, mypos * recv_count, recv_count, axis=0
            )
        return cur[:n]

    return collectives._build_flat(body, group.topology, kind, "rhd")
