"""Hierarchical 2D ring-of-rings allreduce & reduce-scatter (torus-aware).

For a group spanning >= 2 mesh axes the single-shot ``lax`` body asks XLA to
reduce over the whole sub-torus at once. The hierarchical decomposition
instead phases the reduction so each phase rides ONE physical ring:

  allreduce(n) over axes (a0, ..., a_minor):
    1. reduce-scatter along the minor axis ring  -> shard of n/|minor|
    2. reduce (psum) over the remaining axes     -> shard fully reduced,
       each remaining-axis ring moving only n/|minor| bytes
    3. all-gather back along the minor axis ring -> full n

  reduce_scatter(n = G*rc) over exactly (a0, a1):
    local transpose to a1-major chunk order, then
    1. psum_scatter along a1 -> (|a0|*rc,) slab  (chunks for my a1 column)
    2. psum_scatter along a0 -> (rc,) chunk      (my group-rank chunk)
    Placement: member (i0, i1) receives group chunk i0*|a1| + i1 — exactly
    the flattened (major -> minor) group-rank slice of the MPI contract.

Wire per member: n + n/|minor| vs the fused reduction's n per axis — the win
grows with the torus dimension, which is why 2D/3D-torus allreduce
implementations (and EQuARX inside XLA) decompose exactly this way.
SUM only: the scatter phases are ``lax.psum_scatter``.

Like rhd, the schedule is exposed as staged ``steps`` (one collective phase
per entry) shared by the standalone ``build`` program and the compiled
overlap engine (comm/overlap.py), which embeds the phases in-graph between
other layers' work.
"""

from __future__ import annotations

from typing import Callable, List, Tuple

import jax.numpy as jnp
from jax import lax

from mlsl_tpu.comm.mesh import ProcessGroup
from mlsl_tpu.log import mlsl_assert


def _live_axes(group: ProcessGroup):
    from mlsl_tpu.comm import collectives

    sizes = collectives._axis_sizes(group.topology.mesh)
    axes = tuple(group.axes)
    return axes, [a for a in axes if sizes[a] > 1], sizes


def steps(
    kind: str,
    group: ProcessGroup,
    n: int,
    *,
    op=None,
    recv_count=None,
) -> Tuple[Callable, List[Callable], Callable]:
    """The staged ring-of-rings schedule: ``(prep, phases, finish)``, each
    phase exactly one collective over ONE named mesh axis set. Bodies run
    inside a shard_map over the group's own (grid) mesh — shared by
    ``build`` and the compiled overlap engine. ``prep(x, mypos)``/``finish``
    take/return the same carry convention as rhd.steps (mypos rides along
    unused: ring2d placement is axis-index-native)."""
    axes, live, sizes = _live_axes(group)
    mlsl_assert(
        len(live) >= 2,
        "ring2d needs a group spanning >= 2 non-degenerate mesh axes "
        "(got %s with sizes %s)", axes, [sizes[a] for a in axes],
    )

    if kind == "reduce_scatter":
        # degenerate size-1 axes contribute nothing to the flattened group
        # rank, so the 2D placement math runs over the two LIVE axes
        mlsl_assert(len(live) == 2, "ring2d reduce_scatter is 2D only")
        a0, a1 = live
        A0, A1 = sizes[a0], sizes[a1]
        mlsl_assert(
            recv_count is not None and n == A0 * A1 * recv_count,
            "ring2d reduce_scatter needs count == G*recv_count "
            "(count %d, G %d, recv_count %s)", n, A0 * A1, recv_count,
        )

        def prep_rs(x, mypos):
            # a1-major chunk order so the two scatters land group chunk
            # i0*A1 + i1 on member (i0, i1) — a local relabeling, no wire
            xr = jnp.transpose(
                x.reshape(A0, A1, recv_count), (1, 0, 2)
            ).reshape(-1)
            return (xr, mypos)

        def rs_a1(carry):
            cur, mypos = carry
            return lax.psum_scatter(
                cur, a1, scatter_dimension=0, tiled=True
            ), mypos

        def rs_a0(carry):
            cur, mypos = carry
            return lax.psum_scatter(
                cur, a0, scatter_dimension=0, tiled=True
            ), mypos

        return prep_rs, [rs_a1, rs_a0], lambda carry: carry[0]

    minor = live[-1]
    rest = tuple(a for a in axes if a != minor)
    A_minor = sizes[minor]
    m = -(-n // A_minor) * A_minor

    def prep(x, mypos):
        xp = jnp.pad(x, (0, m - n)) if m != n else x
        return (xp, mypos)

    def rs_minor(carry):
        cur, mypos = carry
        return lax.psum_scatter(
            cur, minor, scatter_dimension=0, tiled=True
        ), mypos

    def reduce_rest(carry):
        cur, mypos = carry
        return lax.psum(cur, rest), mypos

    def ag_minor(carry):
        cur, mypos = carry
        return lax.all_gather(cur, minor, axis=0, tiled=True), mypos

    phases = [rs_minor] + ([reduce_rest] if rest else []) + [ag_minor]
    return prep, phases, lambda carry: carry[0][:n]


def build(kind: str, group: ProcessGroup, *, op=None, recv_count=None,
          **_) -> Callable:
    from mlsl_tpu.comm import collectives

    mesh = group.topology.mesh

    def body(x):
        prep, phases, finish = steps(
            kind, group, x.shape[0], op=op, recv_count=recv_count
        )
        carry = prep(x, jnp.int32(0))
        for phase in phases:
            carry = phase(carry)
        return finish(carry)

    return collectives._build_axis(body, mesh, kind, "ring2d")
