"""Two-tier hierarchical collectives: DCN-aware compressed allreduce.

Fifth entry in the selection table (after ``lax``/``rhd``/``ring2d``/
``pallas_ring``). Everything before this lowering assumes one flat/2D ICI
mesh; production scale means pods — fast ICI slices bridged by a slow DCN
tier. The DynamiQ multi-hop shape (PAPERS.md) wins there:

  allreduce(n) over G = T slices x L devices/slice:
    1. intra-slice reduce-scatter (f32, ICI)   -> shard of n/L
    2. inter-slice allreduce over the shard    -> only n/L crosses the DCN,
       per-tier codec applies HERE (int8-blockwise / top-k / f32)
    3. intra-slice all-gather (f32, ICI)       -> full n

The compressed DCN hop is THC-shaped: every slice quantizes its shard
against a SHARED per-block scale (one tiny pmax across slices), the slices
exchange int8 payloads summed in int32 — exact integer arithmetic, no
dequantize/requantize round-trip per hop — and ONE dequantize lands the
result. Round-to-nearest-even entry rounding keeps the per-element
quantization error zero-mean (the bias-corrected integer-sum contract);
what error remains is carried by the same client-side error-feedback
residual the flat quant ring uses, so CommRequest's snapshot/rewind and the
supervisor's degrade-to-f32 flush apply unchanged (the residual inverts to
the logical layout through ``flush_residual`` — each member owns its own
slice's error).

Tier structure derives from ``mesh.world_tier_ids`` (real ``slice_index``
on TPU multislice; the ``MLSL_MESH_TIERS=TxL`` synthetic override lets the
8-dev CPU proof mesh and tier-1 exercise a two-tier split). Groups are
eligible when their members split into T contiguous equal runs of L in
group-rank order — exactly what ``mesh.dcn_aware_devices`` ordering
produces for the data/replica axes.

Like rhd/ring2d, the schedule is exposed as staged ``steps`` shared by the
standalone ``build`` program and the compiled overlap engine: the ICI
phases emit early and the compressed DCN phase is the natural stage
boundary between layers.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mlsl_tpu.comm.mesh import ProcessGroup, world_tier_ids
from mlsl_tpu.comm.collectives import _axis_sizes
from mlsl_tpu.log import mlsl_assert

#: DCN-tier codecs (the ICI tier is always f32 — its phases are exact).
#: int8/f32/topk keep their hand-written bit-exact hops; the rest route
#: through the registry's ``Codec.hier_aggregate`` (mlsl_tpu.codecs).
DCN_CODECS = ("int8", "f32", "topk", "vq", "prune")
DEFAULT_DCN_CODEC = "int8"


def dcn_codec(value: Optional[str] = None) -> str:
    """The DCN-tier codec: explicit value > MLSL_HIER_DCN_CODEC > int8."""
    v = (value if value is not None
         else os.environ.get("MLSL_HIER_DCN_CODEC", "")).strip().lower()
    if not v:
        return DEFAULT_DCN_CODEC
    mlsl_assert(v in DCN_CODECS,
                "MLSL_HIER_DCN_CODEC must be one of %s (got %r)",
                "/".join(DCN_CODECS), v)
    return v


# ---------------------------------------------------------------------------
# Tier structure
# ---------------------------------------------------------------------------


def _live_axis(group: ProcessGroup) -> Optional[str]:
    if group.colors is not None or group.is_self:
        return None
    sizes = _axis_sizes(group.topology.mesh)
    live = [a for a in group.axes if sizes[a] > 1]
    return live[0] if len(live) == 1 else None


def tier_structure(group: ProcessGroup) -> Optional[Tuple[int, int]]:
    """(T, L) when the group's members split into T contiguous equal tiers
    of L members (in group-rank order) under the world tier map, identically
    for every group instance — else None (the flat lowerings apply).

    T==1 (one tier holds the whole group — the degenerate 1xG split) and
    L==1 (every member its own tier, Gx1) are both valid shapes: the
    corresponding ICI/DCN phase simply vanishes."""
    if _live_axis(group) is None or int(group.size) <= 1:
        return None
    tids = world_tier_ids(tuple(group.topology.mesh.devices.flat))
    if tids is None:
        return None
    from mlsl_tpu.comm.collectives import _axis_groups_tbl

    g = int(group.size)
    shape = None
    for row in _axis_groups_tbl(group):
        runs: List[Tuple[int, int]] = []  # (tier id, run length)
        for w in row:
            t = tids[w]
            if runs and runs[-1][0] == t:
                runs[-1] = (t, runs[-1][1] + 1)
            else:
                runs.append((t, 1))
        if len({t for t, _ in runs}) != len(runs):
            return None  # a tier appears in two runs: interleaved layout
        lens = {n for _, n in runs}
        if len(lens) != 1:
            return None
        cur = (len(runs), runs[0][1])
        if shape is None:
            shape = cur
        elif shape != cur:
            return None  # instances see different splits
    if shape is None or shape[0] * shape[1] != g:
        return None
    return shape


def _tier_groups(g: int, t: int, l: int) -> Tuple[list, list]:
    """(intra groups, inter groups) as axis_index_groups over the live axis:
    intra = the L members of each tier (contiguous), inter = the T tier
    peers sharing a local rank."""
    intra = [[ti * l + li for li in range(l)] for ti in range(t)]
    inter = [[ti * l + li for ti in range(t)] for li in range(l)]
    return intra, inter


def _inter_sum(x, axis: str, inter, t: int):
    """Sum over the T tier peers: all_gather + a LOCAL axis-0 sum (this
    jax's shard_map psum does not take axis_index_groups; the gather form is
    exact for the int32 codec payload, and its fixed local summation order
    makes every member's float result bit-identical). T is the pod count —
    small — so the (T-1)x inbound gather traffic stays modest."""
    if t <= 1:
        return x
    g = lax.all_gather(x, axis, axis=0, axis_index_groups=inter)
    return jnp.sum(g, axis=0)


# ---------------------------------------------------------------------------
# Eligibility
# ---------------------------------------------------------------------------


def eligible(kind: str, group: ProcessGroup, op=None) -> bool:
    """Dense eligibility: SUM over a single-live-axis group with a uniform
    two-tier split (the scatter phases are psum_scatter, SUM-only like
    ring2d)."""
    from mlsl_tpu.types import ReductionType

    if op not in (None, ReductionType.SUM):
        return False
    return tier_structure(group) is not None


def eligible_quant(group: ProcessGroup, block: int) -> bool:
    """Compressed eligibility (the QUANTIZATION route through the table):
    allreduce only — the tentpole shape is RS -> compressed AR -> AG; a
    quantized reduce_scatter keeps the flat quant-ring wire."""
    del block  # geometry pads internally; any block size serves
    return tier_structure(group) is not None


# ---------------------------------------------------------------------------
# Dense lowering (f32 both tiers)
# ---------------------------------------------------------------------------


def steps(
    kind: str,
    group: ProcessGroup,
    n: int,
    *,
    op=None,
    recv_count=None,
) -> Tuple[Callable, List[Callable], Callable]:
    """The staged two-tier schedule: ``(prep, phases, finish)``, rhd/ring2d
    carry convention ((x, mypos) rides through; mypos unused — placement is
    axis-index-native). One collective per phase: intra-RS, inter-AR,
    intra-AG, with degenerate tiers (T==1 or L==1) dropping their phases."""
    axis = _live_axis(group)
    tiers = tier_structure(group)
    mlsl_assert(
        axis is not None and tiers is not None,
        "hier needs a single-live-axis group with a uniform tier split "
        "(MLSL_MESH_TIERS or multislice topology); got axes=%s", group.axes,
    )
    t, l = tiers
    g = t * l
    intra, inter = _tier_groups(g, t, l)

    if kind == "reduce_scatter":
        mlsl_assert(
            recv_count is not None and n == g * recv_count,
            "hier reduce_scatter needs count == G*recv_count "
            "(count %d, G %d, recv_count %s)", n, g, recv_count,
        )
        rc = recv_count

        def prep_rs(x, mypos):
            # l-major chunk order so intra-scatter-by-l then inter-scatter-
            # by-t lands group chunk t*L+l on member (t, l) — its own group
            # rank (a local relabeling, no wire)
            xr = jnp.transpose(
                x.reshape(t, l, rc), (1, 0, 2)
            ).reshape(-1)
            return (xr, mypos)

        def rs_intra(carry):
            cur, mypos = carry
            return lax.psum_scatter(
                cur, axis, scatter_dimension=0, tiled=True,
                axis_index_groups=intra,
            ), mypos

        def rs_inter(carry):
            cur, mypos = carry
            return lax.psum_scatter(
                cur, axis, scatter_dimension=0, tiled=True,
                axis_index_groups=inter,
            ), mypos

        phases = ([rs_intra] if l > 1 else []) + ([rs_inter] if t > 1 else [])
        if not phases:
            return prep_rs, [], lambda carry: carry[0][:rc]
        return prep_rs, phases, lambda carry: carry[0]

    sc = -(-n // l)
    m = sc * l

    def prep(x, mypos):
        xp = jnp.pad(x, (0, m - n)) if m != n else x
        return (xp, mypos)

    def rs_intra(carry):
        cur, mypos = carry
        return lax.psum_scatter(
            cur, axis, scatter_dimension=0, tiled=True,
            axis_index_groups=intra,
        ), mypos

    def ar_inter(carry):
        cur, mypos = carry
        return _inter_sum(cur, axis, inter, t), mypos

    def ag_intra(carry):
        cur, mypos = carry
        return lax.all_gather(
            cur, axis, axis=0, tiled=True, axis_index_groups=intra,
        ), mypos

    phases = ([rs_intra] if l > 1 else []) \
        + ([ar_inter] if t > 1 else []) \
        + ([ag_intra] if l > 1 else [])
    return prep, phases, lambda carry: carry[0][:n]


def build(kind: str, group: ProcessGroup, *, op=None, recv_count=None,
          **_) -> Callable:
    from mlsl_tpu.comm import collectives

    mesh = group.topology.mesh

    def body(x):
        prep, phases, finish = steps(
            kind, group, x.shape[0], op=op, recv_count=recv_count
        )
        carry = prep(x, jnp.int32(0))
        for phase in phases:
            carry = phase(carry)
        return finish(carry)

    return collectives._build_axis(body, mesh, kind, "hier")


# ---------------------------------------------------------------------------
# Compressed DCN tier (the QUANTIZATION wire through the table)
# ---------------------------------------------------------------------------


def quant_geometry(
    kind: str, group: ProcessGroup, count: int, block: int
) -> Tuple[int, int, int, Tuple[int, int]]:
    """-> (g, slen, err_len, (T, L)): the hierarchical compressed layout.

    ``slen`` is the per-member DCN shard: ceil(count/L) aligned UP to the
    quant block, so no compressed-tier block ever straddles the intra-slice
    shard boundary (the A114 invariant) and the shared-scale blocks tile the
    shard exactly. The error-feedback residual covers exactly the member's
    own shard (err_len == slen): unlike the flat ring — whose residual spans
    the whole logical buffer in ring-chunk layout — each member only ever
    quantizes its 1/L slice, and the degrade flush re-places that slice at
    its logical offset (``flush_residual``)."""
    mlsl_assert(kind == "allreduce",
                "hier compressed wire serves allreduce only (got %s)", kind)
    tiers = tier_structure(group)
    mlsl_assert(tiers is not None,
                "hier quant geometry needs a tiered group")
    t, l = tiers
    slen = -(-(-(-count // l)) // block) * block
    return t * l, slen, slen, (t, l)


def intra_positions(group: ProcessGroup) -> np.ndarray:
    """(R, D, S, M) int array: each world position's intra-tier rank l — the
    static table the degrade flush uses to re-place a member's residual at
    its own logical slice offset."""
    tiers = tier_structure(group)
    mlsl_assert(tiers is not None, "intra_positions needs a tiered group")
    _, l = tiers
    topo = group.topology
    out = np.zeros(topo.grid_shape, dtype=np.int32)
    w = topo.world_size
    for p in range(w):
        out[topo.coords(p)] = group.group_idx_of(p) % l
    return out


def flush_residual(err, l_idx, L: int, slen: int, count: int):
    """Hier-layout error-feedback residual -> the logical buffer layout.

    ``err``: (*lead, slen) — each member's residual for ITS OWN slice.
    ``l_idx``: (*lead) static intra-tier ranks (``intra_positions``). The
    plain-allreduce degrade flush sums every member's flushed payload, so
    placing each residual at offset l*slen delivers slice l's un-sent error
    exactly once (summed over that slice's tier peers — the same total the
    healthy compressed hop still owed). Padding-region residual beyond
    ``count`` is discarded, like the healthy path truncates its result."""
    lead = err.shape[:-1]
    onehot = jax.nn.one_hot(l_idx, L, dtype=err.dtype)      # (*lead, L)
    placed = onehot[..., :, None] * err[..., None, :]       # (*lead, L, slen)
    return placed.reshape(*lead, L * slen)[..., :count]


def _block_quant_shared(xq, block: int, axis: str, inter, t: int):
    """Shared-scale blockwise int8 for the DCN hop: per-block absmax pmax'd
    across the tier peers (the only extra DCN traffic — one f32 per block),
    quantize-once against the SHARED scale (round-to-nearest-even, the
    zero-mean entry rounding), then exchange the int8 payload — the wire
    stays 1 byte/elem — widening to int32 only in the LOCAL sum: the THC
    shape, the slow tier never dequantizes per hop. -> (red, new_err)."""
    blocks = xq.reshape(-1, block)
    m = jnp.max(jnp.abs(blocks), axis=1)
    if t > 1:
        m = lax.pmax(m, axis, axis_index_groups=inter)
    scale = jnp.where(m == 0, 1.0, m / 127.0).astype(jnp.float32)
    q8 = jnp.clip(
        jnp.round(blocks / scale[:, None]), -127, 127
    ).astype(jnp.int8)
    xhat = (q8.astype(jnp.float32) * scale[:, None]).reshape(-1)
    new_err = xq - xhat
    if t > 1:
        gathered = lax.all_gather(q8, axis, axis=0, axis_index_groups=inter)
        q = jnp.sum(gathered.astype(jnp.int32), axis=0)
    else:
        q = q8.astype(jnp.int32)
    red = (q.astype(jnp.float32) * scale[:, None]).reshape(-1)
    return red, new_err


def _topk_shared(xq, ratio: float, axis: str, inter, t: int):
    """Top-k DCN codec: keep the k largest-|.| shard elements, the rest feed
    the residual; the kept (sparse) payload sums across tiers."""
    k = max(1, int(xq.shape[0] * ratio))
    vals = lax.top_k(jnp.abs(xq), k)[0]
    thr = vals[k - 1]
    keep = jnp.where(jnp.abs(xq) >= thr, xq, 0.0)
    new_err = xq - keep
    return _inter_sum(keep, axis, inter, t), new_err


def quant_steps(
    group: ProcessGroup,
    count: int,
    block: int,
    *,
    codec: Optional[str] = None,
    topk_ratio: float = 0.01,
) -> Tuple[Callable, List[Callable], Callable, int]:
    """Staged compressed-allreduce schedule for the overlap engine:
    ``(prep(x, mypos, err) -> carry, phases, finish(carry) -> (out,
    new_err), err_len)``. Phase boundaries mirror the dense ``steps``: the
    ICI reduce-scatter emits early, the compressed DCN exchange is its own
    phase (the natural stage boundary), the ICI all-gather last."""
    axis = _live_axis(group)
    g, slen, err_len, (t, l) = quant_geometry("allreduce", group, count,
                                              block)
    intra, inter = _tier_groups(g, t, l)
    codec = dcn_codec(codec)
    if t == 1:
        codec = "f32"  # nothing crosses the DCN; never quantize on ICI
    reg = None
    if codec not in ("int8", "topk", "f32"):
        # registry-routed DCN codec: resolve the instance once, outside the
        # traced phases; knobs come from the process env (MLSL_VQ_*,
        # MLSL_PRUNE_RATIO) since quant_steps has no session Config in hand.
        from mlsl_tpu import codecs as codecs_mod
        from mlsl_tpu.config import Config
        reg = codecs_mod.configure(codec, Config.from_env())

    def prep(x, mypos, err):
        xp = x.astype(jnp.float32)
        pad = l * slen - count
        if pad:
            xp = jnp.pad(xp, (0, pad))
        del mypos
        return (xp, err)

    def rs_intra(carry):
        cur, err = carry
        if l == 1:
            return carry
        return lax.psum_scatter(
            cur, axis, scatter_dimension=0, tiled=True,
            axis_index_groups=intra,
        ), err

    def dcn_hop(carry):
        cur, err = carry
        xq = cur + err
        if codec == "int8":
            red, new_err = _block_quant_shared(xq, block, axis, inter, t)
        elif codec == "topk":
            red, new_err = _topk_shared(xq, topk_ratio, axis, inter, t)
        elif reg is not None:  # registry codec: wire exchange + aggregate
            red, new_err = reg.hier_aggregate(xq, axis=axis, inter=inter,
                                              t=t)
        else:  # f32: exact hop, residual fully delivered and reset
            red = _inter_sum(xq, axis, inter, t)
            new_err = jnp.zeros_like(xq)
        return red, new_err

    def ag_intra(carry):
        cur, err = carry
        if l == 1:
            return carry
        return lax.all_gather(
            cur, axis, axis=0, tiled=True, axis_index_groups=intra,
        ), err

    phases = ([rs_intra] if l > 1 else []) + [dcn_hop] \
        + ([ag_intra] if l > 1 else [])
    return prep, phases, lambda carry: (carry[0][:count], carry[1]), err_len


def quant_body(
    kind: str,
    group: ProcessGroup,
    count: int,
    block: int,
    *,
    codec: Optional[str] = None,
    topk_ratio: float = 0.01,
) -> Tuple[Callable, int]:
    """The compressed round as an un-compiled ``(x, err) -> (result,
    new_err)`` shard_map body — quant_ring.inline_body's contract, so
    ``build_quantized_collective(ring='hier')`` compiles it through the same
    ``build_stateful_collective`` scaffolding (and the same chaos roundtrip
    wrapper) as the flat ring."""
    prep, phases, finish, err_len = quant_steps(
        group, count, block, codec=codec, topk_ratio=topk_ratio
    )
    mlsl_assert(kind == "allreduce",
                "hier compressed wire serves allreduce only (got %s)", kind)

    def body(x, err):
        carry = prep(x, jnp.int32(0), err)
        for phase in phases:
            carry = phase(carry)
        return finish(carry)

    return body, err_len


# ---------------------------------------------------------------------------
# Cost model (benchmarks/hier_bench.py DCN bandwidth-delay simulator)
# ---------------------------------------------------------------------------


def dcn_wire_bytes(count: int, tiers: Tuple[int, int], codec: str,
                   block: int) -> int:
    """Bytes one member's DCN link carries for a hier allreduce of ``count``
    f32 elems: the 1/L shard at the codec's wire width, ring-modeled across
    the T tier peers (2(T-1)/T), plus the shared-scale exchange for int8."""
    t, l = tiers
    if t <= 1:
        return 0
    slen = -(-(-(-count // l)) // block) * block
    if codec == "int8":
        per = slen * 1 + 4 * (slen // block)  # q + the shared-scale pmax
    elif codec == "topk":
        per = slen * 4  # dense psum carries the masked shard (sim mesh)
    elif codec not in ("f32", "none"):  # "none" = hier_bench's uncompressed
        from mlsl_tpu import codecs as codecs_mod
        per = codecs_mod.configure(codec).wire_len(slen)  # encoded shard
    else:
        per = slen * 4
    return int(2 * (t - 1) / t * per)


def dcn_phases(tiers: Tuple[int, int], codec: str) -> int:
    """DCN round-trips (latency terms) for one hier allreduce: the shared-
    scale pmax (int8 only) plus the 2(T-1) exchange hops of a ring-modeled
    allreduce across tiers."""
    t, _ = tiers
    if t <= 1:
        return 0
    return 2 * (t - 1) + (1 if codec == "int8" else 0)
