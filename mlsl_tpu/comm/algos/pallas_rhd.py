"""The ``pallas_rhd`` algos-engine lowering: latency-class fused allreduce.

Recursive halving/doubling (the ``rhd`` pair math, eplib/allreduce_pr.c) as
ONE Pallas kernel (ops/rhd_kernels.py): 2*log2(G) symmetric remote-DMA
exchange rounds between VMEM slots instead of the ring's 2(G-1) hops — the
small-message (``msg_priority_threshold``-class) regime where per-hop
latency, not algbw, decides (ROADMAP #1, decode-time serving).

Selection: a forced ``MLSL_ALGO=pallas_rhd`` or a tuned-profile cell works
like every other algorithm; additionally the heuristic rung prefers this
kernel for sub-``MLSL_PALLAS_RHD_MAX_BYTES`` dense SUM allreduces when the
operator armed ``MLSL_PALLAS_RHD=1`` — an explicit knob, so untuned default
behavior stays bit-for-bit the baseline (the engine's founding contract).

``build`` compiles the standalone host-dispatch program over the flat world
mesh (interpreter-executable off-TPU for tier-1 parity); ``steps`` exposes
the compiled-overlap phase form (TPU only — rhd_kernels.inline_ok)."""

from __future__ import annotations

from typing import Callable

from mlsl_tpu.comm.mesh import ProcessGroup
from mlsl_tpu.log import mlsl_assert


def eligible(kind: str, group: ProcessGroup, op=None) -> bool:
    from mlsl_tpu.ops import rhd_kernels

    return rhd_kernels.eligible(kind, group, op)


def steps(kind: str, group: ProcessGroup, count: int, *, op=None,
          recv_count=None, slots=None):
    from mlsl_tpu.ops import rhd_kernels

    return rhd_kernels.steps(kind, group, count, op=op,
                             recv_count=recv_count, slots=slots)


def build(kind: str, group: ProcessGroup, *, op=None, recv_count=None,
          slots=None, **_) -> Callable:
    """Compile the standalone pallas_rhd program (build_collective calling
    convention); geometry resolves at trace time from the buffer length."""
    from mlsl_tpu.ops import rhd_kernels
    from mlsl_tpu.ops import ring_kernels as rk

    mlsl_assert(eligible(kind, group, op),
                "pallas_rhd cannot lower %s on this group/backend", kind)

    def body(x):
        inner = rhd_kernels.allreduce_body(
            group, int(x.shape[0]), slots=slots,
        )
        return inner(x)

    return rk.build_flat_program(body, group, kind)
