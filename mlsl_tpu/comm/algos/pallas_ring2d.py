"""The ``pallas_ring2d`` algos-engine lowering: the fused ring on a 2D torus.

The SAME fused ring kernel as ``pallas_ring`` (ops/ring_kernels.py) — only
the neighbor addressing changes: the ring is the boustrophedon (snake)
Hamiltonian cycle of a 2-live-axis sub-torus, whose edge set mixes minor-
axis links inside each row with major-axis links between rows, so ONE ring
keeps both ICI axes' links busy. With ``MLSL_PALLAS_RING_BIDIR`` the PR 10
block-row split then rides each link's two directions on top — both axes,
both directions, one kernel.

Covers exactly the groups the 1D ``pallas_ring`` refuses (two live axes,
where ``ring2d``'s composed lax phases were the only topology-aware option).
``build``/``steps`` follow pallas_ring verbatim."""

from __future__ import annotations

from typing import Callable

from mlsl_tpu.comm.mesh import ProcessGroup
from mlsl_tpu.log import mlsl_assert


def eligible(kind: str, group: ProcessGroup, op=None) -> bool:
    from mlsl_tpu.ops import ring_kernels

    return ring_kernels.eligible_dense2d(kind, group, op)


def steps(kind: str, group: ProcessGroup, count: int, *, op=None,
          recv_count=None, slots=None, bidir=None):
    from mlsl_tpu.ops import ring_kernels

    return ring_kernels.steps(kind, group, count, op=op,
                              recv_count=recv_count, slots=slots,
                              bidir=bidir, snake=True)


def build(kind: str, group: ProcessGroup, *, op=None, recv_count=None,
          slots=None, bidir=None, **_) -> Callable:
    """Compile the standalone snake-ring program (build_collective calling
    convention); geometry resolves at trace time from the buffer length."""
    from mlsl_tpu.ops import ring_kernels as rk

    mlsl_assert(eligible(kind, group, op),
                "pallas_ring2d cannot lower %s on this group/backend", kind)

    def body(x):
        inner = rk.dense_ring_body(
            kind, group, int(x.shape[0]), x.dtype,
            recv_count=recv_count, slots=slots, bidir=bidir, snake=True,
        )
        return inner(x)

    return rk.build_flat_program(body, group, kind)
