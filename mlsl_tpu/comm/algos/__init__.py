"""Collective algorithm engine: multiple lowering strategies per collective.

The reference eplib ships TWO allreduce implementations — the MPI-native one
and a recursive-halving/doubling priority allreduce (eplib/allreduce_pr.c) —
selected by environment knobs. Our TPU port until now lowered every
collective to exactly one ``lax`` program. This package restores (and
extends) the algorithm dimension:

- ``lax``     — the single-shot XLA-native body (comm/collectives.py): psum /
                psum_scatter / gather emulation. The baseline and the
                heuristic default; untuned behavior is bit-for-bit this.
- ``rhd``     — recursive halving/doubling composed from the pairwise
                exchange primitive (``lax.ppermute``, the same op behind the
                sendrecv body): log2(G) rounds of halving (reduce-scatter)
                and doubling (all-gather), with the classic pre/post fold
                remainder step for non-power-of-two groups. Paper parity
                with eplib/allreduce_pr.c. Latency-optimal round count.
- ``ring2d``  — hierarchical ring-of-rings for multi-axis (torus) groups:
                reduce-scatter along the minor mesh axis, reduce over the
                remaining axes, all-gather back along the minor axis. Each
                phase rides ONE physical ICI ring instead of asking XLA to
                fuse a reduction over the whole sub-torus (EQuARX/DynamiQ
                both report the multi-hop topology-aware decomposition is
                where large-group allreduce wins live).
- ``pallas_ring`` — the hand-written fused ring kernel (ops/ring_kernels.py,
                algos/pallas_ring.py): double-buffered
                ``make_async_remote_copy`` RDMA per hop with the int8 codec
                fused inside the kernel at the VMEM boundary. Single-live-
                axis ring groups on TPU (or under the explicit
                MLSL_PALLAS_INTERPRET gate off-chip); dense f32/bf16/i32
                here, and the int8-quantized variant of the same kernel
                selectable for COMPRESSION=QUANTIZATION requests (a
                compressed case the table routes — quant_ring's
                ``ring='pallas'`` wire).
- ``pallas_rhd`` — the latency-class fused allreduce (ops/rhd_kernels.py,
                algos/pallas_rhd.py): recursive halving/doubling as ONE
                Pallas kernel — 2*log2(G) remote-DMA exchange rounds between
                VMEM slots with pre/post folds for non-power-of-two groups
                (rhd's exact pair math). The small-message regime's answer:
                selected by tuned cells / MLSL_ALGO like any algorithm, and
                by the heuristic rung for sub-payload-band dense SUM
                allreduces when MLSL_PALLAS_RHD armed it.
- ``pallas_ring2d`` — the fused ring riding a 2-live-axis sub-torus
                (algos/pallas_ring2d.py): the SAME kernel as pallas_ring
                over the snake (boustrophedon) Hamiltonian cycle, so one
                ring drives both ICI axes' links (and both directions of
                each with MLSL_PALLAS_RING_BIDIR) — the groups the 1D ring
                refuses and ring2d served with composed lax phases.
- ``pallas_a2a`` — the fused quantized all-to-all (ops/a2a_kernels.py,
                algos/pallas_a2a.py) and the first member of the NEW
                ``alltoall`` engine kind: MoE dispatch/combine with the
                int8 blockwise codec fused at the VMEM boundary (quantize
                on send-slot write, dequantize on receive — wire bytes
                <= 1/3 of f32). models/moe.py routes through
                ``inline_alltoall``'s selection instead of hardcoded lax.
- ``hier``    — two-tier hierarchical allreduce for pod-scale worlds
                (algos/hier.py): intra-slice reduce-scatter -> inter-slice
                allreduce over the 1/L shard -> intra-slice all-gather,
                with a per-tier codec (f32 on ICI; int8-blockwise/top-k on
                the DCN hop via quant_ring's ``ring='hier'`` wire — a
                THC-style shared-scale integer sum that never dequantizes
                per hop). Tier structure from ``mesh.world_tier_ids``
                (``MLSL_MESH_TIERS`` override / multislice ``slice_index``).

Selection (``select``) is keyed by (kind, payload bytes, group shape,
compression) with strict precedence:

    explicit config (MLSL_ALGO)  >  tuned profile (mlsl_tpu.tuner)  >
    heuristic default ("lax")

The heuristic default is deliberately the baseline: with no explicit knob
and no measured profile the dispatched programs are bit-for-bit what they
were before this engine existed. Only a measurement (the tuner) or an
explicit operator override changes the program.

Programs built here are cached in the SAME cache as the baseline
(collectives._cache) with the algorithm name in the key, wrapped in the same
chaos-dispatch instrumentation, and therefore cleared by
collectives.clear_cache() and warmed by MLSL_PRECOMPILE like every other
collective program (the plan-cache key carries the algorithm identity —
core/session.py).
"""

from __future__ import annotations

from typing import Callable, Tuple

import numpy as np

from mlsl_tpu.comm.mesh import ProcessGroup
from mlsl_tpu.log import log_debug, mlsl_assert
from mlsl_tpu.types import CompressionType, ReductionType

#: the baseline algorithm: the single-shot lax program (comm/collectives.py)
DEFAULT = "lax"

#: engine kinds: the elementwise-reduction collectives (the reference's
#: algorithm choice is likewise allreduce-first) plus — new with the fused
#: kernel family — the MoE dispatch/combine exchange
ENGINE_KINDS = ("allreduce", "reduce_scatter", "alltoall")


def group_shape(group: ProcessGroup) -> Tuple[int, ...]:
    """The selection-table shape key for a group: per-axis member counts for
    axis-aligned groups (major -> minor, degenerate size-1 axes dropped so a
    4-axis global group over a (1, 4, 1, 2) grid and a 2-axis (4, 2) group
    share one profile cell), ``(-G,)`` for color groups (the sign marks
    'color' so a color group never aliases a 1D axis group of the same
    size)."""
    if group.colors is not None:
        return (-int(group.size),)
    topo = group.topology
    sizes = dict(zip(topo.mesh.axis_names, topo.mesh.devices.shape))
    shape = tuple(int(sizes[a]) for a in group.axes if sizes[a] > 1)
    return shape or (1,)


def _eligible_rhd(kind: str, group: ProcessGroup, op) -> bool:
    # uniform groups only (the pairwise schedule needs equal member counts);
    # any op (pairwise combine handles MIN/MAX, unlike ring/scatter forms)
    if group.is_self or not group.is_uniform:
        return False
    if group.size <= 1:
        return False
    if kind == "reduce_scatter" and op not in (None, ReductionType.SUM,
                                               ReductionType.MIN,
                                               ReductionType.MAX):
        return False
    return True


def _eligible_ring2d(kind: str, group: ProcessGroup, op) -> bool:
    # SUM only (the scatter phases are psum_scatter) on axis-aligned groups
    # spanning >= 2 non-degenerate mesh axes (a real sub-torus)
    if group.colors is not None or op not in (None, ReductionType.SUM):
        return False
    live = [s for s in group_shape(group) if s > 1]
    if len(live) < 2:
        return False
    if kind == "reduce_scatter" and len(live) != 2:
        # the 2-phase scatter placement math is 2D; >2 live axes fall back
        return False
    return True


def _eligible_pallas_ring(kind: str, group: ProcessGroup, op) -> bool:
    # single-live-axis ring groups, SUM only, and only on a backend that can
    # run the kernel (TPU, or the explicit interpret gate) — lazily imported
    # so the registry stays importable from config validation without jax
    from mlsl_tpu.ops import ring_kernels

    return ring_kernels.eligible_dense(kind, group, op)


def _eligible_hier(kind: str, group: ProcessGroup, op) -> bool:
    # single-live-axis groups with a uniform two-tier split (MLSL_MESH_TIERS
    # or multislice topology), SUM only — lazily imported like pallas_ring
    from mlsl_tpu.comm.algos import hier

    return hier.eligible(kind, group, op)


def _eligible_pallas_rhd(kind: str, group: ProcessGroup, op) -> bool:
    # allreduce only, SUM only, single-live-axis uniform groups, and a
    # backend that can run the kernel — lazily imported like pallas_ring
    from mlsl_tpu.ops import rhd_kernels

    return rhd_kernels.eligible(kind, group, op)


def _eligible_pallas_ring2d(kind: str, group: ProcessGroup, op) -> bool:
    # exactly two live mesh axes (the snake cycle is 2D), SUM only
    from mlsl_tpu.ops import ring_kernels

    return ring_kernels.eligible_dense2d(kind, group, op)


def _eligible_pallas_a2a(kind: str, group: ProcessGroup, op) -> bool:
    # alltoall only (op-less), single-live-axis or color-flat uniform groups
    from mlsl_tpu.ops import a2a_kernels

    return a2a_kernels.eligible(kind, group, op=op)


#: name -> eligibility predicate; builders are resolved lazily (the bodies
#: import jax)
_ELIGIBLE = {
    "lax": lambda kind, group, op: True,
    "rhd": _eligible_rhd,
    "ring2d": _eligible_ring2d,
    "pallas_ring": _eligible_pallas_ring,
    "pallas_rhd": _eligible_pallas_rhd,
    "pallas_ring2d": _eligible_pallas_ring2d,
    "pallas_a2a": _eligible_pallas_a2a,
    "hier": _eligible_hier,
}

ALGORITHMS = tuple(_ELIGIBLE)


def eligible(algo: str, kind: str, group: ProcessGroup, op=None) -> bool:
    """Can ``algo`` lower (kind, group, op)? Unknown names are never eligible."""
    if kind not in ENGINE_KINDS:
        return algo == DEFAULT
    if kind == "alltoall" and algo not in (DEFAULT, "pallas_a2a"):
        # the reduction algorithms' predicates predate the alltoall kind and
        # do not check it — the central guard keeps a global MLSL_ALGO=rhd
        # from claiming the MoE exchange it cannot lower
        return False
    pred = _ELIGIBLE.get(algo)
    return bool(pred and pred(kind, group, op))


def candidates(kind: str, group: ProcessGroup, op=None) -> Tuple[str, ...]:
    """Every algorithm eligible for (kind, group, op), baseline first."""
    return tuple(a for a in ALGORITHMS if eligible(a, kind, group, op))


def parse_forced(spec: str) -> dict:
    """Parse MLSL_ALGO: either one algorithm name (forced for every engine
    kind) or a comma list of kind=name entries. Raises MLSLError (via
    mlsl_assert) on unknown algorithm or kind names — the config-validation
    contract: a contradictory setting fails at init, not deep in dispatch."""
    spec = (spec or "").strip()
    out: dict = {}
    if not spec:
        return out
    if "=" not in spec:
        mlsl_assert(
            spec in ALGORITHMS,
            "MLSL_ALGO %r is not a registered collective algorithm "
            "(registry: %s)", spec, ", ".join(ALGORITHMS),
        )
        out["*"] = spec
        return out
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        mlsl_assert("=" in part, "MLSL_ALGO entry %r is not kind=algo", part)
        kind, _, name = part.partition("=")
        kind, name = kind.strip(), name.strip()
        mlsl_assert(
            kind in ENGINE_KINDS,
            "MLSL_ALGO kind %r is not an engine collective (expected one of "
            "%s)", kind, ", ".join(ENGINE_KINDS),
        )
        mlsl_assert(
            name in ALGORITHMS,
            "MLSL_ALGO %r for kind %r is not a registered collective "
            "algorithm (registry: %s)", name, kind, ", ".join(ALGORITHMS),
        )
        out[kind] = name
    return out


def select(
    kind: str,
    group: ProcessGroup,
    payload_bytes: int,
    compression: CompressionType,
    config,
    op=None,
) -> str:
    """The selection table: explicit config > tuned profile > heuristic
    default. An explicit or tuned choice that is not eligible for this
    (kind, group, op) falls back to the baseline with a debug log — forcing
    ``rhd`` globally must not break the ragged-color-group requests the
    pairwise schedule cannot serve."""
    if kind not in ENGINE_KINDS or config is None:
        return DEFAULT
    if compression != CompressionType.NONE:
        # Compressed collectives have their own wire formats (quant ring /
        # sparse top-k); the engine's dense algorithms do not apply — with
        # TWO exceptions the table routes: the fused pallas ring has an
        # int8-quantized variant (quant_ring's ring='pallas' wire), and the
        # two-tier 'hier' lowering carries the compressed wire on its DCN
        # hop only (quant_ring's ring='hier' wire — intra-slice phases stay
        # f32). A forced or tuned choice of either is honored for
        # QUANTIZATION when the group qualifies; everything else keeps the
        # composed flat ring.
        if (
            compression == CompressionType.QUANTIZATION
            and getattr(config, "custom_codec", None) is None
        ):
            name = _requested(kind, group, payload_bytes, compression, config)
            if name == "pallas_ring" and _quant_pallas_eligible(group, config):
                return _breaker_gate(name, kind)
            if name == "hier" and _quant_hier_eligible(kind, group, config):
                return _breaker_gate(name, kind)
            if name in ("pallas_ring", "hier"):
                log_debug(
                    "%s not eligible for quantized %s on group %s; "
                    "keeping the composed quant ring", name, kind,
                    group_shape(group),
                )
        return DEFAULT
    name = _requested(kind, group, payload_bytes, compression, config)
    if name and name != DEFAULT:
        if eligible(name, kind, group, op):
            return _breaker_gate(name, kind)
        log_debug(
            "selected algorithm %s not eligible for %s on group %s; "
            "falling back to %s", name, kind, group_shape(group), DEFAULT,
        )
        return DEFAULT
    if name == DEFAULT:
        # an explicit or tuned 'lax' pins the baseline — the heuristic rung
        # must not override an operator's measured/forced choice
        return DEFAULT
    # Heuristic rung (below explicit and tuned): the latency-class fused
    # allreduce for payloads inside the small-message band — ONLY when the
    # operator armed MLSL_PALLAS_RHD, so with no knob and no profile the
    # dispatched program stays bit-for-bit the baseline (the engine's
    # founding contract).
    if (
        kind == "allreduce"
        and getattr(config, "pallas_rhd", False)
        and eligible("pallas_rhd", kind, group, op)
    ):
        from mlsl_tpu.ops import rhd_kernels

        if payload_bytes <= rhd_kernels.env_max_bytes(config):
            return _breaker_gate("pallas_rhd", kind)
    return DEFAULT


def _requested(kind, group, payload_bytes, compression, config):
    """The raw forced/tuned choice for this cell, eligibility unchecked:
    explicit config (MLSL_ALGO) first, else the tuned profile's cell, else
    None."""
    forced = getattr(config, "_forced_algos", None)
    if forced:
        name = forced.get(kind) or forced.get("*")
        if name:
            return name
    profile = getattr(config, "tuned_profile", None)
    if profile is not None:
        return profile.select(kind, group_shape(group), compression,
                              payload_bytes)
    return None


def _quant_pallas_eligible(group: ProcessGroup, config) -> bool:
    from mlsl_tpu.ops import ring_kernels

    block = int(getattr(config, "quant_block_elems", 256))
    return ring_kernels.eligible_quant(group, block)


def _quant_hier_eligible(kind: str, group: ProcessGroup, config) -> bool:
    from mlsl_tpu.comm.algos import hier

    if kind != "allreduce":
        return False
    block = int(getattr(config, "quant_block_elems", 256))
    return hier.eligible_quant(group, block)


def _breaker_gate(name: str, kind: str) -> str:
    """Rung 3 at selection time: a non-baseline choice is honored only while
    the algo-engine circuit breaker admits it (mlsl_tpu.supervisor). An OPEN
    breaker pins NEW requests to the baseline; requests already built degrade
    per dispatch in CommRequest. Lazy import: the registry must stay
    importable from config validation."""
    from mlsl_tpu import supervisor

    if not supervisor.breaker("algo").allow():
        log_debug(
            "algo breaker open: %s for %s degrades to %s", name, kind, DEFAULT
        )
        return DEFAULT
    return name


def inline_eligible(algo: str, kind: str, group: ProcessGroup, op=None) -> bool:
    """Can ``algo`` be embedded IN-GRAPH (compiled overlap, comm/overlap.py)
    for (kind, group, op)? A strict subset of ``eligible``: the in-graph
    phase builders ride the group's own mesh axes, and a color group's axes
    are ``()`` (core/distribution.py builds them over the flat mesh), so NO
    algorithm — the baseline included — can reduce one in-graph: a psum
    over zero axes would be a silent identity, not a per-color reduction.
    Color-group graphs ride the host path (the standalone flat-mesh
    programs); only degenerate (size-1) color groups pass, where the
    identity IS the reduction. ``pallas_ring`` additionally requires a
    backend whose in-graph form can execute (TPU: the Pallas interpreter
    cannot resolve remote DMA inside the 4-axis grid shard_map, so off-chip
    the overlap plan falls back to the baseline)."""
    if group.colors is not None and int(group.size) > 1:
        return False
    if algo == "pallas_ring":
        from mlsl_tpu.ops import ring_kernels

        if not ring_kernels.inline_ok(group):
            return False
    if algo == "pallas_rhd":
        from mlsl_tpu.ops import rhd_kernels

        if not rhd_kernels.inline_ok(group):
            return False
    if algo == "pallas_ring2d":
        from mlsl_tpu.ops import ring_kernels

        if not ring_kernels.inline_ok2d(group):
            return False
    if algo == "pallas_a2a":
        from mlsl_tpu.ops import a2a_kernels

        if not a2a_kernels.inline_ok(group):
            return False
    return eligible(algo, kind, group, op)


def inline_plan(kind: str, group: ProcessGroup, algo: str, count: int, *,
                op=None, recv_count=None, config=None):
    """The in-graph (compiled-overlap) form of ``algo``: ``(prep, phases,
    finish)`` closures usable inside a shard_map body over the group's own
    topology mesh — ``prep(x, mypos) -> carry``, each ``phases[i](carry) ->
    carry`` is one collective phase (the unit the overlap scheduler
    interleaves between layers), ``finish(carry) -> result``. ``mypos`` must
    be the member's flattened group position (collectives._group_rank over
    the group axes); ``count`` is the static per-member element count.

    ``lax`` is the single-phase baseline (the exact ``_body_allreduce`` /
    ``_body_reduce_scatter`` ops); ``rhd``/``ring2d`` expose the same phase
    sequences their standalone ``build`` programs compile — one ppermute
    round / one ring phase per entry, bit-for-bit the same math.
    """
    from mlsl_tpu.comm import collectives
    from mlsl_tpu.types import ReductionType

    mlsl_assert(
        inline_eligible(algo, kind, group, op),
        "algorithm %s cannot lower %s in-graph on group shape %s",
        algo, kind, group_shape(group),
    )
    rop = ReductionType(op) if op is not None else ReductionType.SUM
    if group.is_self or group.size <= 1:
        # degenerate group: every reduction is the identity (the compiled
        # per-layer schedule is still measurable — bench.py's single-chip row)
        if kind == "reduce_scatter" and recv_count is not None:
            return (lambda x, mypos: (x, mypos), [],
                    lambda carry: carry[0][:recv_count])
        return lambda x, mypos: (x, mypos), [], lambda carry: carry[0]
    if kind == "alltoall":
        if algo == DEFAULT:
            from jax import lax as _lax

            ax = group.axes if len(group.axes) > 1 else group.axes[0]
            g = int(group.size)

            def lax_a2a(carry):
                cur, mypos = carry
                out = _lax.all_to_all(cur.reshape(g, -1), ax,
                                      split_axis=0, concat_axis=0)
                return out.reshape(-1), mypos

            return (lambda x, mypos: (x, mypos), [lax_a2a],
                    lambda carry: carry[0])
        from mlsl_tpu.comm.algos import pallas_a2a
        from mlsl_tpu.ops import a2a_kernels

        # codec/slot knobs from the caller's config, same contract as the
        # fused ring: the in-graph kernel runs the host path's geometry
        return pallas_a2a.steps(
            kind, group, count,
            block=int(getattr(config, "quant_block_elems", 256)),
            quantized=a2a_kernels.quant_enabled(config),
            slots=getattr(config, "pallas_ring_slots", None),
        )
    if algo == DEFAULT:
        sizes = collectives._axis_sizes(group.topology.mesh)

        def lax_phase(carry):
            cur, mypos = carry
            if kind == "reduce_scatter":
                return collectives._body_reduce_scatter(
                    cur, axes=group.axes, sizes=sizes, op=rop,
                    recv_count=recv_count,
                ), mypos
            return collectives._preduce(cur, group.axes, rop), mypos

        return lambda x, mypos: (x, mypos), [lax_phase], lambda carry: carry[0]
    if algo == "rhd":
        from mlsl_tpu.comm.algos import rhd

        ax = group.axes if len(group.axes) > 1 else group.axes[0]
        return rhd.steps(
            kind, int(group.size), count, ax, lambda pairs: pairs,
            op=rop, recv_count=recv_count,
        )
    if algo == "pallas_ring":
        from mlsl_tpu.comm.algos import pallas_ring

        # kernel-geometry knobs come from the caller's config (tuned
        # profiles apply there) — the in-graph kernel must run the same
        # slot geometry as the host-path requests
        return pallas_ring.steps(
            kind, group, count, op=rop, recv_count=recv_count,
            slots=getattr(config, "pallas_ring_slots", None),
            bidir=getattr(config, "pallas_ring_bidir", None),
        )
    if algo == "pallas_rhd":
        from mlsl_tpu.comm.algos import pallas_rhd

        return pallas_rhd.steps(
            kind, group, count, op=rop, recv_count=recv_count,
            slots=getattr(config, "pallas_ring_slots", None),
        )
    if algo == "pallas_ring2d":
        from mlsl_tpu.comm.algos import pallas_ring2d

        return pallas_ring2d.steps(
            kind, group, count, op=rop, recv_count=recv_count,
            slots=getattr(config, "pallas_ring_slots", None),
            bidir=getattr(config, "pallas_ring_bidir", None),
        )
    if algo == "hier":
        from mlsl_tpu.comm.algos import hier

        return hier.steps(kind, group, count, op=rop, recv_count=recv_count)
    from mlsl_tpu.comm.algos import ring2d

    return ring2d.steps(kind, group, count, op=rop, recv_count=recv_count)


def build(kind: str, group: ProcessGroup, dtype, algo: str, **kw) -> Callable:
    """Build (or fetch) the compiled program for ``algo``: global distributed
    buffer -> global result buffer, the exact calling convention of
    collectives.build_collective. ``algo='lax'`` IS build_collective — same
    cache entry, same key, bit-for-bit the baseline program."""
    from mlsl_tpu.comm import collectives

    if algo == DEFAULT:
        return collectives.build_collective(kind, group, dtype, **kw)
    mlsl_assert(
        eligible(algo, kind, group, kw.get("op")),
        "algorithm %s cannot lower %s on group shape %s",
        algo, kind, group_shape(group),
    )
    key = (
        "algo", algo, kind, collectives._group_key(group),
        np.dtype(dtype).str, tuple(sorted(kw.items())),
    )
    fn = collectives._cache.get(key)
    if fn is not None:
        return fn
    if algo == "rhd":
        from mlsl_tpu.comm.algos import rhd as impl
    elif algo == "pallas_ring":
        from mlsl_tpu.comm.algos import pallas_ring as impl
    elif algo == "pallas_rhd":
        from mlsl_tpu.comm.algos import pallas_rhd as impl
    elif algo == "pallas_ring2d":
        from mlsl_tpu.comm.algos import pallas_ring2d as impl
    elif algo == "pallas_a2a":
        from mlsl_tpu.comm.algos import pallas_a2a as impl
    elif algo == "hier":
        from mlsl_tpu.comm.algos import hier as impl
    else:
        from mlsl_tpu.comm.algos import ring2d as impl
    fn = collectives._chaos_dispatch(impl.build(kind, group, **kw), kind)
    collectives._cache[key] = fn
    return fn


# ---------------------------------------------------------------------------
# Engine-owned in-graph collectives for SPMD model/parallel code
# ---------------------------------------------------------------------------
#
# Model and parallelism modules (models/moe.py, parallel/pipeline.py) used to
# embed raw ``lax.p*`` calls inside their shard_map bodies, each carrying an
# A201 lint pragma. These helpers move the raw call INTO the engine: the one
# call site future alternative lowerings (a DCN-staged hierarchical alltoall,
# a tiered gather) slot in behind, and the place the selection table applies
# when the caller can hand over a ProcessGroup. A body-local collective with
# only an axis name lowers to the lax baseline.


def inline_allreduce(x, axis, *, group: ProcessGroup = None, config=None,
                     op=None):
    """In-graph allreduce for shard_map interiors. With ``group`` (and
    config) the selection table picks the lowering — on a two-tier world
    that is the hierarchical decomposition — executed to completion through
    ``inline_plan``; with only ``axis`` the lax baseline applies."""
    from jax import lax as _lax

    from mlsl_tpu.types import ReductionType

    rop = ReductionType(op) if op is not None else ReductionType.SUM
    if group is not None and not group.is_self and int(group.size) > 1:
        count = int(np.prod(x.shape))
        algo = select("allreduce", group, count * 4, CompressionType.NONE,
                      config, op=rop)
        if algo != DEFAULT and inline_eligible(algo, "allreduce", group, rop):
            from mlsl_tpu.comm import collectives

            sizes = collectives._axis_sizes(group.topology.mesh)
            prep, phases, finish = inline_plan(
                "allreduce", group, algo, count, op=rop, config=config,
            )
            carry = prep(x.reshape(-1),
                         collectives._group_rank(group.axes, sizes))
            for phase in phases:
                carry = phase(carry)
            return finish(carry).reshape(x.shape)
        axis = group.axes
    if rop == ReductionType.SUM:
        return _lax.psum(x, axis)
    if rop == ReductionType.MIN:
        return _lax.pmin(x, axis)
    return _lax.pmax(x, axis)


def inline_alltoall(x, axis, *, split_axis=0, concat_axis=0, tiled=False,
                    group: ProcessGroup = None, config=None):
    """In-graph alltoall (the MoE expert dispatch/combine exchange). With
    ``group`` (and config) the selection table picks the lowering — a forced
    ``MLSL_ALGO=alltoall=pallas_a2a`` or a tuned cell routes the exchange
    through the fused quantized kernel; with only ``axis`` (or a selected
    kernel the backend cannot emit in-graph) the lax baseline applies, with
    a debug log naming the fallback so an operator forcing the kernel
    off-TPU sees WHY the wire stayed f32.

    The kernel path applies to the MoE layout specifically: leading dim ==
    group size, ``split_axis == concat_axis == 0``, untiled — exactly the
    flat chunks-by-member convention ops/a2a_kernels.py exchanges."""
    from jax import lax as _lax

    if (
        group is not None and not group.is_self and int(group.size) > 1
        and split_axis == 0 and concat_axis == 0 and not tiled
        and int(x.shape[0]) == int(group.size)
        and x.dtype == np.float32  # the fused kernel's codec/scratch are f32
    ):
        count = int(np.prod(x.shape))
        algo = select("alltoall", group, count * 4, CompressionType.NONE,
                      config)
        if algo != DEFAULT:
            if inline_eligible(algo, "alltoall", group):
                from mlsl_tpu.comm import collectives

                sizes = collectives._axis_sizes(group.topology.mesh)
                prep, phases, finish = inline_plan(
                    "alltoall", group, algo, count, config=config,
                )
                carry = prep(x.reshape(-1),
                             collectives._group_rank(group.axes, sizes))
                for phase in phases:
                    carry = phase(carry)
                return finish(carry).reshape(x.shape)
            log_debug(
                "alltoall algorithm %s selected but not emittable in-graph "
                "on this backend/group; falling back to the lax exchange",
                algo,
            )
    return _lax.all_to_all(x, axis, split_axis=split_axis,
                           concat_axis=concat_axis, tiled=tiled)


def inline_allgather(x, axis, *, gather_axis=0, tiled=True):
    """In-graph all-gather (the MoE output reassembly)."""
    from jax import lax as _lax

    return _lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)
