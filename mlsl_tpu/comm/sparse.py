"""Top-k sparse gradient allreduce with error feedback.

Motivated by importance-weighted pruning on ring allreduce (PAPERS.md; the
family of gradient-compression methods the reference's quantization hook anticipated):
each rank contributes only its k largest-magnitude gradient elements per step; the
un-sent residual is carried in an error-feedback buffer so every coordinate is
eventually applied (same accumulator discipline as the int8 path / reference
quant/quant.c's diff map).

Wire format per member: (k fp32 values, k int32 indices); for groups below
RING_THRESHOLD they are all-gathered ((G, k) peak state) and scatter-added; at or
above it each member's pair circulates the ring with O(k) peak wire state per rank.
Bytes per member: 8k vs 4n dense — a win for k << n (the typical top-k regime is
k/n ~ 1%). Exactness contract: the result equals the sum of every member's
top-k-sparsified contribution, identical across both formats.

Registry note (mlsl_tpu.codecs): ``TopKCodec`` exposes this wire behind the
codec-lab contract — a calibrated cell or ``MLSL_CODEC=topk`` routes a
QUANTIZATION-compressed request here with the ratio from the cell, and the
generalized ``PruneCodec`` (bit-packed mask + kept values, EF residual
carry) is this module's importance-weighted successor on the registry's
compressed-ring transport.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax.numpy as jnp
from jax import lax

from mlsl_tpu.comm.collectives import _gather_group
from mlsl_tpu.comm.mesh import ProcessGroup
from mlsl_tpu.log import mlsl_assert

_cache: dict = {}


# at or above this group size the ring format replaces the all-gather: the
# gathered (G, k) buffers stop being "small" and the ring keeps peak per-rank
# wire memory at O(k)
RING_THRESHOLD = 16


def _sparse_body(x, err, *, axes, sizes, k, n, recv_count, use_ring):
    """Local body: (n,), (n,) -> (result, new_err).

    result is the dense sum of sparsified contributions (allreduce), or this
    member's slice of it (reduce_scatter, recv_count is not None)."""
    xq = x.astype(jnp.float32) + err
    _, idx = lax.top_k(jnp.abs(xq), k)
    vals = jnp.take(xq, idx)
    # residual: everything not selected this step
    sparse_mine = jnp.zeros((n,), jnp.float32).at[idx].set(vals)
    new_err = xq - sparse_mine

    if axes and use_ring:
        out = _ring_merge(sparse_mine, vals, idx, axes[0], sizes[axes[0]], n)
    elif axes:
        all_vals = _gather_group(vals, axes)            # (G, k)
        all_idx = _gather_group(idx, axes)              # (G, k)
        out = jnp.zeros((n,), jnp.float32).at[all_idx.reshape(-1)].add(
            all_vals.reshape(-1)
        )
    else:
        out = sparse_mine
    if recv_count is not None:
        from mlsl_tpu.comm.collectives import _group_rank

        me = _group_rank(axes, sizes) if axes else 0
        out = lax.dynamic_slice_in_dim(out, me * recv_count, recv_count, axis=0)
    return out, new_err


def _ring_merge(own_dense, vals, idx, axis: str, g: int, n: int):
    """Circulate each rank's (vals, idx) around the ring, scatter-adding arrivals:
    peak per-rank wire state is one (k,) pair instead of the (G, k) gather."""
    perm = [(i, (i + 1) % g) for i in range(g)]

    def hop(_, carry):
        out, v_cur, i_cur = carry
        v_cur = lax.ppermute(v_cur, axis, perm)
        i_cur = lax.ppermute(i_cur, axis, perm)
        return out.at[i_cur].add(v_cur), v_cur, i_cur

    out, _, _ = lax.fori_loop(0, g - 1, hop, (own_dense, vals, idx))
    return out


def build_sparse_collective(
    kind: str, group: ProcessGroup, count: int, ratio: float,
    use_ring: Optional[bool] = None,
) -> Tuple[Callable, int]:
    """-> (compiled fn (buf, err) -> (result, new_err), err length).

    kind: 'allreduce' or 'reduce_scatter' (MPI slice placement). SUM only,
    axis-aligned groups (like the quantized path). use_ring: None = auto (ring
    merge for single-axis groups of size >= RING_THRESHOLD)."""
    from mlsl_tpu.comm.collectives import _axis_sizes, _group_key

    mlsl_assert(group.colors is None, "sparse collectives require axis-aligned groups")
    mlsl_assert(0.0 < ratio <= 1.0, "topk ratio must be in (0, 1], got %s", ratio)
    g = 1 if group.is_self else group.size
    if use_ring is None:
        use_ring = g >= RING_THRESHOLD and len(group.axes) == 1
    elif use_ring:
        mlsl_assert(
            len(group.axes) == 1 and g > 1,
            "ring wire format requires a single-axis group of size > 1 "
            "(got axes=%s, size=%d)", group.axes, g,
        )
    recv_count = None
    if kind == "reduce_scatter":
        mlsl_assert(count % g == 0, "reduce_scatter count %d %% group %d", count, g)
        recv_count = count // g
    k = max(1, int(count * ratio))
    key = (kind, _group_key(group), count, k, use_ring)
    fn = _cache.get(key)
    if fn is not None:
        return fn, count

    topo = group.topology
    axes = () if group.is_self else group.axes
    sizes = _axis_sizes(topo.mesh)

    import functools

    from mlsl_tpu.comm.collectives import build_stateful_collective
    from mlsl_tpu.comm.quant_ring import _chaos_roundtrip

    body = functools.partial(
        _sparse_body, axes=axes, sizes=sizes, k=k, n=count, recv_count=recv_count,
        use_ring=use_ring,
    )
    # same 'codec.roundtrip' chaos site and codec breaker as the int8 ring:
    # every compressed wire family is injectable and degradable uniformly
    fn = _chaos_roundtrip(build_stateful_collective(body, topo.mesh),
                          algo="topk")
    _cache[key] = fn
    return fn, count
