"""Communication core: device mesh, process groups, collectives, async requests.

This is the TPU-native replacement for the reference's comm stack (src/comm.hpp +
src/comm_ep.cpp / src/comm_handoff.cpp + eplib/*): a ``jax.sharding.Mesh`` replaces MPI
communicators, cached jit-compiled ``shard_map`` collectives replace endpoint servers,
and async XLA dispatch with host-side request handles replaces the shared-memory command
queue.
"""

from mlsl_tpu.comm.mesh import Topology, ProcessGroup
from mlsl_tpu.comm.request import CommRequest, RequestStorage

__all__ = ["Topology", "ProcessGroup", "CommRequest", "RequestStorage"]
