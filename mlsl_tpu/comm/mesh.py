"""Device mesh topology and process groups.

Replaces the reference's ProcessGroup-over-MPI_Comm (src/comm.hpp:33-46, backend
ProcessGroupImpl src/comm_ep.cpp:144-200): the "world" is the set of JAX devices,
arranged as a ``jax.sharding.Mesh`` of shape (replica, data, seq, model). A ProcessGroup is a
*descriptor* — either an axis-aligned subgroup (named mesh axes, the fast path: XLA
collectives ride ICI rings directly) or a color partition (arbitrary subgroups, the
analog of MPI_Comm_split color, reference src/mlsl.cpp:620-647), executed via a
gather+mask fallback.

Rank layout matches the reference grid math (src/mlsl_impl.hpp:224-266), extended with
a sequence axis (absent in the 2016-era reference; SURVEY.md §5.7 prescribes exposing
sequence sharding as just another grid axis):
    global rank p  =  ((replicaIdx * D + dataIdx) * S + seqIdx) * M + modelIdx
i.e. the model axis is minor (consecutive ranks form a model group), then sequence,
then data, replicas outermost. With S = 1 this reduces exactly to the reference's
layout.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mlsl_tpu.log import mlsl_assert

REPLICA_AXIS = "replica"
DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
GRID_AXES = (REPLICA_AXIS, DATA_AXIS, SEQ_AXIS, MODEL_AXIS)
NUM_GRID_AXES = len(GRID_AXES)


def dcn_aware_devices(
    model_parts: int,
    seq_parts: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Tuple[jax.Device, ...]:
    """Order devices so the minor grid axes (model, then seq) stay WITHIN a
    host while data/replica span hosts — bandwidth-hungry TP/SP collectives
    ride ICI, and only the once-per-step gradient reduction crosses the DCN
    (the standard multi-pod layout; pass the result as ``devices=`` to
    create_distribution).

    Rank layout is model-minor (see module docstring), so "model groups within
    a host" means each host's devices must cover whole model x seq blocks:
    model_parts * seq_parts must divide every host's local device count.
    """
    devices = tuple(jax.devices() if devices is None else devices)
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    block = model_parts * seq_parts
    for proc, ds in by_proc.items():
        mlsl_assert(
            len(ds) % block == 0,
            "host %d has %d devices; model_parts*seq_parts=%d must divide the "
            "per-host device count for model/seq groups to stay on ICI",
            proc, len(ds), block,
        )
    ordered = []
    for proc in sorted(by_proc):
        ordered.extend(sorted(by_proc[proc], key=lambda d: d.id))
    return tuple(ordered)


def parse_mesh_tiers(spec: str) -> Optional[Tuple[int, int]]:
    """Parse ``MLSL_MESH_TIERS='TxL'`` -> (T slices, L devices per slice), or
    None for empty. Raises MLSLError on anything that is not two positive
    ints joined by 'x' — a malformed tier spec must fail at init, not deep
    inside the first hierarchical collective that consults it.
    Config.validate() deliberately duplicates this grammar inline: it must
    stay importable without jax, which this module imports."""
    spec = (spec or "").strip().lower()
    if not spec:
        return None
    parts = spec.split("x")
    mlsl_assert(
        len(parts) == 2 and all(p.strip().isdigit() for p in parts),
        "MLSL_MESH_TIERS must be 'TxL' (slices x devices-per-slice), got %r",
        spec,
    )
    t, l = int(parts[0]), int(parts[1])
    mlsl_assert(t >= 1 and l >= 1,
                "MLSL_MESH_TIERS slices/locals must be >= 1 (got %dx%d)", t, l)
    return t, l


def world_tier_ids(devices=None) -> Optional[Tuple[int, ...]]:
    """Per-world-rank tier (slice) ids, or None when the world is one tier.

    Resolution order:
    - ``MLSL_MESH_TIERS=TxL``: a synthetic contiguous split (rank // L) —
      how the 8-dev CPU proof mesh and tier-1 exercise a two-tier world.
      T*L must cover the world exactly.
    - real hardware: ``device.slice_index`` (TPU multislice). Ranks sharing
      a slice share an ICI domain; distinct slices are bridged by the DCN.
    - neither: None — a single flat/ICI world, no tier structure.
    """
    import os

    devices = tuple(jax.devices() if devices is None else devices)
    n = len(devices)
    spec = parse_mesh_tiers(os.environ.get("MLSL_MESH_TIERS", ""))
    if spec is not None:
        t, l = spec
        # The synthetic split describes the FULL world: every device maps
        # to its world-position tier (world rank // L) by IDENTITY — the
        # same way device.slice_index survives sub-world or permuted
        # Topologies on real multislice. No positional fast path: a
        # permuted full-size tuple must see its true (interleaved) tier
        # ids, and a spec that does not cover the world is a genuine
        # misconfiguration that must fail at arming time, not silently
        # flatten or silently tier a same-length sub-world.
        world = {d: i for i, d in enumerate(jax.devices())}
        mlsl_assert(
            t * l == len(world),
            "MLSL_MESH_TIERS=%dx%d does not cover the %d-device world",
            t, l, len(world),
        )
        if not all(d in world for d in devices):
            return None
        raw = [world[d] // l for d in devices]
        order = {s: i for i, s in enumerate(sorted(set(raw)))}
        return tuple(order[s] for s in raw)
    slices = [getattr(d, "slice_index", None) for d in devices]
    if any(s is None for s in slices) or len(set(slices)) <= 1:
        return None
    order = {s: i for i, s in enumerate(sorted(set(slices)))}
    return tuple(order[s] for s in slices)


def world_tiers(devices=None) -> Optional[Tuple[int, int]]:
    """(T, L) for the world when it splits into T equal contiguous tiers of
    L devices (the shape the hierarchical lowerings and the topology
    fingerprint key on), else None — unequal or interleaved slice layouts
    have no uniform two-tier shape and ride the flat lowerings."""
    ids = world_tier_ids(devices)
    if ids is None:
        return None
    t = len(set(ids))
    n = len(ids)
    if n % t:
        return None
    l = n // t
    if tuple(ids) != tuple(i // l for i in range(n)):
        return None
    return t, l


def survivor_devices(lost, devices=None) -> Tuple[jax.Device, ...]:
    """The post-loss world: ``devices`` (default the full jax world) minus
    ``lost``, in original world-rank order — the survivor set an elastic
    reshard (mlsl_tpu.elastic) re-derives its Topology over.

    Tier-aware: on a tiered world (TPU multislice ``slice_index`` or the
    synthetic ``MLSL_MESH_TIERS`` split) losing ANY member of a tier drops
    the WHOLE tier — the slice's ICI domain is broken and a partial slice
    can neither ride the tiered lowerings nor keep the uniform two-tier
    shape the hier engine/fingerprint key on. Flat worlds shed exactly the
    lost devices. Raises MLSLError when nothing would survive."""
    from mlsl_tpu.log import MLSLError

    devices = tuple(jax.devices() if devices is None else devices)
    lost_set = set(lost)
    ids = world_tier_ids(devices)
    if ids is not None:
        dead_tiers = {t for d, t in zip(devices, ids) if d in lost_set}
        out = tuple(d for d, t in zip(devices, ids) if t not in dead_tiers)
    else:
        out = tuple(d for d in devices if d not in lost_set)
    if not out:
        raise MLSLError(
            f"device loss of {len(lost_set)} device(s) leaves no survivors "
            f"in the {len(devices)}-device world (tiered={ids is not None})"
        )
    return out


class Topology:
    """The device world arranged as a (replica, data, seq, model) mesh.

    One Topology per (Environment, Distribution-shape). The mesh is built so that the
    flattened device order follows the reference's rank layout; group indices derived
    from mesh coordinates therefore match the reference's color math exactly.
    """

    def __init__(
        self,
        data_parts: int,
        model_parts: int,
        devices: Optional[Sequence[jax.Device]] = None,
        seq_parts: int = 1,
    ):
        if devices is None:
            devices = jax.devices()
        n = len(devices)
        mlsl_assert(
            data_parts > 0 and model_parts > 0 and seq_parts > 0,
            "numbers for data/model/seq groups must be positive",
        )
        l_size = data_parts * model_parts * seq_parts
        mlsl_assert(
            n % l_size == 0,
            "device count %d not divisible by dataParts*seqParts*modelParts %d",
            n,
            l_size,
        )
        self.data_parts = data_parts
        self.model_parts = model_parts
        self.seq_parts = seq_parts
        self.replica_count = n // l_size
        self.world_size = n
        dev_array = np.array(list(devices), dtype=object).reshape(
            self.replica_count, data_parts, seq_parts, model_parts
        )
        self.mesh = Mesh(dev_array, GRID_AXES)

    # -- rank <-> coordinate math (reference src/mlsl_impl.hpp:224-240) --

    def coords(self, global_idx: int) -> Tuple[int, int, int, int]:
        """global rank -> (replicaIdx, dataIdx, seqIdx, modelIdx)."""
        l_size = self.data_parts * self.seq_parts * self.model_parts
        l_id = global_idx % l_size
        m = l_id % self.model_parts
        s = (l_id // self.model_parts) % self.seq_parts
        d = l_id // (self.model_parts * self.seq_parts)
        return (global_idx // l_size, d, s, m)

    def global_idx(self, replica: int, data: int, seq: int, model: int) -> int:
        return (
            (replica * self.data_parts + data) * self.seq_parts + seq
        ) * self.model_parts + model

    @property
    def grid_shape(self) -> Tuple[int, int, int, int]:
        return (self.replica_count, self.data_parts, self.seq_parts, self.model_parts)

    @property
    def flat_mesh(self) -> Mesh:
        """The same devices as a single-axis ("world",) mesh, in global-rank order.

        Subgroup collectives (MPI_Comm_split-style color groups) compile against this
        mesh so they can use XLA's native subgroup support (``axis_index_groups`` =
        replica_groups in the lowered HLO) — a single named axis is required for
        axis_index_groups. Sharding is compatible with the 4-axis mesh (device p holds
        rank p's row either way), so the reshape between the two is layout-only.
        """
        if getattr(self, "_flat_mesh", None) is None:
            self._flat_mesh = Mesh(self.mesh.devices.reshape(-1), ("world",))
        return self._flat_mesh

    def buffer_sharding(self, extra_dims: int = 1) -> NamedSharding:
        """Sharding for a 'distributed buffer': global shape
        (replica, data, seq, model, *local_shape), one local payload per rank."""
        spec = P(*GRID_AXES, *([None] * extra_dims))
        return NamedSharding(self.mesh, spec)

    def shard_buffer(self, array) -> jax.Array:
        """Place a host array of shape (R, D, S, M, ...) so that element [r, d, s, m]
        lives on the device with those mesh coordinates."""
        mlsl_assert(
            array.ndim >= NUM_GRID_AXES + 1
            and array.shape[: NUM_GRID_AXES] == self.grid_shape,
            "buffer must have shape (R=%d, D=%d, S=%d, M=%d, ...), got %s",
            *self.grid_shape,
            array.shape,
        )
        return jax.device_put(array, self.buffer_sharding(array.ndim - NUM_GRID_AXES))

    def shard_buffer_local(self, local_block, global_shape) -> jax.Array:
        """Multi-process buffer construction: each host passes ONLY the block
        covering its addressable devices' (r, d, s, m) coordinates (the
        process-local portion of ``global_shape``), and no host ever
        materializes the full global array — the multi-host input-pipeline
        analog of the reference's file-IO offload streaming into local shm
        (eplib ENABLE_FILEIO)."""
        return jax.make_array_from_process_local_data(
            self.buffer_sharding(len(global_shape) - NUM_GRID_AXES),
            np.ascontiguousarray(local_block),
            global_shape,
        )

    def adopt_buffer(self, buf: jax.Array) -> jax.Array:
        """Re-view a distributed buffer laid out for ANOTHER topology over the same
        devices as this topology's (R, D, S, M, n) layout.

        Cross-distribution graph edges hand one distribution's buffer to a
        collective compiled for the other's mesh (redistribution cases 3-5,
        reference src/mlsl_impl.cpp:187-226). Rank p's row lives on device p under
        both layouts (global-rank-major flattening), so this is a device-local
        relabeling: the jitted reshape with an explicit out_sharding compiles to a
        no-transfer layout change.
        """
        grid = self.grid_shape
        if buf.ndim == NUM_GRID_AXES + 1 and tuple(buf.shape[:NUM_GRID_AXES]) == grid:
            return buf
        mlsl_assert(
            int(np.prod(buf.shape[:-1])) == self.world_size,
            "buffer rank-rows %s do not match this topology's world size %d",
            buf.shape[:-1], self.world_size,
        )
        if getattr(self, "_adopt_jit", None) is None:
            import jax.numpy as jnp

            self._adopt_jit = jax.jit(
                lambda x: jnp.reshape(x, (*grid, x.shape[-1])),
                out_shardings=self.buffer_sharding(1),
            )
        return self._adopt_jit(buf)


@dataclasses.dataclass(frozen=True)
class ProcessGroup:
    """A subgroup of the world over which a collective runs.

    Axis-aligned (colors is None): the members are the mesh ranks along ``axes``; the
    member index is the flattened coordinate over ``axes`` in the given (major->minor)
    order. This is the fast path — XLA lowers the collective onto the ICI rings of those
    axes.

    Color-based (colors is not None): ``colors[p]`` assigns world rank p to a group;
    members are ordered by world rank within each color (MPI_Comm_split semantics,
    reference src/comm_ep.cpp:1821-1827).
    """

    topology: Topology
    axes: Tuple[str, ...]  # subset of GRID_AXES (replica, data, seq, model); () = self
    colors: Optional[Tuple[int, ...]] = None

    def __post_init__(self):
        if self.colors is not None:
            mlsl_assert(
                len(self.colors) == self.topology.world_size,
                "colors must cover the world: %d != %d",
                len(self.colors),
                self.topology.world_size,
            )

    @property
    def is_self(self) -> bool:
        return self.colors is None and len(self.axes) == 0

    @property
    def group_sizes(self) -> Tuple[int, ...]:
        """Per-color group sizes, ordered by ascending color (colors mode only)."""
        mlsl_assert(self.colors is not None, "group_sizes requires colors mode")
        from collections import Counter

        counts = Counter(self.colors)
        return tuple(counts[c] for c in sorted(counts))

    @property
    def is_uniform(self) -> bool:
        """True when every group has the same member count (axis-aligned groups
        always are; color groups may be ragged, like MPI_Comm_split's)."""
        if self.colors is None:
            return True
        return len(set(self.group_sizes)) == 1

    @property
    def size(self) -> int:
        """Member count of the group — the max across groups when colors are
        ragged (reference MPI_Comm_split permits unequal partitions,
        src/comm_ep.cpp:1821-1827). SPMD buffers are uniform across ranks, so
        size-dependent results (allgather/gather) on ragged groups are padded to
        the max size; see collectives._make_ragged_body."""
        if self.colors is not None:
            return max(self.group_sizes)
        size = 1
        shape = dict(
            zip(self.topology.mesh.axis_names, self.topology.mesh.devices.shape)
        )
        for a in self.axes:
            size *= shape[a]
        return max(size, 1)

    def member_world_ranks(self, color: int) -> Tuple[int, ...]:
        """World ranks of a color group, in group-rank order (colors mode only)."""
        mlsl_assert(self.colors is not None, "member_world_ranks requires colors mode")
        return tuple(p for p, c in enumerate(self.colors) if c == color)

    def group_idx_of(self, global_idx: int) -> int:
        """Member index of world rank ``global_idx`` within its group."""
        if self.colors is not None:
            return self.member_world_ranks(self.colors[global_idx]).index(global_idx)
        if not self.axes:
            return 0
        r, d, s, m = self.topology.coords(global_idx)
        coord = {REPLICA_AXIS: r, DATA_AXIS: d, SEQ_AXIS: s, MODEL_AXIS: m}
        shape = dict(
            zip(self.topology.mesh.axis_names, self.topology.mesh.devices.shape)
        )
        idx = 0
        for a in self.axes:
            idx = idx * shape[a] + coord[a]
        return idx
