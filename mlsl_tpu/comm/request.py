"""Asynchronous communication requests: the Start/Wait/Test engine.

Replaces the reference's CommRequest + eplib command queue (src/comm.hpp:368-409,
eplib/cqueue.c): where the reference hands a command to a shared-memory ring drained by
endpoint-server processes, here ``start`` dispatches an already-compiled XLA executable
— JAX's async dispatch returns immediately while the TPU runs the collective — and the
returned jax.Array is the completion handle (``block_until_ready`` = Wait,
``is_ready()`` = Test).

Also implements, as host-side scheduling policy:
- large-message chunking (reference splits >128 MiB allreduces, src/comm_ep.cpp:640-657):
  a big allreduce is dispatched as several independent chunk programs, so completion is
  incremental and chunks from different requests interleave;
- newest-first priority (reference eplib/allreduce_pr.c LIFO queue, :76-79): requests
  larger than the threshold are deferred onto a stack and dispatched LIFO at the next
  sync point, so the most recently produced gradients hit the wire first.
"""

# mlsl-lint: disable-file=A202 -- this module IS the dispatch engine: the
# Dispatcher's progress thread owns deferred dispatch, with explicit
# ordering/supersede invariants (see Dispatcher + flush docstrings). The
# A202 rule exists to keep dispatch OUT of every other background thread
# (the PR 6 loader contract); the engine itself is the sanctioned site.

from __future__ import annotations

import dataclasses
import enum
import threading
import time
from typing import Callable, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from mlsl_tpu import chaos, checker, supervisor
from mlsl_tpu.obs import metrics as obs_metrics
from mlsl_tpu.obs import tracer as obs
from mlsl_tpu.comm.mesh import NUM_GRID_AXES, ProcessGroup
from mlsl_tpu.log import (
    MLSLError,
    MLSLTimeoutError,
    mlsl_assert,
    log_debug,
    log_error,
    log_warning,
)
from mlsl_tpu.comm import collectives
from mlsl_tpu.comm import algos
from mlsl_tpu.core import stats as stats_mod
from mlsl_tpu.types import (
    CompressionType,
    DataType,
    ReductionType,
    dtype_size,
    jnp_dtype,
)


class ComputeType(enum.IntEnum):
    """What a request carries (reference CommDesc src/comm.hpp:253-261)."""

    FPROP = 0
    BPROP = 1
    PARAM_GRAD = 2
    PARAM_INC = 3
    GENERIC = 4


@dataclasses.dataclass
class CommDesc:
    kind: str                      # 'allreduce' | 'bcast' | ... | 'barrier'
    group: ProcessGroup
    count: int                     # elements per rank (send side)
    data_type: DataType
    compute_type: ComputeType = ComputeType.GENERIC
    op: Optional[ReductionType] = None
    root: Optional[int] = None
    recv_count: Optional[int] = None
    recv_counts: Optional[tuple] = None
    send_counts: Optional[tuple] = None
    send_offsets: Optional[tuple] = None
    recv_offsets: Optional[tuple] = None
    pairs: Optional[tuple] = None  # sendrecv: ((src, dst), ...) member indices
    compression: CompressionType = CompressionType.NONE
    # registry codec pin (mlsl_tpu.codecs) for QUANTIZATION wires: '' = let
    # setup() resolve by request name (explicit MLSL_CODEC > calibrated
    # assignment > config.codec > int8); set by bucketing (members share one
    # codec) and by the guardrail demotion (pin to int8)
    codec: str = ""
    # per-set quant block override (0 = config.quant_block_elems)
    quant_block: int = 0

    def payload_bytes(self) -> int:
        return self.count * dtype_size(self.data_type)


class CommRequest:
    """One reusable communication request (the analog of a cached CommRequestImpl).

    Lifecycle: construct -> setup() (compile) -> start(buf) / wait() / test() any number
    of times. ``start`` never blocks; ``wait`` returns the result array.
    """

    _seq_lock = threading.Lock()
    _seq = 0

    def __init__(self, desc: CommDesc, dispatcher: "Dispatcher", name: str = ""):
        self.desc = desc
        self.dispatcher = dispatcher
        self.name = name
        self._fns: List[Callable] = []
        self._chunk_slices: List[slice] = []
        self._concat_fn: Optional[Callable] = None
        self._results: List[jax.Array] = []
        self._result: Optional[jax.Array] = None
        self._quant_fn: Optional[Callable] = None
        self._err: Optional[jax.Array] = None  # quantization error-feedback state
        self._quant_fns: Optional[List[Callable]] = None  # chunked quant programs
        self._err_lens: Optional[List[int]] = None
        self._errs: Optional[List[jax.Array]] = None
        self.is_started = False
        self.is_setup = False
        # which program family carries this request's collective: a comm/algos
        # registry name ('lax'/'rhd'/'ring2d') for the dense engine kinds, or
        # the compressed wire family ('quant_ring'/'custom_codec'/'topk').
        # Resolved at setup(); traces, stats, and describe() all report it.
        self.algo = algos.DEFAULT
        self._epoch = 0
        self._dlock = threading.Lock()  # serializes dispatch vs restart
        self._dispatch_error: Optional[BaseException] = None
        self._single_full = False  # hot path: one un-chunked program
        # recovery-ladder state (mlsl_tpu.supervisor). _breaker is None for
        # requests with no degradable subsystem (the plain 'lax' path) — the
        # hot dispatch then pays exactly one None test. Assigned at setup().
        self._breaker: Optional[supervisor.CircuitBreaker] = None
        self._degrade_subsys: Optional[str] = None
        self._degrade_fns: Optional[tuple] = None   # (flush jit, plain fn)
        self._degrade_geoms: Optional[List[tuple]] = None  # (count, err_len)/chunk
        self._err_layout: Optional[str] = None      # 'ring' | 'flat'
        self._lax_fns: Optional[List[Callable]] = None  # dense algo fallback
        self._lax_build: Optional[tuple] = None     # (dtype, kw) for it
        # last Start buffer: rung-2 wait retries re-dispatch it (a transient
        # wait failure leaves the in-flight round suspect). One reference —
        # comparable retention to the quant path's _err buffer.
        self._last_buf: Optional[jax.Array] = None
        # error-feedback state at Start (err, errs): any retry or degraded
        # re-attempt rewinds to this before re-dispatching — a failed (or
        # wait-failed) quantized dispatch has already advanced the residual,
        # and replaying from the advanced state would silently drop the
        # accumulated undelivered gradient
        self._ef_snapshot: tuple = (None, None)
        with CommRequest._seq_lock:
            CommRequest._seq += 1
            self.uid = CommRequest._seq
        # extra dispatch-span attribution (e.g. the pallas_ring 'pallas.hop'
        # wire plan), precomputed at setup so the hot path pays one **splat
        self._span_args: dict = {}
        # codec-lab state (mlsl_tpu.codecs): per-chunk registry geometry for
        # the verifier (A115/A116), the per-start wire accounting tuple
        # (codec label, compressed image bytes), the demotion latch, and the
        # pending exactly-once EF flush a demotion leaves for the next
        # successful dispatch
        self._codec_geoms: Optional[List[dict]] = None
        self._wire_rec: Optional[tuple] = None
        self._codec_demoted = False
        self._pending_flush: Optional[tuple] = None
        self.codec_name = ""      # resolved registry name ("" until setup)
        self.codec_source = ""    # env/calibrated/config/desc/demoted/...
        # effective int8 block (desc override > calibration cell > config):
        # the A112 geometry check must model THIS, not the session block
        self._eff_quant_block = 0
        # per-Start hot-path constants (VERDICT r4 item 3: keep the host
        # dispatch floor low — no per-dispatch string building / re-derivation)
        self._trace_name = f"mlsl:{desc.kind}:{name or self.uid}"
        self._payload = desc.payload_bytes()
        # watchdog stamp: monotonic Start time of the current in-flight epoch
        self._started_at: Optional[float] = None

    # -- setup ------------------------------------------------------------

    def setup(self) -> None:
        """Build (and implicitly compile on first run) the collective programs."""
        d = self.desc
        if d.compression == CompressionType.TOPK:
            from mlsl_tpu.comm import sparse

            mlsl_assert(
                d.kind in ("allreduce", "reduce_scatter")
                and d.op in (None, ReductionType.SUM),
                "TOPK compression supports allreduce/reduce_scatter SUM only "
                "(got %s/%s)",
                d.kind, d.op,
            )
            _check_recv_count(d)
            ratio = self.dispatcher.config.topk_ratio
            self._quant_fn, self._err_len = sparse.build_sparse_collective(
                d.kind, d.group, d.count, ratio
            )
            self._chunk_slices = [slice(None)]
            self.algo = "topk"
            # per-codec wire accounting: the sparse image is k (value, index)
            # pairs of one full payload (core/stats CODEC_WIRE_BYTES)
            self._wire_rec = ("topk", 8 * max(1, int(d.count * ratio)))
            # ladder: the sparse wire rides the codec subsystem's breaker;
            # its residual is already in the logical layout ('flat')
            self._breaker = supervisor.breaker("quant")
            self._degrade_subsys = "quant"
            self._err_layout = "flat"
            self._degrade_geoms = [(d.count, self._err_len)]
            self.is_setup = True
            return
        if d.compression == CompressionType.QUANTIZATION and d.kind in (
            "allreduce",
            "reduce_scatter",
        ):
            mlsl_assert(
                d.op in (None, ReductionType.SUM),
                "quantized collectives support SUM only (got %s)",
                d.op,
            )
            _check_recv_count(d)
            from mlsl_tpu import codecs as codecs_mod

            cfg = self.dispatcher.config
            codec = getattr(cfg, "custom_codec", None)
            # registry resolution (mlsl_tpu.codecs.assigned): a user-plugged
            # CustomCodec wins outright (the dlopen contract predates the
            # registry); then an explicit desc pin (bucketing / demotion),
            # then MLSL_CODEC / the calibrated per-set assignment
            self._codec_geoms = None
            # setup() re-entry (calibration re-route at commit, guardrail
            # demotion): drop every stale program/geometry; residual state is
            # either virgin (pre-start) or was consumed by the caller
            # (demote_codec's exactly-once flush capture)
            self._quant_fn = None
            self._quant_fns = None
            self._err_lens = None
            self._err = None
            self._errs = None
            self._degrade_fns = None
            self._wire_rec = None
            self._span_args = {}
            reg_name, reg_cell, reg_src = "int8", None, "default"
            if codec is None:
                if self._codec_demoted:
                    reg_name, reg_src = "int8", "demoted"
                elif d.codec:
                    reg_name, reg_src = d.codec, "desc"
                else:
                    reg_name, reg_cell, reg_src = codecs_mod.assigned(
                        cfg, self.name
                    )
            # resolved identity for bucketing partitions / introspection
            self.codec_name = "custom" if codec is not None else reg_name
            self.codec_source = "custom" if codec is not None else reg_src
            block = int(
                d.quant_block or (reg_cell or {}).get("block", 0)
                or cfg.quant_block_elems
            )
            self._eff_quant_block = block
            if codec is None and reg_name == "topk":
                # registry route into the seed sparsifier: same wire, same
                # flat residual layout, ratio from the calibration cell
                from mlsl_tpu.comm import sparse

                ratio = float(
                    (reg_cell or {}).get("params", {}).get("ratio", 0)
                    or cfg.topk_ratio
                )
                self._quant_fn, self._err_len = sparse.build_sparse_collective(
                    d.kind, d.group, d.count, ratio
                )
                self._chunk_slices = [slice(None)]
                self.algo = "topk"
                self._breaker = supervisor.breaker("quant")
                self._degrade_subsys = "quant"
                self._err_layout = "flat"
                self._degrade_geoms = [(d.count, self._err_len)]
                self._wire_rec = ("topk", 8 * max(1, int(d.count * ratio)))
                if reg_src == "calibrated":
                    codecs_mod.guard_register(self)
                self.is_setup = True
                return
            self.algo = "custom_codec" if codec is not None else "quant_ring"
            reg_codec = None
            if codec is not None:
                # user-pluggable codec (reference dlopen contract,
                # quant/quant.c:96-133): compressed ring wire, framework-owned
                # error feedback
                from mlsl_tpu.comm import codec as codec_mod

                def build(n):
                    return codec_mod.build_custom_collective(
                        d.kind, d.group, n, codec
                    )
            elif reg_name != "int8":
                # registry codec ('vq'/'prune'/'f32'/plugins) on the SAME
                # compressed-ring transport as the dlopen contract: entry EF,
                # per-hop encode, compressed-domain aggregate when declared
                from mlsl_tpu.comm import codec as codec_mod

                reg_codec = codecs_mod.configure(reg_name, cfg, reg_cell)
                wrapped = reg_codec.as_custom()
                self.algo = f"codec:{reg_name}"

                def build(n):
                    return codec_mod.build_custom_collective(
                        d.kind, d.group, n, wrapped
                    )
            else:
                from mlsl_tpu.comm import quant_ring
                # hop-engine selection through the PR 4 table: a forced or
                # tuned 'pallas_ring' routes the SAME compressed wire family
                # through the fused kernel (identical entry error feedback,
                # identical residual layout — quant_ring ring='pallas');
                # 'hier' routes it through the two-tier decomposition (the
                # codec applies only on the DCN hop; per-shard residual
                # layout — quant_ring ring='hier')
                ring = "lax"
                ring_kw = {}
                sel = algos.select(d.kind, d.group, self._payload,
                                   d.compression, cfg, op=d.op)
                if sel == "pallas_ring":
                    ring = "pallas"
                    self.algo = "pallas_ring"
                    ring_kw = dict(
                        slots=int(getattr(cfg, "pallas_ring_slots", 2)),
                        bidir=bool(getattr(cfg, "pallas_ring_bidir", False)),
                    )
                elif sel == "hier":
                    ring = "hier"
                    self.algo = "hier"
                    ring_kw = dict(
                        dcn_codec=getattr(cfg, "hier_dcn_codec", None),
                        topk_ratio=float(getattr(cfg, "topk_ratio", 0.01)),
                    )

                def build(n):
                    return quant_ring.build_quantized_collective(
                        d.kind, d.group, n, block, ring=ring, **ring_kw
                    )

            chunks = self._plan_chunks(compressed_ok=True)
            if chunks is not None and d.kind == "allreduce":
                # large quantized allreduce: independent per-chunk ring programs,
                # each with its own error-feedback state (slices are disjoint)
                self._quant_fns = []
                self._err_lens = []
                for sl in chunks:
                    fn, el = build(sl.stop - sl.start)
                    self._quant_fns.append(fn)
                    self._err_lens.append(el)
                self._chunk_slices = chunks
                self._degrade_geoms = [
                    (sl.stop - sl.start, el)
                    for sl, el in zip(chunks, self._err_lens)
                ]
            else:
                self._quant_fn, self._err_len = build(d.count)
                self._chunk_slices = [slice(None)]
                self._degrade_geoms = [(d.count, self._err_len)]
            if self.algo == "pallas_ring":
                # span reflects the geometry of ONE dispatched program (a
                # chunked request splits into independent per-chunk rings)
                self._set_pallas_span(
                    d, block, quantized=True,
                    count=(self._chunk_slices[0].stop
                           - self._chunk_slices[0].start)
                    if self._chunk_slices[0] != slice(None) else d.count,
                    programs=len(self._chunk_slices), **ring_kw,
                )
            # ladder: codec faults count against the quant breaker; when it
            # trips, dispatch degrades to the plain f32 SUM program with the
            # residual flushed (_dispatch_degraded)
            self._breaker = supervisor.breaker("quant")
            self._degrade_subsys = "quant"
            if self.algo == "hier":
                # per-shard residual layout: each member owns its own 1/L
                # slice's error; the degrade flush re-places it at that
                # slice's logical offset (hier.flush_residual) via the
                # static intra-tier position table captured here
                from mlsl_tpu.comm.algos import hier

                self._err_layout = "hier"
                self._hier_meta = (
                    hier.tier_structure(d.group)[1],
                    hier.intra_positions(d.group),
                )
            else:
                self._err_layout = "ring"  # quant_ring AND custom_codec
            # codec-lab accounting: per-chunk registry geometry (the
            # verifier's A115/A116 anchor — what the programs were ACTUALLY
            # built from) and the per-start wire-byte record. Wire bytes are
            # the compressed image of one full payload — the codec-comparable
            # signal, not per-hop wire traffic (which varies by ring shape).
            g_sz = 1 if d.group.is_self else d.group.size
            rs = d.kind == "reduce_scatter"
            if reg_codec is not None:
                self._codec_geoms = []
                for n, el in self._degrade_geoms:
                    hop = n // g_sz if rs else -(-n // g_sz)
                    geom = reg_codec.geometry(hop)
                    geom["err_len"] = int(el)
                    geom["hops"] = g_sz
                    self._codec_geoms.append(geom)
                self._wire_rec = (reg_name, sum(
                    reg_codec.wire_len(n) for n, _ in self._degrade_geoms
                ))
            elif codec is not None:
                self._wire_rec = ("custom", _custom_wire_bytes(
                    codec, self._degrade_geoms
                ))
            else:
                int8_image = codecs_mod.get("int8", block=block)
                self._wire_rec = ("int8", sum(
                    int8_image.wire_len(n) for n, _ in self._degrade_geoms
                ))
            if reg_src == "calibrated" and reg_name != "int8":
                # calibrated non-int8 assignment: place this request under
                # the sentinel-fed convergence guardrail (demotes to int8 on
                # a sustained loss z-score breach)
                codecs_mod.guard_register(self)
            self.is_setup = True
            return
        if d.kind == "barrier":
            self._fns = [collectives.build_barrier(d.group)]
            self._chunk_slices = [slice(None)]
            self._single_full = True
            self.is_setup = True
            return

        kw = {}
        if d.op is not None:
            kw["op"] = ReductionType(d.op)
        if d.root is not None:
            kw["root"] = int(d.root)
        if d.recv_count is not None:
            kw["recv_count"] = int(d.recv_count)
        if d.recv_counts is not None and d.kind != "alltoallv":
            # alltoallv's recv_counts may be a full (G, G) matrix and is
            # consumed by _normalize_alltoallv below, not flattened here.
            kw["recv_counts"] = tuple(int(c) for c in d.recv_counts)
        if d.kind == "alltoall":
            kw["send_count"] = int(d.count)
        if d.kind == "sendrecv":
            kw["pairs"] = tuple((int(s), int(t)) for s, t in d.pairs)
        if d.kind == "alltoallv":
            kw.update(_normalize_alltoallv(d))

        dtype = jnp_dtype(d.data_type)
        # Algorithm selection (comm/algos): explicit config > tuned profile >
        # the 'lax' baseline. 'lax' routes through build_collective unchanged
        # — same cache entry, same program, bit-for-bit the untuned behavior.
        # Chunked requests select once on the FULL payload (the knob the
        # operator reasons about) and reuse one program across chunks.
        self.algo = algos.select(
            d.kind, d.group, self._payload, d.compression,
            self.dispatcher.config, op=kw.get("op"),
        )
        lax_kw = dict(kw)
        if self.algo in ("pallas_ring", "pallas_ring2d"):
            # kernel-geometry knobs ride the build kw (and so the program
            # cache key) — but never the 'lax' fallback build below
            cfg = self.dispatcher.config
            kw["slots"] = int(getattr(cfg, "pallas_ring_slots", 2))
            kw["bidir"] = bool(getattr(cfg, "pallas_ring_bidir", False))
        elif self.algo in ("pallas_rhd", "pallas_a2a"):
            cfg = self.dispatcher.config
            kw["slots"] = int(getattr(cfg, "pallas_ring_slots", 2))
            if self.algo == "pallas_a2a":
                from mlsl_tpu.ops import a2a_kernels
                kw["block"] = int(getattr(cfg, "quant_block_elems", 256))
                kw["quantized"] = a2a_kernels.quant_enabled(cfg)
        chunks = self._plan_chunks()
        span_count = ((chunks[0].stop - chunks[0].start) if chunks
                      else d.count)
        span_programs = len(chunks) if chunks else 1
        if self.algo in ("pallas_ring", "pallas_ring2d"):
            # the snake ring is the same kernel program over 2D neighbour
            # tables — the 1D describe_plan IS its wire plan
            self._set_pallas_span(
                d, None, quantized=False, slots=kw["slots"],
                bidir=kw["bidir"], count=span_count,
                programs=span_programs,
            )
        elif self.algo == "pallas_rhd":
            from mlsl_tpu.ops import rhd_kernels
            g = 1 if d.group.is_self else int(d.group.size)
            m, _ = rhd_kernels.geometry(g, span_count)
            self._span_args = {
                "pallas.hop": rhd_kernels.describe_plan(g, m, kw["slots"])
            }
        elif self.algo == "pallas_a2a":
            from mlsl_tpu.ops import a2a_kernels
            cfg = self.dispatcher.config
            g = 1 if d.group.is_self else int(d.group.size)
            # an alltoall desc's count is the PER-DESTINATION send_count;
            # the kernel's wire plan covers the g-chunk exchange
            self._span_args = {
                "pallas.hop": a2a_kernels.describe_plan(
                    g, g * span_count,
                    int(getattr(cfg, "quant_block_elems", 256)),
                    a2a_kernels.quant_enabled(cfg), kw["slots"],
                )
            }
        if chunks is None:
            self._fns = [algos.build(d.kind, d.group, dtype, self.algo, **kw)]
            self._chunk_slices = [slice(None)]
        else:
            fn = algos.build(d.kind, d.group, dtype, self.algo, **kw)
            self._fns = [fn] * len(chunks)
            self._chunk_slices = chunks
        if self.algo != algos.DEFAULT:
            # ladder: a tuned/forced algorithm can degrade to the 'lax'
            # baseline per dispatch; the baseline itself has no lower rung
            # (its failures escalate straight to supervised restart)
            self._breaker = supervisor.breaker("algo")
            self._degrade_subsys = "algo"
            self._lax_build = (dtype, lax_kw)
        # hot-path precomputation: the per-layer dispatch floor must stay in
        # single-digit µs (VERDICT r4 item 3), so nothing re-derived per Start
        self._single_full = (
            len(self._chunk_slices) == 1 and self._chunk_slices[0] == slice(None)
        )
        self.is_setup = True

    def _set_pallas_span(self, d: CommDesc, block: Optional[int], *,
                         quantized: bool, slots=None, bidir=None,
                         count: Optional[int] = None,
                         programs: int = 1) -> None:
        """Precompute the ``pallas.hop`` dispatch-span argument (hops, slot
        bytes, codec) for a request the table routed to the fused kernel —
        the wire plan belongs on the trace next to the algorithm name.
        ``count`` is the per-program element count (ONE chunk of a split
        large-message request), ``programs`` the number of chunk rings."""
        from mlsl_tpu.ops import ring_kernels as rk

        cfg = self.dispatcher.config
        slots = rk.env_slots(
            slots if slots is not None
            else getattr(cfg, "pallas_ring_slots", None)
        )
        bidir = rk.env_bidir(
            bidir if bidir is not None
            else getattr(cfg, "pallas_ring_bidir", None)
        )
        count = d.count if count is None else int(count)
        if quantized:
            g, _, chunk, _ = rk.quant_geometry(d.kind, d.group, count, block)
        else:
            g, _, chunk = rk.dense_geometry(d.kind, d.group, count)
        self._span_args = {
            "pallas.hop": rk.describe_plan(
                g, chunk, quantized, block or 0, bidir, slots,
                dense_dtype=jnp_dtype(d.data_type), programs=programs,
            )
        }

    def precompile(self) -> int:
        """Run every compiled program once on zero buffers so the jit caches
        are hot before the first timed step (Session.precompile_collectives /
        MLSL_PRECOMPILE). A warm CALL is required — jax's AOT
        lower().compile() does not populate the dispatch cache the normal
        call path consults, so only execution removes the step-0 stall (the
        isolation replay relies on the same fact). Request round state
        (_results / is_started / the error-feedback buffers) is untouched: a
        never-started request must not look completed afterwards, and a zero
        warm must not perturb _err. Returns the number of programs run."""
        mlsl_assert(self.is_setup, "request must be setup() before precompile()")
        d = self.desc
        topo = d.group.topology
        buf = topo.shard_buffer(
            np.zeros((*topo.grid_shape, max(d.count, 1)), dtype=jnp_dtype(d.data_type))
        )

        def zero_err(el):
            return topo.shard_buffer(
                np.zeros((*topo.grid_shape, el), dtype=np.float32)
            )

        n = 0
        seen: set = set()  # chunked requests repeat one program across
        # same-length chunks ([fn]*k, shared quant fns) — warm each distinct
        # (program, chunk length) once, not once per chunk

        def warm(fn, sl, *err):
            nonlocal n
            inner = _unwrap_chaos(fn)
            key = (id(inner), sl.stop - sl.start if sl.stop is not None else None)
            if key in seen:
                return
            seen.add(key)
            arg = buf if sl == slice(None) else buf[..., sl]
            jax.block_until_ready(inner(arg, *err))
            n += 1

        if self._quant_fns is not None:
            for fn, sl, el in zip(
                self._quant_fns, self._chunk_slices, self._err_lens
            ):
                warm(fn, sl, zero_err(el))
        elif self._quant_fn is not None:
            warm(self._quant_fn, slice(None), zero_err(self._err_len))
        elif self._single_full:
            warm(self._fns[0], slice(None))
        else:
            for fn, sl in zip(self._fns, self._chunk_slices):
                warm(fn, sl)
        return n

    def _plan_chunks(self, compressed_ok: bool = False):
        """Chunk only elementwise-decomposable hot collectives (allreduce)."""
        d = self.desc
        cfg = self.dispatcher.config
        if d.kind != "allreduce":
            return None
        if d.compression != CompressionType.NONE and not compressed_ok:
            return None
        threshold = cfg.large_msg_size_mb * 1024 * 1024
        if threshold <= 0 or d.payload_bytes() <= threshold or cfg.large_msg_chunks <= 1:
            return None
        k = min(cfg.large_msg_chunks, d.count)
        bounds = np.linspace(0, d.count, k + 1).astype(int)
        return [slice(int(a), int(b)) for a, b in zip(bounds[:-1], bounds[1:]) if b > a]

    # -- start/wait/test --------------------------------------------------

    def start(self, buf: jax.Array, *, _rewind_ef: bool = False) -> "CommRequest":
        """``_rewind_ef`` (internal, wait-retry only): rewind the
        error-feedback state to the previous Start's snapshot inside the
        epoch-bump critical section — after the bump a stale in-flight
        dispatch skips on the epoch check, and one that completed first is
        rewound here, so the replay always quantizes from the exact state
        the suspect round saw."""
        mlsl_assert(self.is_setup, "request must be setup() before start()")
        if chaos._plans:
            chaos.inject("request.start", request=self.name or self.uid,
                         kind=self.desc.kind)
        chkp = checker.level()
        if chkp:
            checker.check_buffer(buf, self.desc, chkp)
        # Bump the epoch under the dispatch lock: a stale dispatch of the
        # PREVIOUS start's buffer racing on the progress thread either sees the
        # new epoch and skips, or finishes writing _results before the reset
        # below — never after it (the clobber the supersede logic exists for).
        with self._dlock:
            self._epoch += 1
            if _rewind_ef:
                self._ef_restore()
            self._results = []
            self._result = None
            self._dispatch_error = None
            self.is_started = True
            self._started_at = time.monotonic()  # watchdog stamp
            self._last_buf = buf  # rung-2 wait retries re-dispatch this
            self._ef_snapshot = (
                self._err, list(self._errs) if self._errs is not None else None
            )
        tr = obs._tracer
        if tr is not None:
            tr.instant("submit", "req", track=self._trace_name,
                       req=self.name or self.uid, epoch=self._epoch,
                       bytes=self._payload)
        if self._wire_rec is not None:
            # per-codec wire accounting (one dict upsert, like the ALGO
            # dispatch line): compressed image bytes of this round's payload
            stats_mod.record_codec_wire(*self._wire_rec)
        self.dispatcher.submit(self, buf)
        return self

    def _dispatch(self, buf: jax.Array, epoch: Optional[int] = None) -> None:
        """Actually launch the XLA programs (called by the Dispatcher).

        ``epoch`` is the request epoch captured when the dispatch was queued; a
        mismatch means a later start() superseded this entry while it sat in the
        queue (or mid-flight on the progress thread) — drop it.

        The TraceAnnotation marks the host-side enqueue (request identity and
        dispatch ordering); the device-side span carries the collective's identity
        via the jax.named_scope baked into the compiled program
        (collectives.build_collective)."""
        with self._dlock:
            if epoch is not None and epoch != self._epoch:
                log_debug("dropping superseded dispatch of %s", self.name or self.uid)
                return
            tr = obs._tracer
            t0 = tr.now() if tr is not None else 0
            try:
                with jax.profiler.TraceAnnotation(self._trace_name):
                    # retry-in-place under _dlock IS the dispatch/restart
                    # serialization contract: the only other takers are this
                    # request's own wait()/test()/restart, which must see the
                    # ladder's outcome before touching round state
                    # mlsl-lint: disable=A211 -- deliberate hold across the retry ladder
                    self._dispatch_ladder(buf)
            except Exception as e:
                if tr is not None:
                    tr.instant("dispatch.error", "req", track=self._trace_name,
                               req=self.name or self.uid, error=repr(e))
                if epoch is None:
                    raise  # direct dispatch: fail the caller's start()
                # Queued dispatch: record the failure on the request while the
                # epoch is still known-current. Recording it after releasing
                # _dlock would race a fresh start() (which resets
                # _dispatch_error and bumps the epoch) and attach this stale
                # failure to the new start.
                self._dispatch_error = e
            else:
                if tr is not None:
                    # host-side enqueue span: XLA's async dispatch returns
                    # before the device finishes, so this measures launch
                    # cost; device completion lands in the wait span. The
                    # algo arg attributes the time to the program family the
                    # selection table chose (comm/algos).
                    tr.complete("dispatch", "req", t0, track=self._trace_name,
                                req=self.name or self.uid, epoch=self._epoch,
                                algo=self.algo, **self._span_args)

    def _dispatch_ladder(self, buf: jax.Array) -> None:
        """Rungs 2+3 of the recovery ladder around one dispatch (caller holds
        _dlock). TRANSIENT failures (supervisor.classify) retry in place with
        exponential backoff + jitter (``MLSL_COMM_RETRIES`` /
        ``MLSL_COMM_RETRY_BACKOFF_S``); CORRUPTION/PERSISTENT failures count
        against the request's subsystem breaker, and once it is OPEN — the
        tripping failure included — the dispatch is served by the degraded
        fallback path instead of raising. A healthy dispatch while the
        breaker is HALF_OPEN is the probe: its success re-closes the breaker
        and re-engages the fast path. FATAL failures raise untouched.

        The retry backoff sleeps in place — on the shared progress thread
        when dispatch is deferred. That stalls other queued dispatches for
        the backoff duration (bounded: ~retries x 1.5 x base, ~0.2s at the
        defaults — comparable to one chunked large-message dispatch);
        transients are rare by classification, and keeping the retry in
        line preserves the dispatch-order/supersede invariants a re-queue
        would have to re-prove. Keep retries x backoff well under the
        watchdog timeout (TUNING.md §11) so a backing-off request cannot
        cascade watchdog trips on the requests queued behind it."""
        br = self._breaker
        attempt = 0
        forced_degrade = False
        while True:
            degraded = forced_degrade or (br is not None and not br.allow())
            try:
                if degraded:
                    self._dispatch_degraded(buf)
                else:
                    self._dispatch_inner(buf)
            except Exception as e:
                # any re-attempt (retry, degrade, half-open probe loop)
                # replays the round from the Start residual state
                self._ef_restore()
                cfg = self.dispatcher.config
                cls = supervisor.classify(e)
                if cls is supervisor.ErrorClass.TRANSIENT:
                    if attempt < getattr(cfg, "comm_retries", 0):
                        delay = supervisor.jittered_backoff(
                            getattr(cfg, "comm_retry_backoff_s", 0.05), attempt
                        )
                        stats_mod.record_comm_retry(
                            "dispatch", self.name or str(self.uid), e,
                            attempt + 1, delay,
                        )
                        log_debug(
                            "transient dispatch failure of %s (%s); retry %d "
                            "in %.3fs", self.name or self.uid, e, attempt + 1,
                            delay,
                        )
                        attempt += 1
                        time.sleep(delay)
                        continue
                if cls is supervisor.ErrorClass.DEVICE_LOSS:
                    # capacity left the world: a breaker fallback would
                    # re-dispatch on the same (now partial) mesh and mask
                    # the loss — escalate straight to the elastic/restart
                    # rungs, without counting the subsystem as unhealthy
                    raise
                if (
                    not degraded
                    and br is not None
                    and cls is not supervisor.ErrorClass.FATAL
                    and br.record_failure(e)
                ):
                    # OPEN now (this failure tripped it, or a half-open probe
                    # failed): serve THIS dispatch degraded — rung 3's whole
                    # point is that the request succeeds instead of dying.
                    # forced: do not re-consult allow() (a zero cooldown must
                    # not ping-pong probe/fail forever inside one dispatch).
                    forced_degrade = True
                    continue
                raise
            else:
                if br is not None and not degraded:
                    br.record_success()  # no-op unless HALF_OPEN (the probe)
                return

    def _dispatch_degraded(self, buf: jax.Array) -> None:
        """The rung-3 fallback dispatch: compressed wire -> plain f32 SUM
        with the error-feedback residual flushed into the payload (delivered
        exactly once, not dropped); tuned algorithm -> the 'lax' baseline.
        Result shape/dtype match the healthy path exactly — callers cannot
        tell a degraded round from a healthy one except through stats."""
        d = self.desc
        topo0 = d.group.topology
        if hasattr(buf, "ndim") and (
            buf.ndim != NUM_GRID_AXES + 1
            or tuple(buf.shape[:NUM_GRID_AXES]) != topo0.grid_shape
        ):
            buf = topo0.adopt_buffer(buf)
        stats_mod.record_degrade(self._degrade_subsys or "?", "fallback")
        if self._quant_fn is not None or self._quant_fns is not None:
            pf = self._pending_flush
            if pf is not None:
                # a breaker degrade racing a codec demotion: the demoted
                # codec's captured residual still rides this round
                buf = pf[0](buf, *pf[1])
            flush, plain = self._degrade_programs()
            out = plain(flush(buf, *self._take_residuals()))
            self._results = [out]
            self._pending_flush = None
            stats_mod.record_algo_dispatch(d.kind, "degraded-plain")
            return
        # dense engine path: tuned/forced algorithm -> the 'lax' baseline
        if self._lax_fns is None:
            dtype, kw = self._lax_build
            fn = algos.build(d.kind, d.group, dtype, algos.DEFAULT, **kw)
            self._lax_fns = [fn] * len(self._chunk_slices)
        stats_mod.record_algo_dispatch(d.kind, algos.DEFAULT)
        if self._single_full:
            self._results = [self._lax_fns[0](buf)]
        else:
            self._results = [
                fn(buf[..., sl])
                for fn, sl in zip(self._lax_fns, self._chunk_slices)
            ]

    def _degrade_programs(self) -> tuple:
        """(flush jit, plain collective) for the degraded compressed path,
        built on first degrade and cached. flush casts to f32 and adds each
        chunk's un-chunked residual (quant_ring.logical_residual) at its
        slice; plain is the SAME cached build_collective program the
        uncompressed path uses — the parity anchor."""
        if self._degrade_fns is None:
            from mlsl_tpu.comm.quant_ring import logical_residual

            d = self.desc
            g = 1 if d.group.is_self else d.group.size
            plain = collectives.build_plain_fallback(d.kind, d.group, d.count)
            rs = d.kind == "reduce_scatter"
            slices = list(self._chunk_slices)
            geoms = list(self._degrade_geoms)
            layout = self._err_layout
            if layout == "hier":
                from mlsl_tpu.comm.algos import hier as hier_mod

                hier_L, l_np = self._hier_meta
                l_idx = jnp.asarray(l_np)

            def flush(b, *errs):
                x = b.astype(jnp.float32)
                for sl, (n, el), e in zip(slices, geoms, errs):
                    if layout == "flat":
                        res = e
                    elif layout == "hier":
                        res = hier_mod.flush_residual(e, l_idx, hier_L, el, n)
                    else:
                        res = logical_residual(
                            e, g, el // g, n // g if rs else -(-n // g), n
                        )
                    x = x + res if sl == slice(None) else x.at[..., sl].add(res)
                return x

            self._degrade_fns = (jax.jit(flush), plain)
        return self._degrade_fns

    def _ef_restore(self) -> None:
        """Rewind the error-feedback state to the Start snapshot before any
        re-attempt: a failed chunked dispatch may have advanced a prefix of
        the residuals, a wait-failed dispatch advanced all of them, and a
        failed degraded dispatch consumed them (_take_residuals) — in every
        case the replay must see the exact state the first attempt saw, or
        accumulated undelivered gradient is silently dropped (or flushed
        zero times). Arrays are immutable, so restoring references is a
        full rewind; the list is copied so the in-place chunk updates of
        the next attempt cannot corrupt the snapshot."""
        err, errs = self._ef_snapshot
        self._err = err
        self._errs = list(errs) if errs is not None else None

    def _take_residuals(self) -> List[jax.Array]:
        """Consume the error-feedback residual(s) for a degraded dispatch:
        lazily zeroed like the healthy path's first round, then RESET — the
        flush delivers the residual, and the next healthy round (the
        half-open probe) starts from virgin feedback state. Consumed BEFORE
        the plain dispatch runs; a transiently failed fallback dispatch is
        made safe by _ef_restore in the retry loop (the residual is flushed
        exactly once — by whichever attempt succeeds)."""
        topo = self.desc.group.topology

        def zeros(el):
            return topo.shard_buffer(
                np.zeros((*topo.grid_shape, el), dtype=np.float32)
            )

        if self._quant_fns is not None:
            errs = self._errs if self._errs is not None else [
                zeros(el) for el in self._err_lens
            ]
            self._errs = None
            return errs
        err = self._err if self._err is not None else zeros(self._err_len)
        self._err = None
        return [err]

    def demote_codec(self, reason: str = "") -> None:
        """Convergence-guardrail demotion (mlsl_tpu.codecs.guard_note): pin
        this request's compressed wire to the int8 seed codec. One
        DEGRADE-ladder rung: the demoted codec's EF residual is captured
        through the SAME flush program the breaker fallback uses and folded
        into the next successful dispatch exactly once; from then on the
        programs are bit-for-bit the plain int8 quant_ring build (the
        pinned-fallback contract every other rung honors)."""
        from mlsl_tpu import codecs as codecs_mod

        with self._dlock:
            if (
                self._codec_demoted
                or self.desc.compression != CompressionType.QUANTIZATION
                or (self._quant_fn is None and self._quant_fns is None)
            ):
                return
            label = self.algo
            # capture the OLD geometry's flush before setup() rebuilds:
            # residuals are consumed here (reset to virgin) and delivered by
            # whichever dispatch next succeeds (_dispatch_inner)
            flush, _ = self._degrade_programs()
            self._pending_flush = (flush, self._take_residuals())
            self._codec_demoted = True
            self._ef_snapshot = (None, None)
            self.setup()
        codecs_mod.guard_unregister(self)
        stats_mod.record_codec_demotion(
            self.name or str(self.uid), label, reason or "guardrail"
        )
        log_warning(
            "codec guardrail: %s demoted %s -> int8 (%s); residual flushes "
            "with the next round", self.name or self.uid, label,
            reason or "guardrail",
        )

    def _dispatch_inner(self, buf: jax.Array) -> None:
        # per-algorithm launch attribution (ALGO line in mlsl_stats.log);
        # one dict upsert — stays under the per-layer dispatch-floor budget
        stats_mod.record_algo_dispatch(self.desc.kind, self.algo)
        # Cross-distribution edges (redistribution cases 3-5) hand a buffer laid
        # out for the OTHER distribution's grid; re-view it onto this request's
        # group topology (device-local, no transfer — see Topology.adopt_buffer).
        topo0 = self.desc.group.topology
        if hasattr(buf, "ndim") and (
            buf.ndim != NUM_GRID_AXES + 1
            or tuple(buf.shape[:NUM_GRID_AXES]) != topo0.grid_shape
        ):
            buf = topo0.adopt_buffer(buf)
        if self._quant_fn is not None or self._quant_fns is not None:
            pf = self._pending_flush
            if pf is not None:
                # demotion's exactly-once EF flush: fold the demoted codec's
                # captured residual into this round's payload. Cleared only
                # after the dispatch succeeds — a transient failure replays
                # against the ORIGINAL buffer, so the residual lands in
                # exactly one delivered round, never zero, never two.
                buf = pf[0](buf, *pf[1])
            topo = self.desc.group.topology
            if self._quant_fns is not None:
                if self._errs is None:
                    self._errs = [
                        topo.shard_buffer(
                            np.zeros((*topo.grid_shape, el), dtype=np.float32)
                        )
                        for el in self._err_lens
                    ]
                self._results = []
                for i, (fn, sl) in enumerate(zip(self._quant_fns, self._chunk_slices)):
                    out, self._errs[i] = fn(buf[..., sl], self._errs[i])
                    self._results.append(out)
                self._pending_flush = None
                return
            if self._err is None:
                self._err = topo.shard_buffer(
                    np.zeros((*topo.grid_shape, self._err_len), dtype=np.float32)
                )
            out, self._err = self._quant_fn(buf, self._err)
            self._results = [out]
            self._pending_flush = None
            return
        if self._single_full:
            self._results = [self._fns[0](buf)]
        else:
            self._results = [
                fn(buf[..., sl]) for fn, sl in zip(self._fns, self._chunk_slices)
            ]

    def _assemble(self) -> jax.Array:
        if self._result is None:
            if len(self._results) == 1:
                self._result = self._results[0]
            else:
                self._result = jnp.concatenate(self._results, axis=-1)
        return self._result

    # -- watchdog ---------------------------------------------------------

    def _watchdog_deadline(self, timeout: Optional[float]) -> Optional[float]:
        """Absolute deadline for this wait, measured from the Start stamp (the
        watchdog bounds total in-flight time, not time inside wait())."""
        t = timeout
        if t is None:
            t = getattr(self.dispatcher.config, "watchdog_timeout_s", 0.0)
        if not t or t <= 0:
            return None
        return (self._started_at or time.monotonic()) + t

    def describe(self) -> str:
        """One-line stuck-request descriptor for the watchdog log."""
        d = self.desc
        s = (
            f"{d.kind} name={self.name or self.uid} algo={self.algo} "
            f"count={d.count} dtype={d.data_type.name} axes={d.group.axes} "
            f"payload={self._payload}B epoch={self._epoch}"
        )
        br = self._breaker
        if br is not None and br.state != supervisor.CLOSED:
            # the ladder's state is part of the request's identity while it
            # lasts: a watchdog report on a DEGRADED dispatch must say so
            s += f" breaker={br.name}:{br.state}"
        return s

    def _watchdog_trip(self, phase: str) -> None:
        """Log the stuck descriptor (core/stats.py keeps the event record) and
        raise the recoverable timeout."""
        waited = time.monotonic() - (self._started_at or time.monotonic())
        desc = self.describe()
        tr = obs._tracer
        if tr is not None:
            # on the stuck request's OWN track, before the flight record is
            # cut (record_watchdog_event) so the dump contains it
            tr.instant("watchdog.trip", "watchdog", track=self._trace_name,
                       req=self.name or self.uid, phase=phase,
                       waited_s=round(waited, 3), descriptor=desc)
        stats_mod.record_watchdog_event(desc, phase, waited)
        raise MLSLTimeoutError(
            f"watchdog: request stuck in {phase} for {waited:.2f}s: {desc}"
        )

    def _block_ready(self, out: jax.Array, deadline: Optional[float]) -> None:
        if deadline is None:
            jax.block_until_ready(out)
            return
        # exponential-backoff poll: fast completions (the common case) pay
        # ~10 µs over plain block_until_ready, a genuine hang converges to
        # 1 ms polls until the deadline trips
        delay = 1e-5
        while not _array_is_ready(out):
            if time.monotonic() > deadline:
                self._watchdog_trip("wait")
            time.sleep(delay)
            delay = min(delay * 2, 1e-3)

    # -- wait/test --------------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> jax.Array:
        # A completed request can be wait()ed any number of times, whether it
        # completed via wait() or test() (MPI semantics: MPI_Wait on a completed
        # request returns immediately).
        if not self.is_started and self._result is not None:
            return self._result
        mlsl_assert(self.is_started, "request was not started")
        tr = obs._tracer
        t0 = tr.now() if tr is not None else 0
        attempt = 0
        while True:
            try:
                out = self._wait_inner(timeout)
            except Exception as e:
                # rung 2 for the wait side: a TRANSIENT failure surfacing at
                # wait (an injected fault at the wait site, a dispatch error
                # that exhausted ITS retries, a device read error) re-Starts
                # the stored buffer — the in-flight round is suspect, and a
                # fresh epoch supersedes anything still racing. Worst case
                # (permanently-transient fault) is (retries+1)^2 dispatch
                # attempts: both layers spend their own small budget.
                cfg = self.dispatcher.config
                if (
                    supervisor.classify(e)
                    is not supervisor.ErrorClass.TRANSIENT
                    or attempt >= getattr(cfg, "comm_retries", 0)
                    or self._last_buf is None
                ):
                    # the round is failing: drain its queued CHKP verdicts
                    # (logged, never raised here — the real error must stay
                    # primary) so a LATER healthy request's wait cannot
                    # inherit and mis-surface them
                    self._drain_chkp_logged()
                    raise
                delay = supervisor.jittered_backoff(
                    getattr(cfg, "comm_retry_backoff_s", 0.05), attempt
                )
                stats_mod.record_comm_retry(
                    "wait", self.name or str(self.uid), e, attempt + 1, delay
                )
                log_debug(
                    "transient wait failure of %s (%s); re-dispatching, "
                    "retry %d in %.3fs", self.name or self.uid, e,
                    attempt + 1, delay,
                )
                attempt += 1
                time.sleep(delay)
                self.start(self._last_buf, _rewind_ef=True)
                continue
            break
        self.is_started = False
        # the round is over: the retry buffer and residual snapshot are only
        # needed while in flight — release them or every request permanently
        # retains a gradient-sized device array between rounds
        self._last_buf = None
        self._ef_snapshot = (None, None)
        if checker._pending:
            # CHKP_VALUES round boundary: resolve every finiteness verdict
            # queued since the last completion with ONE device sync (raises
            # MLSLError naming all offending buffers of the round)
            checker.flush_values()
        if tr is not None:
            # the wait STALL: host time blocked for this request (dispatch
            # race + device completion) — the per-op overlap-loss signal
            # behind Statistics.overlap_report's p50/p95 fields. algo rides
            # along because THIS span holds the wire time the per-algorithm
            # trace summary (obs/export.summarize) attributes — the dispatch
            # span alone is only the async enqueue cost.
            tr.complete("wait", "req", t0, track=self._trace_name,
                        req=self.name or self.uid, epoch=self._epoch,
                        algo=self.algo)
        m = obs_metrics._registry
        if m is not None:
            self._record_done_metrics(m)
        return out

    def _wait_inner(self, timeout: Optional[float]) -> jax.Array:
        """One wait attempt: chaos site, dispatch drain, error surface,
        assemble, block. Split out so wait() can retry transients."""
        if chaos._plans:
            chaos.inject("request.wait", request=self.name or self.uid,
                         kind=self.desc.kind)
        deadline = self._watchdog_deadline(timeout)
        self.dispatcher.wait_dispatched(self, deadline)
        if self._dispatch_error is not None:
            err, self._dispatch_error = self._dispatch_error, None
            self.is_started = False
            raise err
        out = self._assemble()
        self._block_ready(out, deadline)
        return out

    def _drain_chkp_logged(self) -> None:
        """Resolve any queued CHKP_VALUES verdicts on a FAILING round without
        letting a CHKP violation replace the round's real error: the verdict
        outcome is logged (and counted), the queue is clean for the next
        round."""
        if not checker._pending:
            return
        try:
            checker.flush_values()
        except MLSLError as ce:
            log_warning(
                "CHKP verdicts from the failed round of %s: %s",
                self.name or self.uid, ce,
            )

    def test(self) -> tuple:
        """Non-blocking completion poll -> (is_completed, result_or_None)."""
        if not self.is_started:
            return True, self._result
        if chaos._plans:
            chaos.inject("request.test", request=self.name or self.uid,
                         kind=self.desc.kind)
        self.dispatcher.flush()
        if self._dispatch_error is not None:
            err, self._dispatch_error = self._dispatch_error, None
            self.is_started = False
            self._drain_chkp_logged()
            raise err
        # A dispatch racing on the progress thread builds _results incrementally;
        # check in-flight FIRST — once it clears, _results is fully built.
        if self.dispatcher.is_in_flight(self.uid) or not self._results:
            return False, None
        ready = all(_array_is_ready(r) for r in self._results)
        if ready:
            out = self._assemble()
            jax.block_until_ready(out)
            self.is_started = False
            self._last_buf = None  # round over: release the retry buffer
            self._ef_snapshot = (None, None)
            if checker._pending:
                checker.flush_values()  # CHKP_VALUES round boundary
            tr = obs._tracer
            if tr is not None:
                tr.instant("test.done", "req", track=self._trace_name,
                           req=self.name or self.uid, epoch=self._epoch)
            m = obs_metrics._registry
            if m is not None:
                self._record_done_metrics(m)
            return True, out
        return False, None

    def _record_done_metrics(self, m) -> None:
        """Telemetry-plane feed at round completion (metrics armed only):
        the dispatch->wait in-flight latency histogram plus the achieved
        algbw (payload bytes over in-flight time — the algorithm-bandwidth
        definition) labeled by the algorithm the selection table chose and
        its tier shape, so /metrics exposes the per-algo/per-tier bandwidth
        distribution a tuned profile's effect shows up in."""
        started = self._started_at
        if not started:
            return
        waited_s = time.monotonic() - started
        m.observe("mlsl_dispatch_wait_ms", waited_s * 1e3,
                  kind=self.desc.kind)
        if waited_s > 0 and self._payload:
            m.observe(
                "mlsl_algbw_gbps", self._payload / waited_s / 1e9,
                buckets=obs_metrics.ALGBW_BUCKETS_GBPS,
                algo=self.algo,
                tier="two-tier" if self.algo == "hier" else "flat",
            )


def in_graph_descriptor(kind: str, name: str, algo: str, count: int,
                        data_type: DataType, group: ProcessGroup) -> str:
    """One-line descriptor for an IN-GRAPH collective round (the compiled
    overlap engine, comm/overlap.py). The rounds never construct a
    CommRequest — the whole comm segment is one compiled program — but
    stats/trace tooling reads ONE descriptor grammar, so this mirrors
    CommRequest.describe() field-for-field with an ``in_graph=1`` marker in
    place of the epoch (in-graph rounds have no per-round host state)."""
    payload = count * dtype_size(data_type)
    return (
        f"{kind} name={name} algo={algo} count={count} "
        f"dtype={data_type.name} axes={group.axes} "
        f"payload={payload}B in_graph=1"
    )


def _unwrap_chaos(fn):
    """The compiled program beneath the chaos instrumentation (the wrappers'
    ``_mlsl_inner`` — the same jit object the dispatch path calls, so the
    warm hits the same cache; NOT ``__wrapped__``, which on a bare jitted fn
    is the raw un-jitted Python callable). The precompile warm must NOT pass
    the chaos sites: it would spend one-shot fault budgets (and shift
    '@after N' schedules) inside Commit instead of the training step those
    faults target, and a 'hang' would wedge Commit where no watchdog is
    armed."""
    return getattr(fn, "_mlsl_inner", fn)


def _custom_wire_bytes(codec, geoms) -> int:
    """Compressed-image bytes of one full payload under a user CustomCodec,
    via shape-only tracing of its compress fn (0 when untraceable — the
    stats row then reads 'custom: 0' rather than lying)."""
    total = 0
    for n, _ in geoms:
        try:
            out = jax.eval_shape(
                codec.compress, jax.ShapeDtypeStruct((n,), jnp.float32)
            )
            total += int(np.prod(out.shape)) * np.dtype(out.dtype).itemsize
        except Exception:
            return 0
    return total


def _check_recv_count(d: CommDesc) -> None:
    """Compressed reduce_scatter derives recv_count as count // group_size; a
    caller-supplied value that disagrees would silently change placement."""
    if d.kind != "reduce_scatter" or d.recv_count is None:
        return
    g = d.group.size if not d.group.is_self else 1
    mlsl_assert(
        d.recv_count == d.count // g,
        "compressed reduce_scatter recv_count %d != count//group %d",
        d.recv_count,
        d.count // g,
    )


def _normalize_alltoallv(d: CommDesc) -> dict:
    """Expand user count/offset arrays into full static matrices.

    MPI semantics: S[i][j] = elements i->member j. 1-D arrays mean 'same on every
    rank' (S[i][j] = counts[j]); (G, G) arrays give the full instance-uniform
    matrix (every group instance exchanges the same geometry). Offsets default to
    the packed (cumulative) layout. The receive matrix is derived: R[i][j] = S[j][i].

    (W, G) arrays (world size x group size, W != G) select per-rank mode: row w is
    what world rank w sends to each member of ITS OWN group instance — the full
    generality of each MPI rank passing its own count vectors
    (reference src/comm_ep.cpp:1188-1265), so different instances of a subgroup
    may exchange different geometries.
    """
    g = d.group.size
    w = d.group.topology.world_size
    a = np.asarray(d.send_counts, dtype=int)
    if a.ndim == 2 and a.shape == (w, g) and w != g:
        return _normalize_alltoallv_per_rank(d, a)

    def packed(mat):
        return np.hstack([np.zeros((g, 1), int), np.cumsum(mat, axis=1)[:, :-1]])

    def expand(arr):
        a = np.asarray(arr, dtype=int)
        if a.ndim == 1:
            return np.tile(a, (g, 1))
        mlsl_assert(a.shape == (g, g), "counts/offsets matrix must be (%d,%d)", g, g)
        return a

    s = expand(d.send_counts)
    soff = packed(s) if d.send_offsets is None else expand(d.send_offsets)
    r = s.T
    if d.recv_counts is not None:
        # MPI requires recvcounts[i][j] == sendcounts[j][i]; a mismatch is a
        # usage error the reference would deadlock/corrupt on — raise instead.
        mlsl_assert(
            np.array_equal(expand(d.recv_counts), r),
            "alltoallv recv_counts do not match transposed send_counts",
        )
    roff = packed(r) if d.recv_offsets is None else expand(d.recv_offsets)
    recv_len = int(np.max(roff + r)) if g > 0 else 1
    to_t = lambda m: tuple(tuple(int(v) for v in row) for row in m)
    return dict(S=to_t(s), Soff=to_t(soff), Roff=to_t(roff), recv_len=max(recv_len, 1))


def _normalize_alltoallv_per_rank(d: CommDesc, s: np.ndarray) -> dict:
    """Per-rank mode: each world rank supplies its own (G,) count/offset rows,
    stacked into (W, G) arrays. The receive geometry is DERIVED from the send
    matrix via the member table (R[w][j] = S[member_j_of_w's_instance][pos(w)]);
    explicit recv_counts must match it — the MPI pairwise invariant
    (sendcounts[j]@i == recvcounts[i]@j), checked here at trace time instead of
    deadlocking/corrupting at run time like a mismatched MPI exchange would."""
    g = d.group.size
    w = d.group.topology.world_size
    mlsl_assert(
        d.group.is_uniform,
        "per-rank alltoallv requires equal-size groups (ragged partitions are "
        "spelled with zero counts on an equal-size group; docs/DESIGN.md)",
    )
    M = collectives._member_world_table(d.group)  # (W, G)
    pos = np.empty(w, dtype=int)
    for p in range(w):
        pos[p] = list(M[p]).index(p)

    def packed(mat):
        return np.hstack([np.zeros((w, 1), int), np.cumsum(mat, axis=1)[:, :-1]])

    def expand(arr, name):
        a = np.asarray(arr, dtype=int)
        if a.ndim == 1:
            a = np.tile(a, (w, 1))
        mlsl_assert(
            a.shape == (w, g),
            "per-rank alltoallv %s must be (world=%d, group=%d), got %s",
            name, w, g, a.shape,
        )
        return a

    soff = packed(s) if d.send_offsets is None else expand(d.send_offsets,
                                                           "send_offsets")
    r = s[M, pos[:, None]]  # R[w][j] = S[M[w][j]][pos[w]]
    if d.recv_counts is not None:
        mlsl_assert(
            np.array_equal(expand(d.recv_counts, "recv_counts"), r),
            "alltoallv recv_counts violate the MPI pairwise invariant: "
            "recv_counts[w][j] must equal member j's send count toward w",
        )
    roff = packed(r) if d.recv_offsets is None else expand(d.recv_offsets,
                                                           "recv_offsets")
    recv_len = int(np.max(roff + r)) if r.size else 1
    to_t = lambda m: tuple(tuple(int(v) for v in row) for row in m)
    return dict(Sw=to_t(s), Swoff=to_t(soff), Rwoff=to_t(roff),
                recv_len=max(recv_len, 1))


def _array_is_ready(arr: jax.Array) -> bool:
    try:
        return bool(arr.is_ready())
    except AttributeError:  # pragma: no cover - very old jax
        jax.block_until_ready(arr)
        return True


class Dispatcher:
    """Host-side dispatch policy: immediate async launch, or newest-first deferral.

    The reference's endpoint servers pull commands from a queue and (optionally) serve
    the newest large allreduce first (eplib/cqueue.c:1999-2012 routing to
    allreduce_pr.c LIFO). Here the queue is a host-side stack of not-yet-launched
    requests; flush() launches them LIFO. Small messages bypass the stack entirely.

    Progress is autonomous, as in the reference (eplib's servers drive the network
    without the app thread, eplib/allreduce_pr.c:69-278): a daemon thread flushes
    deferred requests after a short coalescing window
    (config.msg_priority_flush_ms), so a large deferred allreduce makes progress
    even if the app never calls wait()/test(). The window is what preserves
    newest-first ordering for back-to-back starts: requests deferred within it are
    launched together, LIFO.
    """

    def __init__(self, config):
        self.config = config
        self._pending: List[tuple] = []  # stack of (request, buf)
        self._by_id: dict = {}           # req uid -> (request, buf), native path
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._in_flight: set = set()     # uids popped from the queue, dispatch running
        self._thread: Optional[threading.Thread] = None
        self._deadline = 0.0
        self._stopped = False
        self._native = None
        self._native_tried = False

    def _ensure_native_locked(self):
        """Lazily bind the C++ priority queue (config may be toggled post-init).
        Caller must hold self._lock — the check-and-swap must not race submits."""
        cfg = self.config
        if not self._native_tried or (
            self._native is not None
            and self._native.params != (cfg.msg_priority_threshold, cfg.msg_priority_mode)
            and self._native.pending() == 0  # never strand deferred entries
        ):
            self._native_tried = True
            try:
                from mlsl_tpu.native import NativeScheduler

                self._native = NativeScheduler(
                    cfg.msg_priority_threshold, cfg.msg_priority_mode
                )
            except (RuntimeError, ImportError):
                self._native = None
        return self._native

    def submit(self, req: CommRequest, buf: jax.Array) -> None:
        cfg = self.config
        if not cfg.msg_priority or req.desc.kind == "barrier":
            if req.desc.kind == "barrier":
                # A barrier orders everything before it: launch any deferred
                # requests first so they are on the wire when the barrier lands.
                self.flush()
            req._dispatch(buf)
            return
        if req._payload <= cfg.msg_priority_threshold:
            # small message: below every deferral threshold in both the native
            # and Python schedulers — dispatch immediately without touching the
            # lock or the ctypes queue (the per-layer hot path)
            req._dispatch(buf)
            return
        native = None
        immediate = False
        with self._lock:
            native = self._ensure_native_locked()
            if native is not None:
                immediate = native.submit(req.uid, req.desc.payload_bytes())
                if not immediate:
                    self._by_id[req.uid] = (req, buf, req._epoch)
                    self._note_deferred_locked()
        if native is not None:
            if immediate:
                req._dispatch(buf)  # outside the lock: may trigger compilation
            else:
                tr = obs._tracer
                if tr is not None:
                    tr.instant("defer", "req", track=req._trace_name,
                               req=req.name or req.uid, bytes=req._payload,
                               scheduler="native")
                log_debug(
                    "deferred request %s (%d B)", req.name, req.desc.payload_bytes()
                )
            return
        # payload > threshold here (the small-message fast path returned above)
        with self._lock:
            # A restart of an already-deferred request supersedes the stale entry
            # (otherwise flush would re-dispatch the old buffer last and clobber
            # the fresh results). An entry already popped mid-flight is dropped
            # by the epoch check in _dispatch.
            self._pending = [e for e in self._pending if e[0] is not req]
            self._pending.append((req, buf, req._epoch))
            self._note_deferred_locked()
        tr = obs._tracer
        if tr is not None:
            tr.instant("defer", "req", track=req._trace_name,
                       req=req.name or req.uid, bytes=req._payload,
                       scheduler="python")
        log_debug("deferred request %s (%d B)", req.name, req._payload)

    def _note_deferred_locked(self) -> None:
        """Arm the progress thread: dispatch happens msg_priority_flush_ms from the
        LAST deferral (coalescing window), with no app poll required."""
        import time

        self._deadline = time.monotonic() + self.config.msg_priority_flush_ms / 1e3
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._progress_loop, daemon=True, name="mlsl-dispatch"
            )
            self._thread.start()
        self._cv.notify_all()

    def _progress_loop(self) -> None:
        import time

        while True:
            with self._cv:
                while not self._stopped and not (self._pending or self._by_id):
                    self._cv.wait()
                if self._stopped:
                    return
                deadline = self._deadline
            delay = deadline - time.monotonic()
            if delay > 0:
                time.sleep(min(delay, 0.05))
                continue
            try:
                self.flush()
            except Exception as e:  # pragma: no cover - defensive: keep daemon alive
                log_error("background flush failed: %r", e)

    def shutdown(self) -> None:
        """Launch anything still deferred and stop the progress thread."""
        self.flush()
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # A still-alive progress thread means a dispatch is wedged (or
                # a chaos hang is armed) — abandoning it silently would make
                # the eventual symptom undiagnosable.
                log_warning(
                    "dispatch progress thread %s still alive after 5s join "
                    "(%d deferred requests pending); abandoning it",
                    self._thread.name,
                    self.pending_count,
                )
            self._thread = None

    def flush(self) -> None:
        if not self._pending and not self._by_id:
            # Nothing deferred: skip the lock (the hot wait()/test() path).
            # Lock-free read is safe: entries THIS thread cares about were
            # added by this thread (visible), and flush marks a request
            # in-flight BEFORE removing it from the queues (ordering below),
            # so a request is never in neither place.
            return
        # INVARIANT for the lock-free fast paths in flush()/wait_dispatched()/
        # is_in_flight(): _in_flight gains a uid BEFORE the entry leaves
        # _pending/_by_id. The lock orders writers, but lock-free readers see
        # individual bytecodes — with the opposite order a reader could find
        # the queues empty and the uid not yet in-flight while its dispatch
        # has not run, and read half-built _results.
        if self._native is not None:
            with self._lock:
                order = self._native.drain()
                items = [self._by_id[rid] for rid in order if rid in self._by_id]
                self._in_flight.update(e[0].uid for e in items)
                for rid in order:
                    self._by_id.pop(rid, None)
            self._dispatch_items(items)
            return
        with self._lock:
            self._in_flight.update(e[0].uid for e in self._pending)
            pending, self._pending = self._pending, []
            items = list(reversed(pending)) if self.config.msg_priority_mode else pending
        self._dispatch_items(items)

    def _dispatch_items(self, items) -> None:
        """Launch outside the lock (may compile); then release waiters.

        A dispatch failure is recorded on ITS request by _dispatch itself
        (under the request's dispatch lock, re-raised by that request's
        wait()/test()), so it neither strands the remaining items of the batch
        nor, on the progress thread, kills the daemon."""
        if not items:
            return
        try:
            for req, buf, epoch in items:
                req._dispatch(buf, epoch)
        finally:
            with self._cv:
                for req, _, _ in items:
                    self._in_flight.discard(req.uid)
                self._cv.notify_all()

    def is_in_flight(self, uid: int) -> bool:
        # GIL-atomic set membership; flush() adds the uid BEFORE the paired
        # _pending/_by_id removal (see the invariant there), so a caller that
        # saw the queues empty observes the uid here until its dispatch
        # completes (per-poll lock acquisition would dominate the test() floor)
        return uid in self._in_flight

    def wait_dispatched(
        self, req: CommRequest, deadline: Optional[float] = None
    ) -> None:
        """Ensure req's programs have been launched: flush the queue, then wait out
        a dispatch racing on the progress thread (its _results would otherwise be
        read half-built). ``deadline`` (monotonic) is the request watchdog's
        bound: a dispatch wedged on the progress thread past it trips the
        recoverable MLSLTimeoutError instead of blocking forever."""
        self.flush()
        if req.uid not in self._in_flight:  # hot path: nothing racing
            return
        with self._cv:
            while req.uid in self._in_flight:
                if deadline is None:
                    self._cv.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    req._watchdog_trip("dispatch")
                self._cv.wait(min(remaining, 0.05))

    @property
    def pending_count(self) -> int:
        if self._native is not None:
            return self._native.pending()
        return len(self._pending)


class RequestStorage:
    """Tracks live generic requests so Environment.Wait/Test can free them
    (reference RequestStorage src/mlsl_impl.hpp:60-94)."""

    def __init__(self):
        self._reqs: dict = {}
        self._lock = threading.Lock()

    def register(self, req: CommRequest) -> None:
        with self._lock:
            self._reqs[req.uid] = req

    def remove(self, req: CommRequest) -> None:
        with self._lock:
            self._reqs.pop(req.uid, None)

    def __len__(self) -> int:
        return len(self._reqs)
