"""Int8-compressed ring collectives with error feedback.

The TPU-native equivalent of the reference's quantized allreduce (eplib routes
MPI_QUANT_OP allreduces through quantize -> reduce -> dequantize on the endpoint
server, eplib/cqueue.c:1977-1994, with the int8 block transform + error-feedback diff
buffer of quant/quant.c:153-211).

Design: a ring reduce-scatter + ring all-gather built from ``lax.ppermute`` where every
hop moves int8 payload + per-block f32 scales instead of f32 data — 4x less ICI
traffic. Each hop dequantizes, accumulates, and requantizes (the reference's custom
MPI reduction op does the same per pair). The client-side error-feedback residual is
returned functionally: callers carry it between iterations
(CommRequest holds it per request).

Ring index math: rank p's travelling partial starts at chunk (p-1) mod G; after G-1
hops it has accumulated all ranks' contributions for chunk p (MPI reduce-scatter
placement). The all-gather phase then circulates each rank's owned chunk.

Registry note (mlsl_tpu.codecs): this module stays the int8 seed wire — the
codec lab's ``Int8Codec`` wraps ``quantize_blocks_ref``/``dequantize_blocks_ref``
behind the declared encode/decode/geometry contract (and ``hier._block_quant_shared``
behind its DCN-hop hook), so a calibrated per-set block lands here through the
same programs; non-int8 registry codecs route through comm/codec.py instead.
"""

from __future__ import annotations

import functools
from typing import Callable, Tuple

import jax.numpy as jnp
from jax import lax

from mlsl_tpu.comm.mesh import ProcessGroup
from mlsl_tpu.comm.collectives import _axis_sizes
from mlsl_tpu.log import mlsl_assert
from mlsl_tpu.ops import quant_kernels as qk


def _quant(x2d, use_pallas):
    if use_pallas:
        # non-jitted internals; we are already inside jit/shard_map
        n, block = x2d.shape
        return qk._quantize_pallas(x2d)
    return qk.quantize_blocks_ref(x2d)


def _dequant(q, s, use_pallas):
    # Always the XLA form here, even when use_pallas enables the quantize
    # kernel: on bare 2-D blocks (exactly the ring's case) the pallas dequant
    # measured 0.88-1.01x of this (never a win) at 256 MiB streaming, and the
    # jnp multiply fuses into the ring's accumulate adds, which an opaque
    # pallas_call cannot. (The public 1-D dequantize() wrapper is the
    # opposite case — see quant_kernels.dequantize.)
    del use_pallas
    return qk.dequantize_blocks_ref(q, s)


def _chunk_unit(rc: int, use_pallas: bool, block: int) -> int:
    """Ring-chunk alignment unit (elements). On the pallas path chunks align
    to tile-legal rows (ROW_TILE); large per-rank slices align to PACK_ROWS
    rows instead so every per-hop QUANTIZE takes the packed-scale kernel
    (dense (g, 128) scales — see quant_kernels; ~1.6x at streaming sizes;
    the dequant direction always uses the XLA form, see _dequant). The
    coarse unit engages only where its padding waste is bounded by 12.5%
    (same 8*block*PACK_ROWS threshold as quantize())."""
    if not use_pallas:
        return block
    if rc >= 8 * block * qk.PACK_ROWS:
        return block * qk.PACK_ROWS
    return block * qk.ROW_TILE


def use_pallas_for(group: ProcessGroup, block: int) -> bool:
    """Whether the ring's quantize hops take the Pallas kernel path on this
    group's mesh (the same predicate build_quantized_collective applies)."""
    return (
        group.topology.mesh.devices.flat[0].platform == "tpu" and block % 128 == 0
    )


def ring_aligned_rc(group: ProcessGroup, rc: int, block: int) -> int:
    """Per-rank ring slice length >= ``rc`` aligned to the chunk unit.

    Coalesced quantized payloads (core/bucketing.py) size the bucket so each
    rank's slice already sits on the ``_chunk_unit`` boundary: the ring then
    adds zero internal padding and — on the pallas path — every per-hop
    quantize sees a row count that engages the packed-scale kernels (dense
    (g, 128) scales, the fast path; see ops/quant_kernels.py). Aligning can
    push ``rc`` across the coarse-unit threshold, so iterate to the fixpoint
    (units are nested multiples: block | block*ROW_TILE | block*PACK_ROWS —
    one extra pass suffices)."""
    use_pallas = use_pallas_for(group, block)
    for _ in range(2):
        unit = _chunk_unit(rc, use_pallas, block)
        rc = -(-rc // unit) * unit
    return rc


def logical_residual(err, g, chunk, rc, count):
    """Ring-layout error-feedback residual -> the logical buffer layout.

    The residual a quantized request carries (CommRequest._err) lives in the
    ring's chunked layout: ``(*grid, g*chunk)`` where slice ``j`` of the
    logical partition (length ``rc``) sits at the START of padded chunk
    ``j`` (see ``_to_chunks``). When the recovery supervisor degrades the
    quantized ring to the plain allreduce, the un-sent residual must be
    flushed INTO the plain payload — delivered exactly once, not dropped —
    so this inverts the chunking: take the first ``rc`` elements of each
    chunk and truncate the concatenation to ``count``. Residual accumulated
    in the zero-padding region is discarded: it never contributes to the
    healthy path's output either (the ring result is likewise truncated).

    Trailing-dim-only reshapes/slices: sharding over the grid axes is
    preserved, so the flush is local (no communication)."""
    lead = err.shape[:-1]
    e = err.reshape(*lead, g, chunk)[..., :rc]
    return e.reshape(*lead, g * rc)[..., :count]


def _to_chunks(x, G, rc, chunk):
    """(n_orig,) -> (G, chunk): slice j of the logical partition (length rc) sits at
    the START of padded chunk j, so ring chunk ownership == MPI slice placement."""
    xp = jnp.pad(x, (0, G * rc - x.shape[0]))
    return jnp.pad(xp.reshape(G, rc), ((0, 0), (0, chunk - rc)))


def _ring_body(x, err, *, axis, G, rc, chunk, block, n_orig, mode, use_pallas):
    """Local body (inside shard_map). x: (n_orig,), err: (G*chunk,)."""
    xq = _to_chunks(x.astype(jnp.float32), G, rc, chunk).reshape(-1) + err
    # Entry quantization + error feedback (reference quant_quantize semantics).
    q0, s0 = _quant(xq.reshape(-1, block), use_pallas)
    xhat = _dequant(q0.reshape(-1, block), s0, use_pallas).reshape(-1)
    new_err = xq - xhat
    chunks = xhat.reshape(G, chunk)

    me = lax.axis_index(axis)
    perm = [(i, (i + 1) % G) for i in range(G)]

    if G == 1:
        result = xhat[:n_orig] if mode == "allreduce" else xhat[:rc]
        return result, new_err

    # --- phase 1: ring reduce-scatter (quantized wire) ---
    partial = lax.dynamic_index_in_dim(chunks, (me - 1) % G, keepdims=False)

    def rs_step(t, partial):
        q, s = _quant(partial.reshape(-1, block), use_pallas)
        q = lax.ppermute(q, axis, perm)
        s = lax.ppermute(s, axis, perm)
        received = _dequant(q.reshape(-1, block), s, use_pallas).reshape(-1)
        local = lax.dynamic_index_in_dim(chunks, (me - 2 - t) % G, keepdims=False)
        return received + local

    partial = lax.fori_loop(0, G - 1, rs_step, partial)
    # partial = fully reduced chunk `me`; its first rc elements are MPI slice `me`

    if mode == "reduce_scatter":
        return partial[:rc], new_err

    # --- phase 2: ring all-gather (quantized wire) ---
    qo, so = _quant(partial.reshape(-1, block), use_pallas)
    own = _dequant(qo.reshape(-1, block), so, use_pallas).reshape(-1)
    out = jnp.zeros((G, chunk), dtype=jnp.float32)
    out = lax.dynamic_update_index_in_dim(out, own, me, axis=0)

    def ag_step(k, carry):
        out, q, s = carry
        q = lax.ppermute(q, axis, perm)
        s = lax.ppermute(s, axis, perm)
        val = _dequant(q.reshape(-1, block), s, use_pallas).reshape(-1)
        idx = (me - 1 - k) % G
        return lax.dynamic_update_index_in_dim(out, val, idx, axis=0), q, s

    out, _, _ = lax.fori_loop(0, G - 1, ag_step, (out, qo, so))
    return out[:, :rc].reshape(-1)[:n_orig], new_err


_cache: dict = {}


def ring_geometry(
    kind: str, group: ProcessGroup, count: int, block: int
) -> Tuple[int, int, int, int, bool]:
    """-> (g, rc, chunk, err_len, use_pallas): the quantized-ring layout for
    (kind, group, count, block). The single source of the geometry both the
    standalone compiled program (``build_quantized_collective``) and the
    in-graph compiled-overlap body (``inline_body``) use — parity between
    the two paths depends on identical rc/chunk placement."""
    g = 1 if group.is_self else group.size
    mlsl_assert(group.colors is None, "quantized collectives require axis-aligned groups")
    use_pallas = use_pallas_for(group, block)
    # Per-rank logical slice rc, padded to the block/tile unit -> ring chunk.
    if kind == "reduce_scatter":
        mlsl_assert(count % g == 0, "reduce_scatter count %d %% group %d != 0", count, g)
        rc = count // g
    else:
        rc = -(-count // g)
    unit = _chunk_unit(rc, use_pallas, block)
    chunk = -(-rc // unit) * unit
    return g, rc, chunk, g * chunk, use_pallas


def inline_body(
    kind: str, group: ProcessGroup, count: int, block: int
) -> Tuple[Callable, int]:
    """-> (local body ``(x, err) -> (result, new_err)``, error-feedback
    length): the quantize -> ring -> dequantize round as an UN-compiled
    shard_map body, for embedding in a larger program (the compiled overlap
    engine's in-graph quantized units). Same body selection as
    ``build_quantized_collective`` — single-axis groups ride the compressed
    ring, degenerate/multi-axis groups the entry-quantization + psum
    fallback — so the overlap path is op-for-op the host request's program."""
    sizes = _axis_sizes(group.topology.mesh)
    g, rc, chunk, err_len, use_pallas = ring_geometry(kind, group, count, block)

    if g > 1 and len(group.axes) == 1:
        body = functools.partial(
            _ring_body,
            axis=group.axes[0],
            G=g,
            rc=rc,
            chunk=chunk,
            block=block,
            n_orig=count,
            mode=kind,
            use_pallas=use_pallas,
        )
    else:
        def body(x, err, _axes=group.axes, _g=g):
            xq = _to_chunks(x.astype(jnp.float32), _g, rc, chunk).reshape(-1) + err
            q0, s0 = _quant(xq.reshape(-1, block), use_pallas)
            xhat = _dequant(q0.reshape(-1, block), s0, use_pallas).reshape(-1)
            new_err = xq - xhat
            red = lax.psum(xhat, _axes) if _axes and _g > 1 else xhat
            red_chunks = red.reshape(_g, chunk)
            if kind == "reduce_scatter" and _g > 1:
                from mlsl_tpu.comm.collectives import _group_rank

                me = _group_rank(_axes, sizes)
                mine = lax.dynamic_index_in_dim(red_chunks, me, axis=0, keepdims=False)
                return mine[:rc], new_err
            if kind == "reduce_scatter":
                return red_chunks[0, :rc], new_err
            return red_chunks[:, :rc].reshape(-1)[:count], new_err

    return body, err_len


def build_quantized_collective(
    kind: str, group: ProcessGroup, count: int, block: int,
    ring: str = "lax", slots=None, bidir=None, dcn_codec=None,
    topk_ratio: float = 0.01,
) -> Tuple[Callable, int]:
    """-> (compiled fn (buf, err) -> (result, new_err), error-feedback length).

    ``kind``: 'allreduce' or 'reduce_scatter' (SUM only — the reference's quantized
    path is likewise allreduce-SUM, eplib/cqueue.c:1977-1994; callers must reject
    other ops).
    Single-axis groups use the compressed ring; degenerate/multi-axis groups fall back
    to entry-quantization + psum (same numerics contract, uncompressed wire).

    ``ring`` selects the hop engine: ``'lax'`` is this module's composed
    ring (ppermute programs, XLA-scheduled); ``'pallas'`` is the fused
    kernel (ops/ring_kernels.py — in-kernel per-hop codec, double-buffered
    RDMA), selected by the algos table as ``'pallas_ring'``. Both share the
    entry error-feedback math and the slice-at-chunk-start layout, so the
    residual contract (and the supervisor's logical_residual degrade flush)
    is identical. ``'hier'`` is the two-tier hierarchical wire
    (comm/algos/hier.py, selected as ``'hier'``): the codec applies ONLY on
    the inter-slice DCN hop (``dcn_codec``: int8-blockwise shared-scale
    integer sum / top-k / f32) and the residual covers each member's own
    1/L shard — a different layout (CommRequest._err_layout == 'hier'),
    inverted on degrade by hier.flush_residual instead of
    logical_residual, but the same snapshot/rewind and breaker machinery.
    """
    from mlsl_tpu.comm.collectives import _group_key

    mesh = group.topology.mesh
    if ring == "hier":
        from mlsl_tpu.comm.algos import hier

        codec = hier.dcn_codec(dcn_codec)
        tiers = hier.tier_structure(group)
        mlsl_assert(tiers is not None,
                    "hier quantized wire needs a tiered group "
                    "(MLSL_MESH_TIERS or multislice topology)")
        key = (kind, ring, _group_key(group), count, block, codec,
               tiers, topk_ratio if codec == "topk" else None)
        _, _, err_len, _ = hier.quant_geometry(kind, group, count, block)
        fn = _cache.get(key)
        if fn is None:
            body, _ = hier.quant_body(kind, group, count, block,
                                      codec=codec, topk_ratio=topk_ratio)
            from mlsl_tpu.comm.collectives import build_stateful_collective

            fn = _chaos_roundtrip(
                build_stateful_collective(body, mesh), algo="hier"
            )
            _cache[key] = fn
        return fn, err_len
    if ring == "pallas":
        from mlsl_tpu.ops import ring_kernels as rk

        mlsl_assert(rk.eligible_quant(group, block),
                    "pallas quantized ring cannot serve this group/backend")
        slots, bidir = rk.env_slots(slots), rk.env_bidir(bidir)
        key = (kind, ring, _group_key(group), count, block, slots, bidir)
        _, _, _, err_len = rk.quant_geometry(kind, group, count, block)
        fn = _cache.get(key)
        if fn is None:
            body, _ = rk.quant_ring_body(kind, group, count, block,
                                         slots=slots, bidir=bidir)
            fn = _chaos_roundtrip(
                rk.build_flat_program(body, group, kind, stateful=True),
                algo="pallas_ring",
            )
            _cache[key] = fn
        return fn, err_len
    key = (kind, ring, _group_key(group), count, block)
    _, _, _, err_len, _ = ring_geometry(kind, group, count, block)

    fn = _cache.get(key)
    if fn is not None:
        return fn, err_len

    body, _ = inline_body(kind, group, count, block)

    from mlsl_tpu.comm.collectives import build_stateful_collective

    fn = _chaos_roundtrip(build_stateful_collective(body, mesh))
    _cache[key] = fn
    return fn, err_len


def _chaos_roundtrip(fn: Callable, algo: str = "quant_ring") -> Callable:
    """Wrap the compiled ring so every (buf, err) round-trip passes the
    'codec.roundtrip' chaos site — faults at the compressed-wire layer must be
    recoverable (EQuARX/THC pair compressed collectives with correctness
    safeguards; ours is the tested recovery path) — and, when tracing is armed
    (mlsl_tpu.obs), records the host-side quant encode/ring/decode enqueue as
    a 'quant.roundtrip' span (device completion lands in the owning request's
    wait span). ``algo`` names the wire family in the span (the sparse top-k
    path reuses this wrapper — every compressed family shares the codec
    chaos site and the codec circuit breaker)."""
    from mlsl_tpu import chaos
    from mlsl_tpu.obs import tracer as obs

    def roundtrip(buf, err):
        if chaos._plans:
            chaos.inject("codec.roundtrip")
        tr = obs._tracer
        if tr is None:
            return fn(buf, err)
        t0 = tr.now()
        out = fn(buf, err)
        tr.complete("quant.roundtrip", "quant", t0,
                    elems=int(buf.shape[-1]) if hasattr(buf, "shape") else 0,
                    algo=algo)
        return out

    roundtrip.__wrapped__ = fn
    # precompile warm bypass (request._unwrap_chaos): warming at Commit must
    # not consume armed fault budgets at this site
    roundtrip._mlsl_inner = fn
    return roundtrip
