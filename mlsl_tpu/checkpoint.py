"""Checkpoint/resume: orbax-backed persistence of training state.

The reference has no checkpointing (SURVEY.md §5.4 — MLSL only moves bytes; its
closest artifact is the endpoint-server async file-IO offload). A *framework* needs
one, so this module provides it TPU-natively: async orbax saves (the save executes in
the background while training continues — the same overlap idea as eplib's offloaded
file reads), sharding-preserving restore, and trainer integration.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except ImportError:  # pragma: no cover
    _HAVE_ORBAX = False


class CheckpointManager:
    """Save/restore pytrees of (possibly sharded) jax.Arrays by step number."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        if not _HAVE_ORBAX:
            raise RuntimeError("orbax-checkpoint is not available")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=True
            ),
        )

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        """Dispatch an async save of ``state`` (any pytree of arrays)."""
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def restore(self, step: Optional[int] = None, template: Any = None) -> Any:
        """Restore the given (or latest) step. ``template`` — a pytree of arrays or
        ShapeDtypeStructs with shardings — reproduces the original placement."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        if template is not None:
            target = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
                if hasattr(x, "shape")
                else x,
                template,
            )
            return self._mgr.restore(step, args=ocp.args.StandardRestore(target))
        return self._mgr.restore(step)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


def _trainer_state(trainer, step: int) -> dict:
    state = {"params": trainer.params, "step": step}
    # optax state (replicated and/or ZeRO-1 owned-shard buffers) must resume
    # with the params — restarting Adam from zero moments silently diverges
    # the trajectory.
    if getattr(trainer, "_opt_state", None) is not None:
        state["opt_state"] = trainer._opt_state
    if getattr(trainer, "_du_opt_state", None) is not None:
        state["du_opt_state"] = trainer._du_opt_state
    return state


def save_trainer(mgr: CheckpointManager, trainer, step: int, wait: bool = False) -> None:
    """Persist a DataParallelTrainer/HybridTrainer's parameters (and optimizer
    state, when the trainer carries one)."""
    mgr.save(step, _trainer_state(trainer, step), wait=wait)


def restore_trainer(mgr: CheckpointManager, trainer, step: Optional[int] = None) -> Optional[int]:
    """Restore parameters (and optimizer state) in place; returns the restored
    step or None."""
    state = mgr.restore(step, template=_trainer_state(trainer, 0))
    if state is None:
        return None
    trainer.params = state["params"]
    if "opt_state" in state:
        trainer._opt_state = state["opt_state"]
    if "du_opt_state" in state:
        trainer._du_opt_state = state["du_opt_state"]
    return int(state["step"])
