"""Checkpoint/resume: orbax-backed persistence of training state.

The reference has no checkpointing (SURVEY.md §5.4 — MLSL only moves bytes; its
closest artifact is the endpoint-server async file-IO offload). A *framework* needs
one, so this module provides it TPU-natively: async orbax saves (the save executes in
the background while training continues — the same overlap idea as eplib's offloaded
file reads), sharding-preserving restore, and trainer integration.

Hardened for production faults (the chaos layer exercises every path below,
tests/test_chaos.py):

- **Async errors surface.** A failed background save must never be mistaken for
  a committed resume point: ``save()``/``wait()`` run orbax's
  ``check_for_errors`` and re-raise.
- **Checksum manifests.** Every committed step gets a ``manifest-<step>.json``
  of per-file sha256 sums written alongside it; ``verify()`` detects bit-rot.
- **Verified fallback.** ``restore_trainer`` walks steps newest-first and skips
  any step that fails verification (or whose restore raises), resuming from the
  newest *verified* step instead of dying on a corrupt latest.
- **Save retry.** Transient IO errors (OSError) during save dispatch retry with
  exponential backoff (MLSL_CKPT_SAVE_RETRIES / MLSL_CKPT_RETRY_BACKOFF_S).
- **Verified-good steps.** A save made with a passing sentinel audit
  fingerprint (mlsl_tpu.sentinel) records it in the step manifest;
  ``restore_trainer`` prefers the newest VERIFIED step over newer
  unverified ones, so a silently corrupted checkpoint is never the
  preferred resume point once any verified one exists.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, List, Optional

import jax

from mlsl_tpu import chaos
from mlsl_tpu.config import _env_float, _env_int
from mlsl_tpu.log import MLSLError, log_info, log_warning
from mlsl_tpu.obs import tracer as obs

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except ImportError:  # pragma: no cover
    _HAVE_ORBAX = False


class CheckpointManager:
    """Save/restore pytrees of (possibly sharded) jax.Arrays by step number."""

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        save_retries: Optional[int] = None,
        retry_backoff_s: Optional[float] = None,
    ):
        if not _HAVE_ORBAX:
            raise RuntimeError("orbax-checkpoint is not available")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.save_retries = (
            _env_int("MLSL_CKPT_SAVE_RETRIES", 3)
            if save_retries is None
            else save_retries
        )
        self.retry_backoff_s = (
            _env_float("MLSL_CKPT_RETRY_BACKOFF_S", 0.05)
            if retry_backoff_s is None
            else retry_backoff_s
        )
        self._unverified: set = set()  # steps saved but not yet checksummed
        self._bitrot: set = set()      # chaos: steps to corrupt post-manifest
        # step -> passing sentinel audit digest, recorded into the step's
        # manifest at flush (the "verified-good" half of the integrity
        # sentinel: restore_trainer prefers steps that carry one)
        self._fingerprints: dict = {}
        # step -> active world size at save time (elastic mesh): a
        # checkpoint saved on a shrunk world carries ZeRO-1 shard shapes a
        # different world cannot restore — the manifest records the size so
        # restore_trainer can NAME the mismatch instead of surfacing an
        # opaque shape error
        self._worlds: dict = {}
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=True
            ),
        )

    # -- async-error surfacing --------------------------------------------

    def check_errors(self) -> None:
        """Surface a failed background save (orbax ``check_for_errors``) — a
        silent async failure would otherwise let the caller believe the step
        is a committed resume point."""
        chk = getattr(self._mgr, "check_for_errors", None)
        if chk is not None:
            chk()

    # -- save/restore ------------------------------------------------------

    def save(self, step: int, state: Any, wait: bool = False,
             fingerprint: Optional[str] = None,
             world: Optional[int] = None) -> None:
        """Dispatch an async save of ``state`` (any pytree of arrays).

        ``fingerprint`` is a PASSING sentinel audit digest of this state
        (mlsl_tpu.sentinel); it is recorded in the step's manifest, marking
        the step *verified* — ``restore_trainer`` prefers verified steps and
        FaultTolerantLoop's post-restore re-audit compares against it.
        ``world`` is the active world size at save time (elastic mesh),
        recorded in the manifest for restore-time mismatch diagnosis.

        Transient IO errors (OSError) at dispatch retry with exponential
        backoff; anything else propagates (recoverable by FaultTolerantLoop).
        """
        self.check_errors()
        if fingerprint is not None:
            self._fingerprints[step] = fingerprint
        if world is not None:
            self._worlds[step] = int(world)
        tr = obs._tracer
        t0 = tr.now() if tr is not None else 0
        delay = self.retry_backoff_s
        for attempt in range(self.save_retries + 1):
            try:
                # chaos first: an injected OSError exercises the same retry
                # path a flaky filesystem would
                plan = chaos.inject("checkpoint.save", step=step, attempt=attempt)
                if plan is not None and plan.kind == "bitrot":
                    self._bitrot.add(step)
                self._mgr.save(step, args=ocp.args.StandardSave(state))
                break
            except OSError as e:
                if attempt >= self.save_retries:
                    raise
                if tr is not None:
                    tr.instant("ckpt.save.retry", "ckpt", step=step,
                               attempt=attempt + 1, error=repr(e))
                log_warning(
                    "checkpoint save of step %d failed (%s: %s); "
                    "retry %d/%d in %.2fs",
                    step, type(e).__name__, e,
                    attempt + 1, self.save_retries, delay,
                )
                time.sleep(delay)
                delay *= 2
        self._unverified.add(step)
        if tr is not None:
            # dispatch span only: the orbax write itself runs async in the
            # background — its drain lands in the wait() span below
            tr.complete("ckpt.save", "ckpt", t0, step=step, attempts=attempt + 1)
        if wait:
            self.wait()
        # async path: manifests are checksummed at the next drain point
        # (wait()/close()/restore) — never inline on the training hot path,
        # which would stall exactly the overlap the async save buys

    def restore(self, step: Optional[int] = None, template: Any = None) -> Any:
        """Restore the given (or latest) step. ``template`` — a pytree of arrays or
        ShapeDtypeStructs with shardings — reproduces the original placement."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        chaos.inject("checkpoint.restore", step=step)
        tr = obs._tracer
        t0 = tr.now() if tr is not None else 0
        if template is not None:
            target = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
                if hasattr(x, "shape")
                else x,
                template,
            )
            out = self._mgr.restore(step, args=ocp.args.StandardRestore(target))
        else:
            out = self._mgr.restore(step)
        if tr is not None:
            tr.complete("ckpt.restore", "ckpt", t0, step=step)
        return out

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(self._mgr.all_steps())

    def wait(self) -> None:
        tr = obs._tracer
        t0 = tr.now() if tr is not None else 0
        self._mgr.wait_until_finished()
        self.check_errors()
        self._flush_manifests()
        if tr is not None:
            tr.complete("ckpt.drain", "ckpt", t0)

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self.check_errors()  # a failed final save must not vanish at close
        self._flush_manifests()
        self._mgr.close()

    # -- checksum manifests ------------------------------------------------

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, f"manifest-{step}.json")

    def _step_dir(self, step: int) -> Optional[str]:
        """The committed step directory, or None while the save is in flight
        (orbax renames the tmp dir into place only on commit)."""
        cand = os.path.join(self.directory, str(step))
        if os.path.isdir(cand):
            return cand
        for name in os.listdir(self.directory):  # non-default step formats
            p = os.path.join(self.directory, name)
            if (
                os.path.isdir(p)
                and "tmp" not in name
                and name.rsplit("_", 1)[-1] == str(step)
            ):
                return p
        return None

    @staticmethod
    def _file_sha256(path: str) -> str:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for block in iter(lambda: f.read(1 << 20), b""):
                h.update(block)
        return h.hexdigest()

    def _checksum_tree(self, root: str) -> dict:
        files = {}
        for base, _, names in os.walk(root):
            for n in sorted(names):
                p = os.path.join(base, n)
                files[os.path.relpath(p, root)] = self._file_sha256(p)
        return files

    def _flush_manifests(self) -> None:
        """Write ``manifest-<step>.json`` for every save that has committed
        since the last flush, then apply any chaos bit-rot (after the manifest,
        as real rot happens: the manifest records the good bytes, so verify()
        catches the corruption)."""
        live = set(self._mgr.all_steps())
        newest = max(live) if live else None
        for step in sorted(self._unverified):
            d = self._step_dir(step)
            if (
                step not in live
                and d is None
                and newest is not None
                and step < newest
            ):
                # only an OLDER step missing from both the registry and the
                # filesystem was reaped by max_to_keep; the newest save may
                # simply not be listed/committed yet
                self._unverified.discard(step)
                continue
            if d is None:
                continue  # still in flight
            manifest = {"step": step, "written_at": time.time(),
                        "files": self._checksum_tree(d)}
            w = self._worlds.pop(step, None)
            if w is not None:
                manifest["world"] = w
            fp = self._fingerprints.pop(step, None)
            if fp is not None:
                # verified-good marker: the state in this step passed the
                # sentinel's consistency audit at save time, and this digest
                # identifies those exact bytes (post-restore re-audit target)
                manifest["sentinel"] = {"fingerprint": fp}
            tmp = self._manifest_path(step) + ".tmp"
            with open(tmp, "w") as f:
                json.dump(manifest, f)
            os.replace(tmp, self._manifest_path(step))
            self._unverified.discard(step)
            if step in self._bitrot:
                self._bitrot.discard(step)
                self._apply_bitrot(step, d)
        # drop manifests whose step was garbage-collected
        for name in os.listdir(self.directory):
            if name.startswith("manifest-") and name.endswith(".json"):
                try:
                    s = int(name[len("manifest-"):-len(".json")])
                except ValueError:
                    continue
                if s not in live and s not in self._unverified:
                    try:
                        os.remove(os.path.join(self.directory, name))
                    except OSError:
                        pass

    def _apply_bitrot(self, step: int, step_dir: str) -> None:
        """Chaos 'bitrot' kind: flip bytes in the largest payload file of a
        committed checkpoint, simulating on-disk corruption after a clean
        write. verify() must subsequently fail for this step."""
        target, size = None, -1
        for base, _, names in os.walk(step_dir):
            for n in names:
                p = os.path.join(base, n)
                sz = os.path.getsize(p)
                if sz > size:
                    target, size = p, sz
        if target is None:
            return
        with open(target, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(64) or b"\0"
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
        log_warning("chaos: bit-rot injected into step %d (%s)", step, target)

    def recorded_fingerprint(self, step: int) -> Optional[str]:
        """The sentinel audit digest this step's manifest records, or None
        (no manifest yet, or the step was saved without one — an unverified
        checkpoint)."""
        fp = self._fingerprints.get(step)
        if fp is not None:
            return fp  # save dispatched, manifest not yet flushed
        try:
            with open(self._manifest_path(step)) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None
        return (manifest.get("sentinel") or {}).get("fingerprint")

    def recorded_world(self, step: int) -> Optional[int]:
        """The active world size this step's manifest records, or None (no
        manifest, or a pre-elastic save)."""
        w = self._worlds.get(step)
        if w is not None:
            return w
        try:
            with open(self._manifest_path(step)) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None
        w = manifest.get("world")
        return int(w) if w is not None else None

    def verify(self, step: int) -> Optional[bool]:
        """True: manifest present and every file matches. False: corrupt
        (mismatch, missing file, or unreadable manifest). None: no manifest
        (pre-manifest checkpoint or a save that never committed cleanly)."""
        mp = self._manifest_path(step)
        if not os.path.exists(mp):
            return None
        try:
            with open(mp) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return False
        d = self._step_dir(step)
        if d is None:
            return False
        for rel, want in manifest.get("files", {}).items():
            p = os.path.join(d, rel)
            try:
                if self._file_sha256(p) != want:
                    return False
            except OSError:
                return False
        return True


def _trainer_state(trainer, step: int) -> dict:
    state = {"params": trainer.params, "step": step}
    # optax state (replicated and/or ZeRO-1 owned-shard buffers) must resume
    # with the params — restarting Adam from zero moments silently diverges
    # the trajectory.
    if getattr(trainer, "_opt_state", None) is not None:
        state["opt_state"] = trainer._opt_state
    if getattr(trainer, "_du_opt_state", None) is not None:
        state["du_opt_state"] = trainer._du_opt_state
    return state


def _apply_state(trainer, state) -> int:
    trainer.params = state["params"]
    if "opt_state" in state:
        trainer._opt_state = state["opt_state"]
    if "du_opt_state" in state:
        trainer._du_opt_state = state["du_opt_state"]
    return int(state["step"])


def _trainer_world(trainer) -> Optional[int]:
    mesh = getattr(trainer, "mesh", None)
    return int(mesh.devices.size) if mesh is not None else None


def save_trainer(mgr: CheckpointManager, trainer, step: int, wait: bool = False,
                 fingerprint: Optional[str] = None) -> None:
    """Persist a DataParallelTrainer/HybridTrainer's parameters (and optimizer
    state, when the trainer carries one). ``fingerprint`` marks the step
    sentinel-verified (see CheckpointManager.save); the active world size
    rides in the manifest so a cross-world restore names its mismatch."""
    mgr.save(step, _trainer_state(trainer, step), wait=wait,
             fingerprint=fingerprint, world=_trainer_world(trainer))


def restore_trainer(mgr: CheckpointManager, trainer, step: Optional[int] = None) -> Optional[int]:
    """Restore parameters (and optimizer state) in place; returns the restored
    step or None when the directory holds no checkpoints.

    With ``step=None`` the candidate order is newest VERIFIED first:
    steps whose manifest records a passing sentinel audit fingerprint
    (newest to oldest), then unverified steps (newest to oldest) — a
    checkpoint that might hold silently corrupted state is only used when
    no verified one restores. Within that order, a step that fails checksum
    verification, or whose restore raises, is skipped with a warning and
    the next candidate is tried — a corrupt latest checkpoint costs a
    longer replay, not the run. If checkpoints exist but none restores,
    raise (silently restarting from scratch would discard the entire run's
    progress)."""
    template = _trainer_state(trainer, 0)
    if step is not None:
        state = mgr.restore(step, template=template)
        return None if state is None else _apply_state(trainer, state)
    steps = mgr.all_steps()
    if not steps:
        return None
    mgr._flush_manifests()  # checksum anything committed-but-unverified
    newest_first = sorted(steps, reverse=True)
    verified = [s for s in newest_first if mgr.recorded_fingerprint(s)]
    unverified = [s for s in newest_first if s not in verified]
    if verified and unverified and unverified[0] > verified[0]:
        log_warning(
            "preferring newest VERIFIED checkpoint step %d over newer "
            "unverified step %d (no passing audit fingerprint recorded)",
            verified[0], unverified[0],
        )
    world_now = _trainer_world(trainer)
    for s in verified + unverified:
        verdict = mgr.verify(s)
        if verdict is False:
            log_warning(
                "checkpoint step %d fails checksum verification; falling back", s
            )
            continue
        w = mgr.recorded_world(s)
        if w is not None and world_now is not None and w != world_now:
            # elastic mesh: the step was saved at a different world size.
            # Replicated-only state restores anyway (and a same-shape ZeRO-1
            # layout would too), so still TRY — but name the mismatch first,
            # because the opaque alternative is an orbax shape error
            log_warning(
                "checkpoint step %d was saved at world size %d but the "
                "active world is %d (elastic reshard between save and "
                "restore); ZeRO-1 shard shapes may not restore", s, w,
                world_now,
            )
        try:
            state = mgr.restore(s, template=template)
        except Exception as e:
            log_warning(
                "restore of checkpoint step %d failed (%s: %s); falling back",
                s, type(e).__name__, e,
            )
            continue
        if state is None:
            continue
        if s != newest_first[0]:
            log_info("restored fallback step %d (latest step %d unusable)",
                     s, newest_first[0])
        return _apply_state(trainer, state)
    raise MLSLError(
        f"no restorable checkpoint in {mgr.directory}: all {len(steps)} steps "
        "are corrupt or unreadable"
    )
