"""Shared preamble for on-chip benchmark scripts.

`setup_chip(tag)` fail-fast-probes the accelerator tunnel in a subprocess (a
dead tunnel hangs uninterruptibly inside backend init — bench.py:30-35), then
applies the platform override and the persistent compilation cache, and returns
the imported jax module.
"""

import os
import subprocess
import sys
import time

REPO_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, REPO_ROOT)


# Ends with float(...) — a true d2h readback — because block_until_ready can
# acknowledge at dispatch through the axon tunnel (memory: axon-tunnel-timing),
# which would let a half-dead tunnel probe ALIVE. The single source of truth
# for every benchmark probe (bench.py, capture.py, kernels_on_chip.py).
PROBE_SRC = (
    "from mlsl_tpu.sysinfo import apply_platform_override\n"
    "apply_platform_override()\n"
    "import jax\n"
    "import jax.numpy as jnp\n"
    "v = float(jnp.ones((8, 8)).sum())\n"
    "assert v == 64.0, v\n"
    "print('KIND=' + jax.devices()[0].device_kind, flush=True)"
)


def probe_device_kind(timeout: float = 90.0):
    """Run PROBE_SRC in a subprocess -> (device_kind or None, error_tail).
    The ONE copy of the probe-subprocess dance (capture.py, bench.py,
    probe_accelerator, and the on-chip benches all use it)."""
    child = subprocess.Popen(
        [sys.executable, "-c", PROBE_SRC], stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, start_new_session=True,
        cwd=REPO_ROOT,
    )
    try:
        # communicate() drains pipes while waiting (a chatty runtime must not
        # wedge an alive probe into a false timeout)
        out, err = child.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        child.kill()  # best effort; a D-state child never reaps, so don't wait()
        return None, "probe timed out"
    if child.returncode != 0:
        return None, err[-500:]
    for line in out.splitlines():
        if line.startswith("KIND="):
            return line[5:], ""
    return None, "probe printed no KIND"


def probe_accelerator(tag: str, timeout: float = 180.0) -> None:
    kind, err = probe_device_kind(timeout)
    if kind is None:
        print(f"{tag}: accelerator unreachable:\n{err}", file=sys.stderr)
        sys.exit(3)


def setup_chip(tag: str):
    probe_accelerator(tag)
    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()
    import jax

    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(REPO_ROOT, ".jax_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    return jax


def device_sync(tree):
    """Force TRUE device completion of a result tree via a d2h readback of ONE
    element — through the axon tunnel block_until_ready can return before the
    device finishes (memory: axon-tunnel-timing). The element is sliced
    device-side first so only 4 bytes cross the tunnel (np.asarray of a full
    leaf would ship the whole array inside the timed window)."""
    import numpy as np
    import jax

    leaves = jax.tree.leaves(tree)
    if not leaves:
        # degenerate result (e.g. wait on a 1-member group returns None): no
        # output to read back, so this is only a host round trip — it does NOT
        # order against in-flight device work; callers timing real work must
        # sync on a tree that depends on it
        leaves = [jax.numpy.zeros((1,))]
    return float(np.asarray(jax.numpy.ravel(leaves[0])[0]))


MEASURED_PATH = os.path.join(REPO_ROOT, "BENCH_MEASURED.json")


def measured_path() -> str:
    """BENCH_MEASURED.json location; MLSL_BENCH_MEASURED_PATH overrides (tests
    redirect to a tmp file so exercising the capture pipeline end-to-end never
    pollutes the repo-root evidence file)."""
    return os.environ.get("MLSL_BENCH_MEASURED_PATH", MEASURED_PATH)


def model_flops(cfg, batch):
    """Analytic model FLOPs per train step (fwd + bwd = 3x fwd, the standard
    MFU denominator): per token per block 8*d*ad qkvo (ad = n_heads*head_dim,
    which the config does NOT require to equal d_model) + 4*mlp_ratio*d^2 MLP
    matmul FLOPs + 2*S*ad causal attention (4*S*ad full halved by the mask),
    plus the 2*d*V head. Unlike the executed-program cost model this does NOT
    count remat recompute, so remat variants' mfu_model is comparable: a
    faster wall clock is a higher mfu_model, full stop. Returns None for MoE
    configs (active FLOPs depend on routing/capacity; the executed-program
    row is the honest one there)."""
    if cfg.n_experts > 0:
        return None
    t = batch * cfg.seq_len
    d = cfg.d_model
    ad = cfg.n_heads * cfg.head_dim
    per_tok_blk = (8 * d * ad + 4 * cfg.mlp_ratio * d * d
                   + 2 * cfg.seq_len * ad)
    fwd = t * (cfg.n_blocks * per_tok_blk + 2 * d * cfg.vocab)
    return 3.0 * fwd


def git_sha() -> str:
    """Short HEAD sha, suffixed '-dirty' when the tree has uncommitted
    changes — a record claiming a clean sha while measuring workspace code
    misattributes evidence (it happened; see the r5 dots-config record)."""
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=REPO_ROOT,
            capture_output=True, text=True,
        ).stdout.strip() or "unknown"
        porcelain = subprocess.run(
            ["git", "status", "--porcelain"], cwd=REPO_ROOT,
            capture_output=True, text=True,
        ).stdout.strip()
        # the evidence file itself is always mid-append during a capture;
        # compare exact repo-relative paths, not a suffix (a stray
        # OLD_BENCH_MEASURED.json must still mark the tree dirty)
        evidence = os.path.relpath(measured_path(), REPO_ROOT)
        entries = [ln for ln in porcelain.splitlines()
                   if ln[3:].strip() != evidence]
        return sha + ("-dirty" if entries else "")
    except Exception:
        return "unknown"


def append_measurement(record: dict) -> None:
    """Append (or replace, keyed by run_id) one capture record in
    BENCH_MEASURED.json — the durable on-chip evidence file. Shared by bench.py
    and benchmarks/capture.py so the schema has exactly one writer."""
    import json

    path = measured_path()
    data = {"captures": []}
    if os.path.exists(path):
        try:
            with open(path) as f:
                data = json.load(f)
        except Exception:  # mlsl-lint: disable=A205 -- corrupt file = fresh doc
            pass
    caps = data.setdefault("captures", [])
    caps[:] = [c for c in caps if c.get("run_id") != record.get("run_id")]
    caps.append(record)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)


def timed_scan(step, carry0, iters=100, blocks=5):
    """Per-iteration ms for a carry→carry `step`, executed as a lax.scan
    inside ONE device computation, using a PAIRED-length estimate: best time
    at 2*iters minus best time at iters, divided by iters. For sub-ms kernels
    the per-dispatch `timed` path is unusable through the tunnel: subtracting
    a CALIBRATED ~65 ms RTT from a few-ms signal lets multi-ms RTT drift
    swing the result 0.7x-13x run-to-run (observed on flash_fwd_causal), and
    a cached calibration can even exceed a later block's total time. The
    paired difference cancels the RTT and dispatch cost exactly — no
    calibration to drift. Blocks alternate short/long so slow drift hits
    both arms equally. The carry dependency serializes iterations and
    defeats CSE; callers must make `step` keep its values bounded."""
    import time

    import jax
    from jax import lax

    def make(n):
        return jax.jit(
            lambda c: lax.scan(lambda c, _: (step(c), None), c, None, length=n)[0]
        )

    run1, run2 = make(iters), make(2 * iters)
    device_sync(run1(carry0))  # compile + warm
    device_sync(run2(carry0))
    best1 = best2 = float("inf")
    for _ in range(blocks):
        t0 = time.perf_counter()
        device_sync(run1(carry0))
        best1 = min(best1, time.perf_counter() - t0)
        t0 = time.perf_counter()
        device_sync(run2(carry0))
        best2 = min(best2, time.perf_counter() - t0)
    return max((best2 - best1) / iters * 1e3, 1e-3)


def timed(fn, *args, iters=30, warmup=5, blocks=3):
    """Best-of-blocks per-call ms with a TRUE device sync (through the axon
    tunnel block_until_ready can return before the device finishes — memory:
    axon-tunnel-timing), using a PAIRED-block estimate: each round times a
    block of K calls and a block of 2K calls; the reported value is
    (best_2K - best_K) / K, which cancels the sync's ~65 ms tunnel RTT and
    the dispatch cost exactly instead of subtracting a cached calibration
    that the tunnel's multi-ms RTT drift can invalidate (a drifted
    calibration produced negative signals on sub-ms kernels). Minima across
    rounds are taken per arm — shared-tunnel load spikes inflate the mean by
    2x+ on a seconds timescale, and a spike hits one arm of one round, not
    the independent minima."""
    import time

    r = fn(*args)  # also covers warmup=0: r must exist for the first sync
    for _ in range(max(0, warmup - 1)):
        r = fn(*args)
    device_sync(r)
    # each round runs K + 2K calls; keep the TOTAL near the caller's iters
    # budget so existing call sites don't silently triple their wall time —
    # but never below 2 calls per arm, where the paired difference would ride
    # on a single dispatch's RTT jitter
    per_block = max(2, iters // (3 * blocks))
    best1 = best2 = float("inf")
    for _ in range(blocks):
        t0 = time.perf_counter()
        for _ in range(per_block):
            r = fn(*args)
        device_sync(r)
        best1 = min(best1, time.perf_counter() - t0)
        t0 = time.perf_counter()
        for _ in range(2 * per_block):
            r = fn(*args)
        device_sync(r)
        best2 = min(best2, time.perf_counter() - t0)
    # floor at 1 µs: callers derive rates by dividing by this
    return max((best2 - best1) / per_block * 1e3, 1e-3)
