"""AllReduce algorithmic-bandwidth curve: the isolation-benchmark harness.

Produces the algbw-vs-message-size table that is the BASELINE metric (SURVEY.md §6:
"allreduce algbw (GB/s) vs msg size"), using the Statistics isolation methodology
(10 iterations, 4 warm-up skipped — reference src/mlsl_impl_stats.cpp:48-49).

algbw for an allreduce of S bytes over n ranks uses the standard convention
busbw = algbw * 2(n-1)/n. On a single real chip the group is degenerate (the curve
then measures framework dispatch floor); on a v5p slice this is the ≥90%-of-ICI-peak
north-star measurement. Run with MLSL_TPU_PLATFORM=cpu and
XLA_FLAGS=--xla_force_host_platform_device_count=8 for the virtual-mesh curve.

Output: one row per size, plus a JSON summary line.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-kb", type=int, default=4)
    ap.add_argument("--max-mb", type=int, default=64)
    ap.add_argument("--quant", action="store_true", help="also run int8 ring")
    args = ap.parse_args()

    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    import numpy as np

    import mlsl_tpu as mlsl
    from mlsl_tpu.comm.request import CommDesc, CommRequest
    from mlsl_tpu.core.stats import isolation_time_request
    from mlsl_tpu.types import CompressionType, DataType, ReductionType

    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()
    dist = env.create_distribution(world, 1)
    n_ranks = dist.get_process_count_data()
    bus_factor = 2 * (n_ranks - 1) / n_ranks if n_ranks > 1 else 1.0

    sizes = []
    s = args.min_kb * 1024
    while s <= args.max_mb * 1024 * 1024:
        sizes.append(s)
        s *= 4

    modes = [("fp32", CompressionType.NONE)]
    if args.quant:
        modes.append(("int8", CompressionType.QUANTIZATION))

    print(f"{'bytes':>12} {'mode':>6} {'us/iter':>10} {'algbw GB/s':>11} {'busbw GB/s':>11}")
    best = 0.0
    for nbytes in sizes:
        count = nbytes // 4
        for name, comp in modes:
            req = CommRequest(
                CommDesc(
                    "allreduce", dist.data_group, count, DataType.FLOAT,
                    op=ReductionType.SUM, compression=comp,
                ),
                env.dispatcher,
            )
            req.setup()
            ns, _ = isolation_time_request(req)
            algbw = nbytes / max(ns, 1)  # bytes/ns == GB/s
            # the headline busbw uses uncompressed fp32 only: int8's algbw is
            # computed from the uncompressed payload, so folding it in would
            # overstate the physical bus bandwidth ~4x
            if comp == CompressionType.NONE:
                best = max(best, algbw * bus_factor)
            print(
                f"{nbytes:>12} {name:>6} {ns / 1e3:>10.1f} {algbw:>11.2f} "
                f"{algbw * bus_factor:>11.2f}"
            )
    print(json.dumps({
        "metric": "allreduce_busbw_peak",
        "value": round(best, 3),
        "unit": "GB/s",
        "ranks": n_ranks,
    }))


if __name__ == "__main__":
    main()
