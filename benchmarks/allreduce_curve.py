"""AllReduce algorithmic-bandwidth curve: the isolation-benchmark harness.

Produces the algbw-vs-message-size table that is the BASELINE metric (SURVEY.md §6:
"allreduce algbw (GB/s) vs msg size"), using the Statistics isolation methodology
(10 iterations, 4 warm-up skipped — reference src/mlsl_impl_stats.cpp:48-49).

algbw for an allreduce of S bytes over n ranks uses the standard convention
busbw = algbw * 2(n-1)/n. On a single real chip the group is degenerate (the curve
then measures framework dispatch floor); on a v5p slice this is the ≥90%-of-ICI-peak
north-star measurement. Run with MLSL_TPU_PLATFORM=cpu and
XLA_FLAGS=--xla_force_host_platform_device_count=8 for the virtual-mesh curve.

Output: one row per size, plus a JSON summary line.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def measure_dispatch_floor(env, dist):
    """Host-side cost of driving one already-compiled request, in µs.

    Three numbers (the knob VERDICT r4 item 3 demands be tracked so host
    dispatch can never silently eat the overlap budget):
      - start_us:      async Start() enqueue alone (the per-layer hot path —
                       the reference's analog is queuing one cached CommRequest
                       on the eplib command queue, eplib/cqueue.c:1906-2026)
      - start_wait_us: full Start()+Wait() round trip on a tiny payload — the
                       smallest achievable per-request latency
      - test_us:       one non-blocking Test() poll on a completed request
    """
    import time

    import numpy as np

    from mlsl_tpu.comm.request import CommDesc, CommRequest
    from mlsl_tpu.types import DataType, ReductionType

    count = 256  # tiny payload: device time ~0, what remains is host dispatch
    req = CommRequest(
        CommDesc("allreduce", dist.data_group, count, DataType.FLOAT,
                 op=ReductionType.SUM),
        env.dispatcher,
    )
    req.setup()
    buf = dist.make_buffer(lambda p: np.zeros(count, dtype=np.float64), count)
    import jax

    bare = req._fns[0]  # the raw compiled XLA program behind the request
    for _ in range(10):  # warm: compile + caches
        req.start(buf)
        req.wait()
    iters, blocks = 150, 3
    # All loops keep in-flight depth at 1 (a free-running start loop starves
    # the CPU backend's in-process collective rendezvous). Best-of-blocks:
    # this box/tunnel is shared, so the minimum is the capability estimate.
    start_us = start_wait_us = launch_us = float("inf")
    for _ in range(blocks):
        t_start = 0
        t0 = time.perf_counter_ns()
        for _ in range(iters):
            a = time.perf_counter_ns()
            req.start(buf)
            t_start += time.perf_counter_ns() - a
            req.wait()
        start_wait_us = min(
            start_wait_us, (time.perf_counter_ns() - t0) / iters / 1e3
        )
        start_us = min(start_us, t_start / iters / 1e3)
        t_call = 0
        for _ in range(iters):
            a = time.perf_counter_ns()
            out = bare(buf)
            t_call += time.perf_counter_ns() - a
            jax.block_until_ready(out)
        launch_us = min(launch_us, t_call / iters / 1e3)
    t0 = time.perf_counter_ns()
    for _ in range(iters):
        req.test()
    test_us = (time.perf_counter_ns() - t0) / iters / 1e3
    return {
        "metric": "dispatch_floor",
        "start_us": round(start_us, 2),
        "launch_us": round(launch_us, 2),       # bare XLA async dispatch
        "overhead_us": round(start_us - launch_us, 2),  # the framework's slice
        "start_wait_us": round(start_wait_us, 2),
        "test_us": round(test_us, 2),
        "unit": "us",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-kb", type=int, default=4)
    ap.add_argument("--max-mb", type=int, default=64)
    ap.add_argument("--quant", action="store_true", help="also run int8 ring")
    args = ap.parse_args()

    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    import numpy as np

    import mlsl_tpu as mlsl
    from mlsl_tpu.comm.request import CommDesc, CommRequest
    from mlsl_tpu.core.stats import isolation_time_request
    from mlsl_tpu.types import CompressionType, DataType, ReductionType

    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()
    dist = env.create_distribution(world, 1)
    n_ranks = dist.get_process_count_data()
    bus_factor = 2 * (n_ranks - 1) / n_ranks if n_ranks > 1 else 1.0

    sizes = []
    s = args.min_kb * 1024
    while s <= args.max_mb * 1024 * 1024:
        sizes.append(s)
        s *= 4

    modes = [("fp32", CompressionType.NONE)]
    if args.quant:
        modes.append(("int8", CompressionType.QUANTIZATION))

    print(f"{'bytes':>12} {'mode':>6} {'us/iter':>10} {'algbw GB/s':>11} {'busbw GB/s':>11}")
    best = 0.0
    for nbytes in sizes:
        count = nbytes // 4
        for name, comp in modes:
            req = CommRequest(
                CommDesc(
                    "allreduce", dist.data_group, count, DataType.FLOAT,
                    op=ReductionType.SUM, compression=comp,
                ),
                env.dispatcher,
            )
            req.setup()
            ns, _ = isolation_time_request(req)
            algbw = nbytes / max(ns, 1)  # bytes/ns == GB/s
            # the headline busbw uses uncompressed fp32 only: int8's algbw is
            # computed from the uncompressed payload, so folding it in would
            # overstate the physical bus bandwidth ~4x
            if comp == CompressionType.NONE:
                best = max(best, algbw * bus_factor)
            print(
                f"{nbytes:>12} {name:>6} {ns / 1e3:>10.1f} {algbw:>11.2f} "
                f"{algbw * bus_factor:>11.2f}"
            )
    floor = measure_dispatch_floor(env, dist)
    print(json.dumps(floor))
    print(json.dumps({
        "metric": "allreduce_busbw_peak",
        "value": round(best, 3),
        "unit": "GB/s",
        "ranks": n_ranks,
        "dispatch_floor_start_us": floor["start_us"],
    }))


if __name__ == "__main__":
    main()
