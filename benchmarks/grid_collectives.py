"""BASELINE config 2: the Distribution(data x model) grid collective set.

Times AllReduce + AllGather + Bcast + ReduceScatter over both the data and
model groups of a hybrid grid (the reference's four grid collectives,
BASELINE.json configs[1]) with the isolation methodology (best-of-blocks,
d2h-synced). On one real chip the groups degenerate to the dispatch floor;
on a mesh (virtual CPU or a real slice) the rows are group-wise algbw.

Usage: MLSL_TPU_PLATFORM=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python benchmarks/grid_collectives.py
Prints one JSON line per (collective, group).
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    import numpy as np

    import mlsl_tpu as mlsl
    from mlsl_tpu.types import DataType, GroupType, ReductionType

    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()
    model = 2 if world % 2 == 0 and world > 1 else 1
    dist = env.create_distribution(max(world // model, 1), model)
    nbytes = 4 * 1024 * 1024  # 4 MiB fp32 per rank
    count = nbytes // 4
    buf = dist.make_buffer(
        lambda p: p * 1.0 + np.arange(count, dtype=np.float64) % 977, count
    )

    from benchmarks._common import timed  # paired-block estimate, 4-byte d2h sync
    from mlsl_tpu.comm.request import CommDesc, CommRequest

    def run(kind, gt):
        gsize = {GroupType.DATA: dist.get_process_count_data(),
                 GroupType.MODEL: dist.get_process_count_model()}[gt]
        group = dist._group(gt)
        # one prebuilt, reused request per row — the same steady-state the
        # committed dispatch_floor metric measures (allreduce_curve.py), so
        # degenerate-group rows stay comparable to it
        kw = {}
        if kind in ("allreduce", "reduce_scatter"):
            kw["op"] = ReductionType.SUM
        if kind == "bcast":
            kw["root"] = 0
        if kind == "reduce_scatter":
            kw["recv_count"] = max(count // max(gsize, 1), 1)
        req = CommRequest(
            CommDesc(kind, group, count, DataType.FLOAT, **kw), env.dispatcher
        )
        req.setup()

        def one():
            req.start(buf)
            return req.wait()

        ms = timed(one, iters=9, warmup=2, blocks=3)
        row = {"metric": f"grid_{kind}", "group": gt.name.lower(),
               "group_size": gsize, "us_per_op": round(ms * 1e3, 1),
               "bytes": nbytes}
        if gsize > 1:
            row["algbw_gbs"] = round(nbytes / (ms / 1e3) / 1e9, 3)
        else:
            # one-member group: the request is the identity program — the row
            # is the per-collective dispatch floor, not bandwidth
            row["note"] = "degenerate group: dispatch floor"
        return row

    for kind in ("allreduce", "allgather", "bcast", "reduce_scatter"):
        for gt in (GroupType.DATA, GroupType.MODEL):
            print(json.dumps(run(kind, gt)))


if __name__ == "__main__":
    main()
