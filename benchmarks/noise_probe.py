"""Probe: tunnel/device noise profile over many blocks.

Runs 30 blocks of 6 SGD steps and prints each block mean with a timestamp, to
see whether the slow mode is bursty (median ok) or persistent (min-of-blocks is
the only stable capability estimator).

Measured (v5e, batch 32): block means swing 18-25 ms on a seconds timescale
with no trend — bursty shared-tunnel load. bench.py therefore reports the
median of many 6-iter blocks plus a best_ms capability estimate.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from benchmarks._common import device_sync, setup_chip

jax = setup_chip("noise_probe")

import jax.numpy as jnp

from mlsl_tpu.models import resnet


def main():
    lr = 0.05
    params = jax.device_put(resnet.init_resnet50(jax.random.PRNGKey(0), 1000))
    rng = np.random.default_rng(0)
    x = jax.device_put(jnp.asarray(rng.normal(size=(32, 224, 224, 3)), jnp.float32))
    y = jax.device_put(jnp.asarray(rng.integers(0, 1000, size=(32,)), jnp.int32))

    @jax.jit
    def sgd(p, b):
        loss, g = jax.value_and_grad(resnet.loss_fn)(p, b)
        return loss, jax.tree.map(lambda w, gg: w - lr * gg, p, g)

    p = params
    for _ in range(4):
        _, p = sgd(p, (x, y))
    device_sync(p)

    t_start = time.perf_counter()
    means = []
    for i in range(30):
        t0 = time.perf_counter()
        for _ in range(6):
            _, p = sgd(p, (x, y))
        device_sync(p)
        ms = (time.perf_counter() - t0) / 6 * 1e3
        means.append(ms)
        print(f"t={time.perf_counter()-t_start:6.1f}s  block {i:2d}: {ms:6.2f} ms")
    means = np.array(means)
    print(
        f"min {means.min():.2f}  p25 {np.percentile(means,25):.2f}  "
        f"median {np.median(means):.2f}  p75 {np.percentile(means,75):.2f}  "
        f"max {means.max():.2f}"
    )


if __name__ == "__main__":
    main()
