"""Probe: does alternating between compiled programs slow each one down?

bench.py times fw/raw/per-layer in alternating blocks; standalone runs of the
same step once measured ~2x faster. This isolates whether program switching
itself costs milliseconds (HBM re-paging of weights between resident programs).

Measured (v5e, batch 32): solo blocks are just as bimodal (~19-30 ms) as
alternating ones — the variance is shared-tunnel load, not program switching.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from benchmarks._common import device_sync, setup_chip

jax = setup_chip("alternation_probe")

import jax.numpy as jnp

from mlsl_tpu.models import resnet


def main():
    lr = 0.05
    params = jax.device_put(resnet.init_resnet50(jax.random.PRNGKey(0), 1000))
    params2 = jax.tree.map(jnp.copy, params)
    rng = np.random.default_rng(0)
    batch = 32
    x = jax.device_put(jnp.asarray(rng.normal(size=(batch, 224, 224, 3)), jnp.float32))
    y = jax.device_put(jnp.asarray(rng.integers(0, 1000, size=(batch,)), jnp.int32))

    @jax.jit
    def sgd(p, b):
        loss, g = jax.value_and_grad(resnet.loss_fn)(p, b)
        return loss, jax.tree.map(lambda w, gg: w - lr * gg, p, g)

    # a second, distinct executable over its own param copy (like bench's 3 sides)
    @jax.jit
    def sgd_b(p, b):
        loss, g = jax.value_and_grad(resnet.loss_fn)(p, b)
        return loss, jax.tree.map(lambda w, gg: w - lr * gg * 0.999, p, g)

    def block(fn, p, iters):
        t0 = time.perf_counter()
        for _ in range(iters):
            _, p = fn(p, (x, y))
        device_sync(p)
        return (time.perf_counter() - t0) / iters * 1e3, p

    for _ in range(4):
        _, params = sgd(params, (x, y))
        _, params2 = sgd_b(params2, (x, y))
    device_sync((params, params2))

    solo = []
    for _ in range(9):
        ms, params = block(sgd, params, 4)
        solo.append(ms)
    print("solo      blocks:", " ".join(f"{m:6.2f}" for m in solo))

    alt_a, alt_b = [], []
    for _ in range(9):
        ms, params = block(sgd, params, 4)
        alt_a.append(ms)
        ms, params2 = block(sgd_b, params2, 4)
        alt_b.append(ms)
    print("alternate A blocks:", " ".join(f"{m:6.2f}" for m in alt_a))
    print("alternate B blocks:", " ".join(f"{m:6.2f}" for m in alt_b))


if __name__ == "__main__":
    main()
