"""Integrity-sentinel overhead bench: the <2% contract at the default interval.

Measures the three costs the sentinel adds to a training loop on the 8-dev
CPU mesh, against the per-layer trainer's measured step floor:

- ``gate_ms``   — one quality-gate screen (the fused nonfinite/norm program +
  the single host sync for the verdict), paid EVERY step when
  ``MLSL_SENTINEL_GATE`` is armed;
- ``audit_ms``  — one cross-replica consistency audit (blockwise fingerprint
  + on-device pmin/pmax + the digest readback), paid every
  ``MLSL_SENTINEL_EVERY`` steps;
- the comparative armed-vs-off step delta (reported, but the CPU mesh
  carries +-15% run-to-run noise — the accounted model is the contract,
  same reasoning as trace_overhead_bench.py).

The acceptance row (ISSUE 9): ``overhead_frac_default`` =
``(gate_ms + audit_ms / DEFAULT_INTERVAL) / step_ms`` < 0.02 at the default
interval. The full run also prints the overhead-vs-interval curve so TUNING
§13's sizing guidance is measured, not guessed.

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
       python benchmarks/sentinel_overhead_bench.py [--smoke]
Prints one JSON row (capture-row shape, metric=sentinel_overhead).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

#: the interval TUNING §13 recommends as the starting point: audits amortize
#: to noise while a silent corruption is still caught within ~1 minute of
#: steps on a real pod
DEFAULT_INTERVAL = 50


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast tier-1 mode: fewer iters")
    args = ap.parse_args()

    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    import numpy as np
    import jax
    import jax.numpy as jnp

    import mlsl_tpu as mlsl
    from mlsl_tpu import sentinel
    from mlsl_tpu.models.train import DataParallelTrainer

    warmup, iters = (3, 8) if args.smoke else (5, 20)
    intervals = (1, 10, DEFAULT_INTERVAL) if args.smoke else (
        1, 5, 10, DEFAULT_INTERVAL, 200
    )

    # A REPRESENTATIVE compute:params ratio is what makes this row honest:
    # the gate's cost scales with the gradient footprint, the step's with
    # batch x FLOPs — a toy batch would overstate the gate fraction by an
    # order of magnitude vs any real workload (ResNet-50 does ~100x more
    # compute per parameter than even this config; 256 examples per replica
    # is an ordinary data-parallel shard). The distortion to beware on the
    # CPU proof mesh: memory-bound elementwise work (the gate's scan) runs
    # ~100x closer to the matmul rate than on a real TPU, so the measured
    # fraction here is an UPPER bound on hardware.
    K, D, B = 6, 512, 8192
    layers = [f"l{i}" for i in range(K)]

    def init_params(key):
        ks = jax.random.split(key, K)
        return {
            f"l{i}": {
                "w": jax.random.normal(k, (D, D)) * 0.05,
                "b": jnp.zeros((D,)),
            }
            for i, k in enumerate(ks)
        }

    def loss_fn(params, batch):
        x, y = batch
        h = x
        for i in range(K):
            h = jnp.tanh(h @ params[f"l{i}"]["w"] + params[f"l{i}"]["b"])
        return jnp.mean((h[:, 0] - y) ** 2)

    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()
    dist = env.create_distribution(world, 1)
    sess = env.create_session()
    sess.set_global_minibatch_size(B)
    trainer = DataParallelTrainer(
        env, dist, sess, init_params(jax.random.PRNGKey(0)), loss_fn,
        layers, lambda p, n: p[n], lr=0.05,
    )
    rng = np.random.default_rng(0)
    x = rng.normal(size=(B, D)).astype(np.float32)
    y = rng.normal(size=(B,)).astype(np.float32)
    batch = trainer.shard_batch(x, y)

    def timed(fn, n, blocks=3):
        # best-of-blocks: the min is each path's noise-free floor (load
        # spikes only ever ADD time on this shared box — the same reasoning
        # as trace_overhead_bench.py)
        best = float("inf")
        per = max(1, n // blocks)
        for _ in range(blocks):
            t0 = time.perf_counter()
            for _ in range(per):
                fn()
            best = min(best, (time.perf_counter() - t0) / per * 1e3)
        return best

    # -- the step floor (sentinel off) ------------------------------------
    for _ in range(warmup):
        jax.block_until_ready(trainer.step(batch))
    step_ms = timed(lambda: jax.block_until_ready(trainer.step(batch)), iters)

    # -- isolated gate + audit cost (the accounted model) ------------------
    s = sentinel.Sentinel(trainer.mesh, gate="warn",
                          every=DEFAULT_INTERVAL)
    loss, grads = trainer._grad_fn(trainer.params, batch)
    jax.block_until_ready(loss)
    for _ in range(warmup):
        s.gate(loss, grads, trainer.params, step=0)
    gate_ms = timed(lambda: s.gate(loss, grads, trainer.params, step=0),
                    iters)
    for _ in range(warmup):
        s.audit_now(trainer, step=0)
    audit_ms = timed(lambda: s.audit_now(trainer, step=0), iters)

    # -- comparative armed-vs-off delta (noisy; reported, not the contract)
    trainer.sentinel = sentinel.Sentinel(trainer.mesh, gate="warn",
                                         every=DEFAULT_INTERVAL)
    for _ in range(warmup):
        jax.block_until_ready(trainer.step(batch))
    armed_ms = timed(lambda: jax.block_until_ready(trainer.step(batch)),
                     iters)
    trainer.sentinel = None

    curve = {
        str(k): round((gate_ms + audit_ms / k) / step_ms, 4)
        for k in intervals
    }
    row = {
        "metric": "sentinel_overhead",
        "devices": world,
        "iters": iters,
        "step_ms": round(step_ms, 3),
        "gate_ms": round(gate_ms, 3),
        "audit_ms": round(audit_ms, 3),
        "interval_default": DEFAULT_INTERVAL,
        "overhead_frac_default": curve[str(DEFAULT_INTERVAL)],
        "overhead_frac_by_interval": curve,
        "armed_step_ms": round(armed_ms, 3),
        "delta_frac": round((armed_ms - step_ms) / step_ms, 4),
        "smoke": bool(args.smoke),
    }
    print(json.dumps(row))
    env.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
