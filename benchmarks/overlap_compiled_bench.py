"""Compiled-overlap benchmark: in-graph vs host per-layer gradient schedule.

The acceptance measurement for the compiled overlap engine (comm/overlap.py):
a ResNet-50-shaped per-layer gradient stream — one registered layer per
conv+BN group plus the fc head, ~54 layers with real ResNet-50 parameter
counts — trained through the SAME DataParallelTrainer twice:

- **host per-layer** (``force_graph_path=True``): the Session/Operation
  Start/Wait engine, one XLA dispatch per layer collective plus the barrier
  update program — the schedule BENCH_r05 showed gains nothing on chip
  (``per_layer_vs_fused: 1.0``).
- **compiled** (``overlap_compiled=True``): ONE donation-enabled step
  program with every layer's collective emitted in-graph, newest-first,
  staged over ``--stages`` unit starts.

The model's compute is deliberately negligible (per-tensor elementwise
loss): the rows measure the dispatch/communication schedule itself, which is
what the engine replaces. A fused monolithic raw-JAX jit of the same math
provides the ``compiled_vs_fused`` context ratio bench.py tracks on chip.

Layer count stays ~54 (the real bench.py per-layer trainer's count): the CPU
proof backend deadlocks past a few dozen concurrent in-flight collectives
(the PR 2 hazard), and the host twin keeps all layers in flight per step.

Usage: MLSL_TPU_PLATFORM=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
       python benchmarks/overlap_compiled_bench.py [--smoke]
--smoke scales tensor sizes down (~1/16, same layer count — the per-layer
dispatch floor being beaten is per layer) and trims iters; the tier-1 wiring
(tests/test_overlap_compiled.py, ``bench_smoke``) runs this mode. Prints one
JSON row per configuration (the standard capture-row shape).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def resnet50_layer_counts(scale: int = 1):
    """Per-LAYER parameter counts of a ResNet-50 at conv+BN granularity:
    53 conv+BN groups + the fc head = 54 layers (the same granularity the
    real bench.py per-layer trainer registers). ``scale`` divides counts
    (smoke) without changing the LAYER count — the per-layer host dispatch
    floor is per layer."""
    counts = []

    def conv(cin, cout, k):
        counts.append(cin * cout * k * k + 2 * cout)  # conv + BN gamma/beta

    conv(3, 64, 7)
    cin = 64
    for blocks, mid in [(3, 64), (4, 128), (6, 256), (3, 512)]:
        for b in range(blocks):
            conv(cin, mid, 1)
            conv(mid, mid, 3)
            conv(mid, mid * 4, 1)
            if b == 0:
                conv(cin, mid * 4, 1)
            cin = mid * 4
    counts.append(2048 * 1000 + 1000)  # fc
    return [max(c // scale, 64) for c in counts]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast tier-1 mode: scaled-down tensors, fewer iters")
    ap.add_argument("--stages", type=int, default=None,
                    help="overlap staging depth (default: config)")
    args = ap.parse_args()

    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    import numpy as np
    import jax
    import jax.numpy as jnp

    import mlsl_tpu as mlsl
    from benchmarks._common import device_sync
    from mlsl_tpu.models.train import DataParallelTrainer
    from mlsl_tpu.types import CompressionType

    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()
    dist0 = env.create_distribution(world, 1)
    degenerate = (
        {"note": "degenerate group: schedule structure only"}
        if dist0.get_process_count_data() == 1 else {}
    )

    counts = resnet50_layer_counts(scale=16 if args.smoke else 1)
    layers = [f"l{i}" for i in range(len(counts))]
    rng = np.random.default_rng(0)
    params = {
        n: {"w": jnp.asarray(rng.normal(size=(c,)).astype(np.float32) * 0.1)}
        for n, c in zip(layers, counts)
    }

    def loss_fn(p, batch):
        x, _ = batch
        s = jnp.mean(x)
        tot = 0.0
        for n in layers:
            w = p[n]["w"]
            tot = tot + jnp.sum(w * s + 0.005 * w * w) / w.shape[0]
        return tot / len(layers)

    def get_layer(p, name):
        return p[name]

    batch = 32
    x = rng.normal(size=(batch, 16)).astype(np.float32)
    y = rng.integers(0, 4, size=(batch,)).astype(np.int32)

    def build(overlap, compression=CompressionType.NONE, stages=None):
        if stages is not None:
            env.config.overlap_stages = stages
        dist = env.create_distribution(world, 1)
        s = env.create_session()
        s.set_global_minibatch_size(batch)
        # force_graph_path on BOTH twins: the host twin must take the
        # Start/Wait engine, and on a single-device world the compiled twin
        # would otherwise lose to the fused no-comm shortcut and never build
        # the engine (the `degenerate` rows measure schedule structure)
        t = DataParallelTrainer(
            env, dist, s, params, loss_fn, layers, get_layer, lr=0.05,
            compression=compression, overlap_compiled=overlap,
            force_graph_path=True,
        )
        return t, t.shard_batch(x, y)

    warmup, blocks, per_block = (2, 3, 2) if args.smoke else (3, 5, 5)

    def timed(t, b):
        for _ in range(warmup):
            t.step(b)
        device_sync(t.params)
        best = float("inf")
        for _ in range(blocks):
            t0 = time.perf_counter()
            for _ in range(per_block):
                t.step(b)
            device_sync(t.params)
            best = min(best, (time.perf_counter() - t0) / per_block)
        return best * 1e3  # ms

    # fused monolithic raw-JAX reference (the compiled_vs_fused anchor):
    # batch sharded over the mesh, params replicated, XLA/GSPMD owns the
    # gradient collectives — bench.py's raw-baseline methodology
    from jax.sharding import NamedSharding, PartitionSpec as P

    lr = 0.05
    mesh = dist0.topology.mesh

    @jax.jit
    def fused_step(p, bx, by):
        loss, grads = jax.value_and_grad(loss_fn)(p, (bx, by))
        return loss, jax.tree.map(lambda w, g: w - lr * g, p, grads)

    raw_p = jax.device_put(params, NamedSharding(mesh, P()))
    data_spec = P(("replica", "data", "seq", "model"))
    bx = jax.device_put(jnp.asarray(x), NamedSharding(mesh, data_spec))
    by = jax.device_put(jnp.asarray(y), NamedSharding(mesh, data_spec))

    def timed_fused():
        nonlocal raw_p
        for _ in range(warmup):
            _, raw_p = fused_step(raw_p, bx, by)
        device_sync(raw_p)
        best = float("inf")
        for _ in range(blocks):
            t0 = time.perf_counter()
            for _ in range(per_block):
                _, raw_p = fused_step(raw_p, bx, by)
            device_sync(raw_p)
            best = min(best, (time.perf_counter() - t0) / per_block)
        return best * 1e3

    fused_ms = timed_fused()

    rows = [("plain", CompressionType.NONE)]
    if not args.smoke:
        rows.append(("quant", CompressionType.QUANTIZATION))
    for tag, comp in rows:
        th, bh = build(False, comp)
        host_ms = timed(th, bh)
        tc, bc = build(True, comp, stages=args.stages)
        assert tc._overlap is not None, "compiled overlap did not engage"
        compiled_ms = timed(tc, bc)
        print(json.dumps({
            "metric": "overlap_compiled_resnet50_stream",
            "compression": tag,
            "layers": len(layers),
            "params": sum(counts),
            "stages": tc._overlap.plan.stages,
            "units": len(tc._overlap.plan.units),
            "host_per_layer_ms": round(host_ms, 3),
            "compiled_ms": round(compiled_ms, 3),
            "speedup": round(host_ms / compiled_ms, 3),
            "fused_ms": round(fused_ms, 3),
            "compiled_vs_fused": round(fused_ms / compiled_ms, 4),
            "accept": host_ms / compiled_ms >= 1.1,
            "unit": "ms",
            **degenerate,
        }))

    if not args.smoke:
        # staging-depth curve: how the interleave window moves the number
        # (on sim meshes usually flat — the backend serializes collectives)
        for stages in (1, 2, 4):
            tc, bc = build(True, stages=stages)
            ms = timed(tc, bc)
            print(json.dumps({
                "metric": "overlap_compiled_stages",
                "stages": stages,
                "compiled_ms": round(ms, 3),
                "unit": "ms",
                **degenerate,
            }))


if __name__ == "__main__":
    main()
