"""Bisection experiments for the ResNet-50 step time (run on a real chip).

Times fwd-only vs fwd+bwd (value_and_grad), the SGD update, and donation, at
several batch sizes, plus bare dispatch latency — so tuning effort goes where
the milliseconds are.
"""

import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from benchmarks._common import device_sync, setup_chip, timed

jax = setup_chip("resnet_tuning")

import jax.numpy as jnp

from mlsl_tpu.models import resnet


def timeit(fn, *args, iters=20, warmup=4):
    return timed(fn, *args, iters=iters, warmup=warmup)


def main():
    dev = jax.devices()[0]
    print("device:", dev.device_kind)

    # bare dispatch latency: tiny no-op jit, timed the same way
    tiny = jax.jit(lambda x: x + 1)
    z = jax.device_put(jnp.zeros((8, 8)))
    print(f"tiny-op round trip: {timeit(tiny, z, iters=50):7.3f} ms")

    params = jax.device_put(resnet.init_resnet50(jax.random.PRNGKey(0), 1000))
    rng = np.random.default_rng(0)

    lr = 0.05
    fwd = jax.jit(resnet.apply_resnet50)
    vg = jax.jit(lambda p, b: jax.value_and_grad(resnet.loss_fn)(p, b))

    @jax.jit
    def sgd(p, b):
        loss, g = jax.value_and_grad(resnet.loss_fn)(p, b)
        return loss, jax.tree.map(lambda w, gg: w - lr * gg, p, g)

    @functools.partial(jax.jit, donate_argnums=0)
    def sgd_donate(p, b):
        loss, g = jax.value_and_grad(resnet.loss_fn)(p, b)
        return loss, jax.tree.map(lambda w, gg: w - lr * gg, p, g)

    def timeit_state(fn, p, b, iters=10, warmup=4):
        # threads params through (for donated variants)
        for _ in range(warmup):
            _, p = fn(p, b)
        device_sync(p)
        t0 = time.perf_counter()
        for _ in range(iters):
            _, p = fn(p, b)
        device_sync(p)
        return (time.perf_counter() - t0) / iters * 1e3

    for batch in (32, 64, 128):
        x = jax.device_put(
            jnp.asarray(rng.normal(size=(batch, 224, 224, 3)), jnp.float32)
        )
        y = jax.device_put(jnp.asarray(rng.integers(0, 1000, size=(batch,)), jnp.int32))
        f_ms = timeit(fwd, params, x, iters=10)
        v_ms = timeit(vg, params, (x, y), iters=10)
        s_ms = timeit_state(sgd, params, (x, y))
        d_ms = timeit_state(sgd_donate, jax.tree.map(jnp.copy, params), (x, y))
        print(
            f"batch {batch:4d}: fwd {f_ms:6.2f}  vg {v_ms:6.2f}  "
            f"sgd {s_ms:6.2f}  sgd+donate {d_ms:6.2f} ms "
            f"({batch/d_ms*1e3:6.0f} img/s)"
        )


if __name__ == "__main__":
    main()
