"""Tracer overhead microbench: the <5% contract on the enabled hot path.

A windowed stream of per-layer gradient allreduces through the ParameterSet
engine — the same backward-shaped schedule quant_bucket_bench.py uses — timed
with the obs tracer disabled vs enabled. Every Start/Wait crosses the
instrumented submit/dispatch/wait sites, so the measured delta IS the tracer's
hot-path cost (a tuple append into the ring per event). The acceptance
contract (ISSUE 3 / tests/test_trace.py bench_smoke wiring): enabled tracing
adds <5% to the stream; the disabled path is one attribute check per site and
is not separately measurable at stream timescales.

Interleaved off/on trial blocks (off,on,off,on,...) with best-of-N medians
keep shared-box load drift from polluting the comparison — drift hits both
arms equally.

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
       python benchmarks/trace_overhead_bench.py [--smoke]
Prints one JSON row (capture-row shape, metric=trace_overhead).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast tier-1 mode: fewer layers/iters")
    args = ap.parse_args()

    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    import numpy as np

    import mlsl_tpu as mlsl
    from mlsl_tpu import obs
    from benchmarks._common import device_sync
    from mlsl_tpu.types import OpType

    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()
    dist = env.create_distribution(world, 1)

    # stays under the CPU backend's in-flight collective limit (see
    # quant_bucket_bench.py); sizes are latency-bound so the per-request
    # host path — the instrumented part — dominates
    nl, count = (8, 2048) if args.smoke else (12, 4096)
    warmup, trials, iters = (6, 6, 6) if args.smoke else (10, 8, 8)
    window = 4

    sess = env.create_session()
    sess.set_global_minibatch_size(8)
    ops = []
    for i in range(nl):
        r = sess.create_operation_reg_info(OpType.CC)
        r.set_name(f"layer{i}")
        r.add_input(8, 4)
        r.add_output(8, 4)
        r.add_parameter_set(count, 1)
        ops.append(sess.get_operation(sess.add_operation(r, dist)))
    sess.commit()
    pss = [op.get_parameter_set(0) for op in ops]
    rng = np.random.default_rng(0)
    bufs = [
        dist.make_buffer(
            lambda p, v=rng.normal(size=count): v + p, count
        )
        for _ in range(nl)
    ]

    def step():
        outs = [None] * nl
        inflight = []
        for i in range(nl - 1, -1, -1):  # backward start order
            pss[i].start_gradient_comm(bufs[i])
            inflight.append(i)
            if len(inflight) > window:
                j = inflight.pop(0)
                outs[j] = pss[j].wait_gradient_comm()
        for j in inflight:
            outs[j] = pss[j].wait_gradient_comm()
        device_sync(outs[0] if outs[0] is not None else bufs[0])

    def timed_block():
        t0 = time.perf_counter()
        for _ in range(iters):
            step()
        return (time.perf_counter() - t0) / iters

    was_enabled = obs.enabled()
    for _ in range(warmup):
        step()
    off_ms, on_ms = [], []
    try:
        for t in range(trials):
            # interleaved AND order-alternating (off/on, on/off, ...): slow
            # drift and first-in-pair effects hit both arms equally
            arms = [(obs.disable, off_ms), (lambda: obs.enable(), on_ms)]
            if t % 2:
                arms.reverse()
            for switch, acc in arms:
                switch()
                acc.append(timed_block() * 1e3)
    finally:
        obs.disable()
        if was_enabled:
            obs.enable()

    # best-of per arm: the min is the noise-free floor of each path (load
    # spikes and GC pauses only ever ADD time); interleaving already gave
    # both arms the same thermal/cache history
    off = min(off_ms)
    on = min(on_ms)
    delta = (on - off) / off if off > 0 else 0.0

    # The acceptance metric is ACCOUNTED overhead: per-event record cost
    # (measured in a tight loop, deterministic to ~ns) x the events one
    # stream step records, over the stream's measured floor. The comparative
    # delta above is reported too, but the CPU-mesh collective times carry
    # +-15% run-to-run noise — an order of magnitude above the tracer's real
    # cost — which is exactly the flaky-comparative-assert failure mode this
    # subsystem exists to retire.
    tr = obs.enable()
    n_probe = 10000
    t0 = time.perf_counter()
    for i in range(n_probe):
        tr.complete("wait", "req", tr.now(), track="probe", req="probe", epoch=i)
    per_event_us = (time.perf_counter() - t0) / n_probe * 1e6
    obs.disable()
    if was_enabled:
        obs.enable()
    # events per step: submit instant + dispatch span + wait span per request
    events_per_step = nl * 3
    accounted = events_per_step * per_event_us / 1e3 / off if off > 0 else 0.0

    print(json.dumps({
        "metric": "trace_overhead",
        "layers": nl,
        "grad_kib": count * 4 // 1024,
        "window": window,
        "trials": trials,
        "off_ms": round(off, 3),
        "on_ms": round(on, 3),
        "delta_frac": round(delta, 4),          # comparative (noisy)
        "per_event_us": round(per_event_us, 3),
        "events_per_step": events_per_step,
        "overhead_frac": round(accounted, 4),   # accounted (the contract)
        "smoke": bool(args.smoke),
    }))
    env.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
