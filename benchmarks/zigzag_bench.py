"""Causal ring attention: contiguous vs zigzag-sharded schedule.

The contiguous causal ring computes the full (2c x 2c) score block every hop
and masks ~half away; zigzag (parallel/sequence.py zigzag_ring_attention)
does exactly two unmasked (c x c) updates per hop — ~2x fewer block-FLOPs,
uniformly across devices. Round-5 committed CPU-mesh row (B1 H4 S4096 D64,
ring of 8): 1.69x (1.7-1.75x across runs of this harness on the shared box).
Needs a multi-device mesh (virtual CPU mesh or a real slice); on a single
chip the ring degenerates and this prints a skip note.

Usage: MLSL_TPU_PLATFORM=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python benchmarks/zigzag_bench.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    import jax
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import mlsl_tpu as mlsl
    from mlsl_tpu.models.train import smap
    from mlsl_tpu.parallel.sequence import (
        ring_attention, zigzag_perm, zigzag_ring_attention,
    )

    env = mlsl.Environment.get_env().init()
    ndev = env.get_process_count()
    if ndev < 2:
        print(json.dumps({"metric": "zigzag_ring_speedup",
                          "skipped": "needs a multi-device mesh"}))
        return
    B, H, S, D = 1, 4, 4096, 64
    SP = ndev
    dist = env.create_distribution(1, 1, seq_parts=SP)
    mesh = dist.topology.mesh
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    perm = zigzag_perm(S, SP)
    spec = P(None, None, "seq", None)

    ring = jax.jit(smap(
        lambda q, k, v: ring_attention(q, k, v, "seq", SP, causal=True,
                                       use_flash=False),
        mesh, in_specs=(spec,) * 3, out_specs=spec,
    ))
    zig = jax.jit(smap(
        lambda q, k, v: zigzag_ring_attention(q, k, v, "seq", SP),
        mesh, in_specs=(spec,) * 3, out_specs=spec,
    ))
    qz, kz, vz = q[:, :, perm], k[:, :, perm], v[:, :, perm]

    from benchmarks._common import device_sync

    def best_ms(f, *a, n=10):
        # d2h readback, not block_until_ready: a future real-slice run goes
        # through the axon tunnel, where block_until_ready can acknowledge at
        # dispatch (memory: axon-tunnel-timing)
        device_sync(f(*a))
        device_sync(f(*a))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                r = f(*a)
            device_sync(r)
            best = min(best, (time.perf_counter() - t0) / n)
        return best * 1e3

    contig = best_ms(ring, q, k, v)
    zigzag = best_ms(zig, qz, kz, vz)
    print(json.dumps({
        "metric": "zigzag_ring_speedup",
        "value": round(contig / zigzag, 3),
        "unit": "x",
        "contiguous_ms": round(contig, 2),
        "zigzag_ms": round(zigzag, 2),
        "shape": f"B{B} H{H} S{S} D{D} ring{SP}",
    }))


if __name__ == "__main__":
    main()
