"""Input-pipeline benchmark: the wire-dtype x depth x cache feed grid.

Streams a fixed synthetic image dataset through the device feed pipeline
(mlsl_tpu.data: DeviceFeed + AsyncLoader) for every cell of
{wire dtype} x {prefetch depth} x {cache on/off}, with a small jitted
consumer forcing materialization of each decoded batch. Reports effective
images/s, achieved H2D MB/s, wire MB/batch, and per-batch input stall — the
numbers that say whether a training job on this machine should ship uint8,
bf16, or full-width batches, how deep to prefetch, and whether its dataset
should pin in HBM.

Epoch 0 of every cell is warmup (staging + decode compiles); the timed
window covers the REPLAY epochs, where the cache pays off (or doesn't).

The closing ``input_pipeline_best`` row names the winning cell — its
``feed_depth`` is the value an operator (or a tuned profile,
tuner.KNOB_RANGES) would carry as ``MLSL_FEED_DEPTH`` on this machine
(docs/TUNING.md §12).

Usage: MLSL_TPU_PLATFORM=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
       python benchmarks/input_pipeline_bench.py [--smoke]
--smoke trims the grid and shapes for the tier-1 wiring
(tests/test_feed.py, ``bench_smoke`` marker). Prints one JSON row per cell
(the standard capture-row shape: a "metric" field per line).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast tier-1 mode: tiny shapes, trimmed grid")
    args = ap.parse_args()

    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    import numpy as np
    import jax
    import jax.numpy as jnp

    import mlsl_tpu as mlsl
    from mlsl_tpu.core import stats as core_stats
    from mlsl_tpu.data import AsyncLoader, DeviceFeed

    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()
    dist = env.create_distribution(world, 1)
    topo = dist.topology

    if args.smoke:
        batch, hw, n_batches, epochs = 16, 16, 3, 2
        wires = ("none", "uint8")
        depths = (2,)
        caches = (0, 64)
    else:
        batch, hw, n_batches, epochs = 64, 64, 6, 3
        wires = ("none", "bf16", "uint8", "int8")
        depths = (1, 2, 4)
        caches = (0, 512)

    rng = np.random.default_rng(0)
    dataset = [
        (rng.normal(size=(batch, hw, hw, 3)).astype(np.float32),
         rng.integers(0, 100, size=(batch,)).astype(np.int32))
        for _ in range(n_batches)
    ]

    @jax.jit
    def consume(b):
        # forces materialization of the decoded batch; tiny on purpose —
        # this bench measures the FEED, bench.py measures feed-under-train
        return sum(jnp.sum(l.astype(jnp.float32)) for l in jax.tree.leaves(b))

    def run_cell(wire, depth, cache_mb):
        core_stats.reset_feed_counters()
        feed = DeviceFeed(dataset, topo, wire=wire, cache_mb=cache_mb,
                          epochs=epochs + 1)
        loader = AsyncLoader(feed, depth=depth)
        it = iter(loader)
        for _ in range(n_batches):  # warm epoch: staging + decode compiles
            float(consume(next(it)))
        f0 = dict(core_stats.FEED_COUNTERS)
        st0 = loader.stats()
        t0 = time.perf_counter()
        count = 0
        for b in it:
            float(consume(b))
            count += 1
        dt = time.perf_counter() - t0
        f1 = dict(core_stats.FEED_COUNTERS)
        st1 = loader.stats()
        loader.close()
        staged = int(f1["batches_staged"] - f0["batches_staged"])
        wire_bytes = f1["wire_bytes"] - f0["wire_bytes"]
        return {
            "metric": "input_pipeline",
            "wire": wire,
            "depth": depth,
            "cache_mb": cache_mb,
            "images_per_s": round(count * batch / dt, 1),
            "h2d_mbps": round(wire_bytes / 1e6 / dt, 2),
            "wire_mb_per_batch": (
                round(wire_bytes / 1e6 / staged, 3) if staged else 0.0
            ),
            "stall_ms_per_batch": round(
                (st1["stall_ms"] - st0["stall_ms"]) / max(count, 1), 3
            ),
            "cache_hits": int(f1["cache_hits"] - f0["cache_hits"]),
            "batch": batch,
            "hw": hw,
            "epochs_timed": epochs,
        }

    rows = []
    for wire in wires:
        for depth in depths:
            for cache_mb in caches:
                row = run_cell(wire, depth, cache_mb)
                rows.append(row)
                print(json.dumps(row), flush=True)

    best = max(rows, key=lambda r: r["images_per_s"])
    print(json.dumps({
        "metric": "input_pipeline_best",
        "wire": best["wire"],
        "feed_depth": best["depth"],
        "cache_mb": best["cache_mb"],
        "images_per_s": best["images_per_s"],
        "device": jax.devices()[0].device_kind,
    }), flush=True)


if __name__ == "__main__":
    main()
