"""Quantized-bucket benchmark: coalesced vs per-layer rings, quant vs plain.

Two measurements through the ParameterSet engine (core/bucketing.py +
comm/quant_ring.py):

1. **algbw curve** — a backward-shaped stream of NL same-size gradient
   allreduces at several message sizes, in all four corners of
   {individual, bucketed} x {plain f32, int8 quantized}. EQuARX/THC both show
   quantized collectives only reach peak algbw at coalesced, bandwidth-sized
   messages — this row set is where that shows up (or doesn't) on the
   attached backend.

2. **ResNet-50-shaped stream** — the full 161-tensor per-layer gradient list
   of a ResNet-50 (conv + BN + fc shapes), quantized, individual vs bucketed:
   aggregate per-step comm time. This is the acceptance row — the coalesced
   compressed ring must beat 161 per-layer compressed rings, which pay the
   host dispatch floor per tensor at latency-bound sizes.

Tensor counts are rounded UP to a small size palette so the per-layer path
compiles a handful of distinct ring programs instead of ~50 (the coalesced
path is insensitive; the palette preserves the size distribution).

Usage: MLSL_TPU_PLATFORM=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
       python benchmarks/quant_bucket_bench.py [--smoke]
--smoke scales the tensor list down (~1/16 the elements, same 161 tensors)
and trims sizes/iters — the tier-1 wiring (tests/test_quant_bucket.py, the
``bench_smoke`` marker) runs this mode. Prints one JSON row per
configuration (the standard capture-row shape: a "metric" field per line).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

#: size palette (elements): counts round UP to the nearest entry so the
#: individual path shares ring programs across same-palette tensors
PALETTE = (1024, 4096, 16384, 65536, 262144, 1048576, 4194304)


def _palette(c: int) -> int:
    for p in PALETTE:
        if c <= p:
            return p
    return c


def resnet50_counts(scale: int = 1):
    """Per-tensor parameter counts of a ResNet-50: 53 convs + 53 BN
    (gamma, beta) pairs + fc weight/bias = 161 tensors, palette-rounded.
    ``scale`` divides every count (smoke mode) without changing the tensor
    COUNT — the per-layer dispatch floor being measured is per tensor."""
    counts = []

    def conv(cin, cout, k):
        counts.append(cin * cout * k * k)
        counts.extend([cout, cout])  # BN gamma, beta

    conv(3, 64, 7)
    cin = 64
    for stage, (blocks, mid) in enumerate(
        [(3, 64), (4, 128), (6, 256), (3, 512)]
    ):
        for b in range(blocks):
            conv(cin, mid, 1)
            conv(mid, mid, 3)
            conv(mid, mid * 4, 1)
            if b == 0:  # downsample projection
                conv(cin, mid * 4, 1)
            cin = mid * 4
    counts.extend([2048 * 1000, 1000])  # fc weight, bias
    return [_palette(max(c // scale, 256)) for c in counts]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast tier-1 mode: scaled-down tensors, fewer iters")
    args = ap.parse_args()

    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    import numpy as np

    import mlsl_tpu as mlsl
    from benchmarks._common import device_sync
    from mlsl_tpu.types import CompressionType, OpType

    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()
    dist = env.create_distribution(world, 1)
    g = dist.get_process_count_data()
    degenerate = {"note": "degenerate group: dispatch floor"} if world == 1 else {}

    def build(counts, bucket_mb, compression):
        env.config.grad_bucket_mb = bucket_mb
        s = env.create_session()
        s.set_global_minibatch_size(8)
        ops = []
        for c in counts:
            r = s.create_operation_reg_info(OpType.CC)
            r.add_input(8, 4)
            r.add_output(8, 4)
            r.add_parameter_set(c, 1, compression_type=compression)
            ops.append(s.get_operation(s.add_operation(r, dist)))
        s.commit()
        env.config.grad_bucket_mb = 0
        return [op.get_parameter_set(0) for op in ops]

    def make_bufs(counts, seed=0):
        rng = np.random.default_rng(seed)
        vals = [rng.normal(size=c).astype(np.float64) for c in counts]
        return [
            dist.make_buffer(lambda p, v=v: v + p, c)
            for c, v in zip(counts, vals)
        ]

    # The CPU proof backend DEADLOCKS past a few dozen concurrent in-flight
    # collectives (a thread-pool rendezvous starves; bucketing_bench.py caps
    # NL=12 for the same reason), so a 161-tensor stream must bound its
    # outstanding requests. The window is BUCKET-AWARE: members of one bucket
    # start together (waiting any member before its bucket fills would trigger
    # the early-Wait fallback and silently measure the individual path), and
    # the window counts in-flight REQUESTS — one per bucket, one per
    # unbucketed member. This is also the realistic backward schedule: a
    # trainer drains old layers' collectives while new ones start.
    WINDOW = 8

    def step(pss, bufs):
        groups = []  # contiguous-by-bucket member index groups, start order
        cur_bucket = object()
        for i in range(len(pss) - 1, -1, -1):  # backward start order
            b = pss[i].bucket
            if b is None or b is not cur_bucket:
                groups.append([])
                cur_bucket = b
            groups[-1].append(i)
        outs = [None] * len(pss)
        inflight = []
        for idxs in groups:
            for i in idxs:
                pss[i].start_gradient_comm(bufs[i])
            inflight.append(idxs)
            if len(inflight) > WINDOW:
                for j in inflight.pop(0):
                    outs[j] = pss[j].wait_gradient_comm()
        for idxs in inflight:
            for j in idxs:
                outs[j] = pss[j].wait_gradient_comm()
        device_sync(outs[-1])

    def timed_step(pss, bufs, warmup, blocks, per_block):
        for _ in range(warmup):
            step(pss, bufs)
        best = float("inf")
        for _ in range(blocks):
            t0 = time.perf_counter()
            for _ in range(per_block):
                step(pss, bufs)
            best = min(best, (time.perf_counter() - t0) / per_block)
        return best * 1e3  # ms

    warmup, blocks, per_block = (1, 2, 1) if args.smoke else (3, 5, 5)

    # --- 1. algbw curve: size x {individual,bucketed} x {plain,quant} ---
    NL = 12  # stays under the CPU backend's in-flight collective limit
    sizes = (2048,) if args.smoke else (2048, 16384, 131072)
    for cnt in sizes:
        counts = [cnt] * NL
        bufs = make_bufs(counts, seed=cnt)
        total_bytes = NL * cnt * 4
        for comp, tag in (
            (CompressionType.NONE, "plain"),
            (CompressionType.QUANTIZATION, "quant"),
        ):
            times = {}
            for label, mb in (("individual_ms", 0), ("bucketed_ms", 4)):
                pss = build(counts, mb, comp)
                times[label] = round(
                    timed_step(pss, bufs, warmup, blocks, per_block), 3
                )
            # allreduce algorithm bandwidth over the aggregate stream
            algbw = {
                k.replace("_ms", "_gbps"): round(
                    2 * (g - 1) / max(g, 1) * total_bytes / (v / 1e3) / 1e9, 3
                )
                for k, v in times.items()
            }
            print(json.dumps({
                "metric": "quant_bucket_algbw",
                "compression": tag,
                "layers": NL,
                "grad_kib": cnt * 4 // 1024,
                **times,
                **algbw,
                "speedup": round(times["individual_ms"] / times["bucketed_ms"], 3),
                "unit": "ms",
                **degenerate,
            }))

    # --- 2. ResNet-50-shaped quantized stream (the acceptance row) ---
    counts = resnet50_counts(scale=16 if args.smoke else 1)
    bufs = make_bufs(counts, seed=50)
    rows = [("quant", CompressionType.QUANTIZATION)]
    if not args.smoke:
        rows.append(("plain", CompressionType.NONE))
    for tag, comp in rows:
        times = {}
        for label, mb in (("individual_ms", 0), ("bucketed_ms", 4)):
            pss = build(counts, mb, comp)
            n_bucketed = sum(ps.bucket is not None for ps in pss)
            times[label] = round(
                timed_step(pss, bufs, warmup, blocks, per_block), 3
            )
        print(json.dumps({
            "metric": "quant_bucket_resnet50_stream",
            "compression": tag,
            "tensors": len(counts),
            "params": sum(counts),
            "bucketed_members": n_bucketed,
            **times,
            "speedup": round(times["individual_ms"] / times["bucketed_ms"], 3),
            "unit": "ms",
            **degenerate,
        }))


if __name__ == "__main__":
    main()
