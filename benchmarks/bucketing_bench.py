"""Gradient-bucketing benchmark: per-layer vs coalesced grad sync.

Measures a backward-pass-shaped stream of N small gradient allreduces through
the ParameterSet engine, individually vs bucketed (core/bucketing.py), at a
launch-bound size and a bandwidth-entering size. The bucket's win is the
amortized host dispatch + wire latency; its cost is one jitted pack/unpack.
Round-5 CPU-mesh numbers: 12 x 8 KiB grads ~1.5x faster bucketed (1.49x in
the committed harness row; up to 1.9x on an unloaded box); 12 x 64 KiB about
par (the CPU backend's in-process reduce is uniquely cheap relative to its
dispatch). On a real chip per-launch cost is tunnel-bound, so the crossover
moves up.

Usage: MLSL_TPU_PLATFORM=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python benchmarks/bucketing_bench.py
Prints one JSON line per configuration.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    import numpy as np

    import mlsl_tpu as mlsl
    from mlsl_tpu.types import OpType

    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()
    dist = env.create_distribution(world, 1)
    # on one device the grad group is degenerate: neither path communicates,
    # so rows measure dispatch noise only — tag them like grid_collectives does
    degenerate = {"note": "degenerate group: dispatch floor"} if world == 1 else {}

    def build(nlayers, count, bucket_mb, du=False):
        env.config.grad_bucket_mb = bucket_mb
        s = env.create_session()
        s.set_global_minibatch_size(8)
        ops = []
        for _ in range(nlayers):
            r = s.create_operation_reg_info(OpType.CC)
            r.add_input(8, 4)
            r.add_output(8, 4)
            r.add_parameter_set(count, 1, distributed_update=du)
            ops.append(s.get_operation(s.add_operation(r, dist)))
        s.commit()
        env.config.grad_bucket_mb = 0
        return [op.get_parameter_set(0) for op in ops]

    # 12 stays under the CPU backend's concurrent in-flight collective limit
    NL = 12
    for cnt in (2048, 16384):
        bufs = [
            dist.make_buffer(
                lambda p: p + np.arange(cnt, dtype=np.float64), cnt
            )
            for _ in range(NL)
        ]

        from benchmarks._common import device_sync

        def step(pss):
            for ps, b in zip(reversed(pss), reversed(bufs)):
                ps.start_gradient_comm(b)
            outs = [ps.wait_gradient_comm() for ps in pss]
            # d2h readback, not block_until_ready: through the axon tunnel
            # block_until_ready can acknowledge at dispatch (memory:
            # axon-tunnel-timing), and this bench runs in the on-chip capture
            device_sync(outs[-1])

        times = {}
        for label, mb in (("individual_ms", 0), ("bucketed_ms", 4)):
            pss = build(NL, cnt, mb)
            for _ in range(3):
                step(pss)
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                for _ in range(5):
                    step(pss)
                best = min(best, (time.perf_counter() - t0) / 5)
            times[label] = round(best * 1e3, 3)
        print(json.dumps({
            "metric": "grad_bucketing_step",
            "layers": NL,
            "grad_kib": cnt * 4 // 1024,
            **times,
            "speedup": round(times["individual_ms"] / times["bucketed_ms"], 3),
            "unit": "ms",
            **degenerate,
        }))

    # ZeRO-1: both phases (grad reduce_scatter + increment all_gather) bucket
    cnt = 2048
    bufs = [dist.make_buffer(lambda p: p + np.arange(cnt, dtype=np.float64), cnt)
            for _ in range(NL)]
    from benchmarks._common import device_sync

    def du_step(pss):
        owned = {}
        for ps, b in zip(reversed(pss), reversed(bufs)):
            ps.start_gradient_comm(b)
        for ps in pss:
            owned[ps] = ps.wait_gradient_comm()
        for ps in pss:
            ps.start_increment_comm(owned[ps])
        outs = [ps.wait_increment_comm() for ps in pss]
        device_sync(outs[-1])

    times = {}
    for label, mb in (("individual_ms", 0), ("bucketed_ms", 4)):
        pss = build(NL, cnt, mb, du=True)
        for _ in range(3):
            du_step(pss)
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(5):
                du_step(pss)
            best = min(best, (time.perf_counter() - t0) / 5)
        times[label] = round(best * 1e3, 3)
    print(json.dumps({
        "metric": "zero1_bucketing_step",
        "layers": NL,
        "grad_kib": cnt * 4 // 1024,
        **times,
        "speedup": round(times["individual_ms"] / times["bucketed_ms"], 3),
        "unit": "ms",
        **degenerate,
    }))


if __name__ == "__main__":
    main()
