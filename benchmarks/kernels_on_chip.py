"""On-chip Pallas kernel validation + timing: flash attention and int8 quant.

The CPU-mesh suite exercises these kernels in interpret mode only
(tests/test_flash.py, tests/test_quant.py); this script compiles the real
pallas_call programs on the attached accelerator, checks them against the XLA
reference implementations, and times both sides. One JSON line per kernel:
{"kernel", "ok", "max_err", "pallas_ms", "xla_ms", "speedup"}.

Run on a machine with a real TPU attached (bench-style); falls back cleanly with
exit 3 if the accelerator is unreachable (same probe as bench.py).
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


from benchmarks._common import probe_accelerator as _probe_impl
from benchmarks._common import timed as _time


def _probe():
    _probe_impl("kernels_on_chip")


def main():
    _probe()
    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.default_backend() != "tpu":
        # the compiled (non-interpret) Pallas timings this script exists for
        # are TPU-only; interpret-mode numbers would be meaningless — skip
        # gracefully instead of crashing a misconfigured run
        print(json.dumps({"kernel": "all", "ok": True,
                          "skipped": "needs a TPU backend "
                                     f"(got {jax.default_backend()})"}))
        return

    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception:
        pass

    from mlsl_tpu.ops import attention_kernels as ak
    from mlsl_tpu.ops import quant_kernels as qk

    results = []

    # --- flash attention fwd (+bwd), causal, long-ish sequence ---
    BH, S, D = 8, 2048, 128
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(BH, S, D)).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.normal(size=(BH, S, D)).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.normal(size=(BH, S, D)).astype(np.float32)) * 0.3
    off = jnp.zeros((1,), jnp.int32)

    for causal in (False, True):
        name = f"flash_fwd_{'causal' if causal else 'full'}"
        fl = jax.jit(lambda q, k, v: ak.flash_attention(q, k, v, off, off,
                                                        causal=causal))
        ref = jax.jit(lambda q, k, v: ak._reference_attention(q, k, v, off, off,
                                                              causal))
        got, want = fl(q, k, v), ref(q, k, v)
        err = float(jnp.max(jnp.abs(got - want)))
        p_ms, x_ms = _time(fl, q, k, v), _time(ref, q, k, v)
        results.append({"kernel": name, "ok": err < 2e-2, "max_err": round(err, 5),
                        "pallas_ms": round(p_ms, 3), "xla_ms": round(x_ms, 3),
                        "speedup": round(x_ms / p_ms, 3)})

    # fwd+bwd through the custom vjp
    def fl_loss(q, k, v):
        return jnp.sum(ak.flash_attention(q, k, v, off, off, causal=True) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(ak._reference_attention(q, k, v, off, off, True) ** 2)

    fl_g = jax.jit(jax.grad(fl_loss, argnums=(0, 1, 2)))
    ref_g = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))
    gf, gr = fl_g(q, k, v), ref_g(q, k, v)
    err = float(max(jnp.max(jnp.abs(a - b)) for a, b in zip(gf, gr)))
    p_ms, x_ms = _time(fl_g, q, k, v), _time(ref_g, q, k, v)
    results.append({"kernel": "flash_fwd_bwd_causal", "ok": err < 5e-2,
                    "max_err": round(err, 5), "pallas_ms": round(p_ms, 3),
                    "xla_ms": round(x_ms, 3), "speedup": round(x_ms / p_ms, 3)})

    # --- int8 block quant roundtrip ---
    n = 8 * 1024 * 1024  # 32 MiB fp32
    x = jnp.asarray(rng.normal(size=(n // 256, 256)).astype(np.float32))

    def pallas_rt(x):
        qv, s = qk._quantize_pallas(x)
        return qk._dequantize_pallas(qv, s)

    def ref_rt(x):
        qv, s = qk.quantize_blocks_ref(x)
        return qk.dequantize_blocks_ref(qv, s)

    pallas_rt_j, ref_rt_j = jax.jit(pallas_rt), jax.jit(ref_rt)
    got, want = pallas_rt_j(x), ref_rt_j(x)
    err = float(jnp.max(jnp.abs(got - want)))
    p_ms, x_ms = _time(pallas_rt_j, x), _time(ref_rt_j, x)
    results.append({"kernel": "quant_int8_roundtrip_32MiB", "ok": err < 1e-6,
                    "max_err": round(err, 8), "pallas_ms": round(p_ms, 3),
                    "xla_ms": round(x_ms, 3), "speedup": round(x_ms / p_ms, 3)})

    for r in results:
        print(json.dumps(r))
    if not all(r["ok"] for r in results):
        sys.exit(1)


if __name__ == "__main__":
    main()
