"""On-chip Pallas kernel validation + timing: flash attention and int8 quant.

The CPU-mesh suite exercises these kernels in interpret mode only
(tests/test_flash.py, tests/test_quant.py); this script compiles the real
pallas_call programs on the attached accelerator, checks them against the XLA
reference implementations, and times both sides. One JSON line per kernel:
{"kernel", "ok", "max_err", "pallas_ms", "xla_ms", "speedup"}.

Run on a machine with a real TPU attached (bench-style); falls back cleanly with
exit 3 if the accelerator is unreachable (same probe as bench.py).
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


from benchmarks._common import probe_accelerator as _probe_impl
from benchmarks._common import timed_scan as _time_scan


def _probe():
    _probe_impl("kernels_on_chip")


def main():
    _probe()
    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.default_backend() != "tpu":
        # the compiled (non-interpret) Pallas timings this script exists for
        # are TPU-only; interpret-mode numbers would be meaningless — skip
        # gracefully instead of crashing a misconfigured run
        print(json.dumps({"kernel": "all", "ok": True,
                          "skipped": "needs a TPU backend "
                                     f"(got {jax.default_backend()})"}))
        return

    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception:  # mlsl-lint: disable=A205 -- cache arming is optional
        pass

    from mlsl_tpu.ops import attention_kernels as ak
    from mlsl_tpu.ops import quant_kernels as qk

    results = []

    def _retry_scan(step, carry, iters):
        # a floored timed_scan (1 µs) means the paired difference went
        # negative under a load spike — remeasure, then give up honestly
        for _ in range(3):
            ms = _time_scan(step, carry, iters=iters)
            if ms > 2e-3:
                return ms
        return None

    def _row(name, ok, err, p_ms, x_ms):
        row = {"kernel": name, "ok": ok, "max_err": err,
               "pallas_ms": None if p_ms is None else round(p_ms, 3),
               "xla_ms": None if x_ms is None else round(x_ms, 3)}
        if p_ms is None or x_ms is None:
            row["speedup"] = None
            row["floored"] = True  # never fabricate a ratio from the floor
        else:
            row["speedup"] = round(x_ms / p_ms, 3)
        return row

    # --- flash attention fwd (+bwd), causal, long-ish sequence. D=128 is the
    # kernel's best case; D=64 is the head_dim the GPT-shaped bench configs
    # actually run (half the MXU contraction depth) ---
    BH, S, D = 8, 2048, 128
    rng = np.random.default_rng(0)
    off = jnp.zeros((1,), jnp.int32)

    def mk(d):
        return tuple(
            jnp.asarray(rng.normal(size=(BH, S, d)).astype(np.float32)) * 0.3
            for _ in range(3)
        )

    q, k, v = mk(D)

    # scan-timing: the attention output is a convex combination of v rows, so
    # feeding it back as the next q keeps the carry bounded for any length
    def _attn_step(f):
        return lambda c: (f(c[0], c[1], c[2]), c[1], c[2])

    for d, causal in ((128, False), (128, True), (64, True)):
        name = f"flash_fwd_{'causal' if causal else 'full'}"
        if d != D:
            name += f"_d{d}"
        qd, kd, vd = (q, k, v) if d == D else mk(d)
        fl = lambda q, k, v: ak.flash_attention(q, k, v, off, off, causal=causal)
        ref = lambda q, k, v: ak._reference_attention(q, k, v, off, off, causal)
        got, want = jax.jit(fl)(qd, kd, vd), jax.jit(ref)(qd, kd, vd)
        err = float(jnp.max(jnp.abs(got - want)))
        p_ms = _retry_scan(_attn_step(fl), (qd, kd, vd), 100)
        x_ms = _retry_scan(_attn_step(ref), (qd, kd, vd), 100)
        results.append(_row(name, err < 2e-2, round(err, 5), p_ms, x_ms))

    # fwd+bwd through the custom vjp
    def fl_loss(q, k, v):
        return jnp.sum(ak.flash_attention(q, k, v, off, off, causal=True) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(ak._reference_attention(q, k, v, off, off, True) ** 2)

    fl_g = jax.jit(jax.grad(fl_loss, argnums=(0, 1, 2)))
    ref_g = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))
    gf, gr = fl_g(q, k, v), ref_g(q, k, v)
    err = float(max(jnp.max(jnp.abs(a - b)) for a, b in zip(gf, gr)))

    # carry stays pinned near the original inputs; tanh bounds the feedback
    def _grad_step(g):
        def step(c):
            dq, dk, dv = g(*c)
            return (q + 1e-3 * jnp.tanh(dq), k + 1e-3 * jnp.tanh(dk),
                    v + 1e-3 * jnp.tanh(dv))
        return step

    p_ms = _retry_scan(_grad_step(fl_g), (q, k, v), 50)
    x_ms = _retry_scan(_grad_step(ref_g), (q, k, v), 50)
    results.append(_row("flash_fwd_bwd_causal", err < 5e-2, round(err, 5),
                        p_ms, x_ms))

    # --- int8 block quant, measured as the codec actually runs it: quantize
    # and dequantize SEPARATELY (the roundtrip comparison flatters XLA, which
    # fuses the two and never materializes the int8 wire buffer), at 256 MiB
    # so the working set exceeds VMEM and the kernels stream from HBM (a
    # 32 MiB scan carry stayed VMEM-resident and measured ~3 TB/s) ---
    from benchmarks._common import timed as _time_multi

    n = 64 * 1024 * 1024  # 256 MiB fp32
    x = jnp.asarray(rng.normal(size=(n // 256, 256)).astype(np.float32))

    qp = jax.jit(lambda x: qk._quantize_pallas(x))
    qr = jax.jit(lambda x: qk.quantize_blocks_ref(x))
    dp = jax.jit(lambda q, s: qk._dequantize_pallas(q, s))
    dr = jax.jit(lambda q, s: qk.dequantize_blocks_ref(q, s))
    qv, s = qp(x)
    qv_r, s_r = qr(x)
    q_ok = (bool(jnp.all(qv == qv_r)) and bool(jnp.all(s == s_r)))
    # same (qv, s) on both sides: isolates the dequant kernel under test from
    # any one-ulp quantizer divergence
    err = float(jnp.max(jnp.abs(dp(qv, s) - dr(qv, s))))

    def _t(f, *a):
        # iters=1800 -> 200-call arms (~100 ms paired diff on a ~0.5 ms
        # kernel), riding out sustained tunnel drift; a floored result (1 µs)
        # means the paired difference went negative under a load spike —
        # remeasure, then give up honestly
        for _ in range(3):
            ms = _time_multi(f, *a, iters=1800)
            if ms > 2e-3:
                return ms
        return None

    p_ms, x_ms = _t(qp, x), _t(qr, x)
    results.append(_row("quant_int8_256MiB", q_ok,
                              0.0 if q_ok else 1.0, p_ms, x_ms))
    p_ms = _t(dp, qv, s)
    x_ms = _t(dr, qv, s)
    results.append(_row("dequant_int8_256MiB", err < 1e-6,
                              round(err, 8), p_ms, x_ms))

    for r in results:
        print(json.dumps(r))
    if not all(r["ok"] for r in results):
        sys.exit(1)


if __name__ == "__main__":
    main()
