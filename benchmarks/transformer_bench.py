"""Transformer training throughput at a realistic long-context config.

The bench.py transformer row uses a tiny d512/seq-512 model where the vocab
projection dominates; this harness measures the long-context workload family
the framework is built for: a GPT-medium-shaped model (d1024 x 16 heads x
12 blocks) at seq 2048 with the flash causal kernel, through the
HybridTrainer on the attached device. Reports tok/s plus achieved TFLOP/s
and MFU from XLA's cost model on the compiled step.

Single chip: dp=sp=tp=1 (groups degenerate — this is the compute headline;
the multi-chip sharding evidence is the CPU-mesh suite). Batch auto-halves
on OOM. One JSON row per config on stdout.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from benchmarks._common import model_flops, setup_chip, timed

jax = setup_chip("transformer_bench")

import jax.numpy as jnp  # noqa: E402


def peak_tflops(kind: str) -> float:
    from bench import _peak_tflops

    return _peak_tflops(kind)


def run_config(env, name, cfg, batch):
    from mlsl_tpu.models import transformer as tfm

    trainer = tfm.HybridTrainer(
        env, cfg, 1, 1, 1, batch=batch, lr=0.1, devices=env.devices[:1]
    )
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab, size=(batch, cfg.seq_len)).astype(np.int32)
    labels = np.roll(toks, -1, axis=1)
    tb, lb = trainer.shard_tokens(toks, labels)

    ms = timed(lambda: trainer.step(tb, lb), iters=24, warmup=4, blocks=6)
    tokens = batch * cfg.seq_len
    row = {
        "metric": "transformer_train_step",
        "config": name,
        "d_model": cfg.d_model,
        "n_blocks": cfg.n_blocks,
        "seq_len": cfg.seq_len,
        "batch": batch,
        "step_ms": round(ms, 3),
        "tok_s": round(tokens / (ms / 1e3)),
    }
    peak = peak_tflops(jax.devices()[0].device_kind)
    # mfu_model = canonical model-FLOPs MFU (analytic, remat-comparable) —
    # needs nothing from the XLA cost model
    mf = model_flops(cfg, batch)
    if peak and mf:
        row["mfu_model"] = round(mf / (ms / 1e3) / 1e12 / peak, 4)
    # achieved TFLOP/s + MFU (executed-program utilization: counts remat
    # recompute) from the compiled step's own cost model
    try:
        compiled = trainer.compiled_step(tb, lb)
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
        if flops > 0:
            tf = flops / (ms / 1e3) / 1e12
            row["tflops"] = round(tf, 3)
            if peak:
                row["mfu"] = round(tf / peak, 4)
    except Exception as e:
        print(f"transformer_bench: cost_analysis unavailable ({e})",
              file=sys.stderr)
    print(json.dumps(row), flush=True)
    return row


def main():
    import argparse

    import mlsl_tpu as mlsl
    from mlsl_tpu.models import transformer as tfm

    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="tiny config (CI smoke on the CPU backend)")
    args = ap.parse_args()

    env = mlsl.Environment.get_env().init()

    if args.quick:
        configs = [
            ("quick-d64", tfm.TransformerConfig(
                vocab=512, d_model=64, n_heads=4, head_dim=16,
                n_blocks=2, seq_len=128), 4),
        ]
    else:
        configs = [
            ("gpt-medium-2k", tfm.TransformerConfig(
                vocab=32768, d_model=1024, n_heads=16, head_dim=64,
                n_blocks=12, seq_len=2048), 8),
            ("gpt-medium-2k-remat", tfm.TransformerConfig(
                vocab=32768, d_model=1024, n_heads=16, head_dim=64,
                n_blocks=12, seq_len=2048, remat=True), 8),
            ("gpt-medium-2k-remat-dots", tfm.TransformerConfig(
                vocab=32768, d_model=1024, n_heads=16, head_dim=64,
                n_blocks=12, seq_len=2048, remat=True,
                remat_policy="dots"), 8),
            # long-context single-chip row: at seq 8k the plain step's saved
            # activations overflow a 16 GiB v5e — remat makes it fit
            ("gpt-medium-8k-remat", tfm.TransformerConfig(
                vocab=32768, d_model=1024, n_heads=16, head_dim=64,
                n_blocks=12, seq_len=8192, remat=True), 2),
            # 'dots' keeps matmul/attention outputs and replays only
            # elementwise work — the cheaper long-context remat when the
            # saved O(blocks*S*d) bytes still fit
            ("gpt-medium-8k-remat-dots", tfm.TransformerConfig(
                vocab=32768, d_model=1024, n_heads=16, head_dim=64,
                n_blocks=12, seq_len=8192, remat=True,
                remat_policy="dots"), 2),
            ("d512-8blk-512", tfm.TransformerConfig(
                vocab=32768, d_model=512, n_heads=8, head_dim=64,
                n_blocks=8, seq_len=512), 32),
        ]
    for name, cfg, batch in configs:
        while batch >= 1:
            try:
                run_config(env, name, cfg, batch)
                break
            except Exception as e:
                s = str(e)
                if batch > 1 and ("RESOURCE_EXHAUSTED" in s or "emory" in s):
                    print(f"transformer_bench: {name} batch {batch} OOM; "
                          f"halving", file=sys.stderr)
                    batch //= 2
                    continue
                raise


if __name__ == "__main__":
    main()
