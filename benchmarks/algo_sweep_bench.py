"""Algorithm-sweep benchmark: algbw per collective algorithm x size x shape.

Runs the tuner sweep (mlsl_tpu.tuner.run_sweep — the SAME measurement the
MLSL_TUNE=1 init path uses) on the attached backend and prints one JSON row
per selection-table cell, so the per-algorithm algbw curves land in the
capture record next to the allreduce/quant curves. Then exercises the full
profile lifecycle: write the profile, reload it, verify the reloaded table
reproduces every recorded selection, and pin the chosen program of one
non-default cell bit-for-bit against the lax baseline on integer payloads
(the acceptance row: tuned path bit-identical to baseline for sum
allreduce).

Usage: MLSL_TPU_PLATFORM=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
       python benchmarks/algo_sweep_bench.py [--smoke] [--quant] \\
              [--profile-out PATH]

--smoke trims sizes/iters for the tier-1 wiring (tests/test_algos.py, the
``bench_smoke`` marker). Full sweeps (default sizes up to 8 MiB plus the
quant-block cell) belong to the standalone/capture run.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# smoke stays small on purpose: the tier-1 budget is tight, the non-default
# selections live at latency-bound sizes, and the bandwidth tail belongs to
# the full (standalone/capture) run
SMOKE_SIZES = (4 * 1024, 64 * 1024)
FULL_SIZES = (16 * 1024, 128 * 1024, 1024 * 1024, 8 * 1024 * 1024)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--quant", action="store_true",
                    help="also sweep the quant-ring block palette")
    ap.add_argument("--profile-out", default="",
                    help="write the profile here (default: a temp file)")
    ap.add_argument("--iters", type=int, default=0)
    args = ap.parse_args()

    from mlsl_tpu import sysinfo

    sysinfo.apply_platform_override()

    import numpy as np
    import jax

    from mlsl_tpu import tuner
    from mlsl_tpu.comm import algos
    from mlsl_tpu.comm.mesh import ProcessGroup, Topology
    from mlsl_tpu.types import ReductionType

    sizes = SMOKE_SIZES if args.smoke else FULL_SIZES
    iters = args.iters or (3 if args.smoke else 7)
    # an explicit --quant is honored even in smoke mode (run_tune.sh passes
    # it through); the tier-1 smoke wiring simply doesn't ask for it
    quant = args.quant

    prof = tuner.run_sweep(sizes=sizes, iters=iters, quant=quant)

    non_default = 0
    for c in prof.cells:
        best_us = c["us"][c["algo"]]
        payload = c.get("payload_bytes") or 0
        if c["algo"] != algos.DEFAULT:
            non_default += 1
        print(json.dumps({
            "metric": "algo_sweep",
            "kind": c["kind"],
            "shape": c["shape"],
            "bytes": payload,
            "chosen": c["algo"],
            "algbw_gbps": round(payload / (best_us / 1e6) / 1e9, 4)
            if best_us else None,
            "us": c["us"],
        }), flush=True)
    print(json.dumps({
        "metric": "algo_sweep_selection",
        "cells": len(prof.cells),
        "non_default": non_default,
        "knobs": {k: v for k, v in prof.knobs.items()
                  if not k.startswith("_")},
    }), flush=True)

    # -- profile round-trip + parity (the acceptance row) -------------------
    path = args.profile_out or os.path.join(
        tempfile.gettempdir(), f"mlsl_tune_profile.{os.getpid()}.json"
    )
    prof.save(path)
    back = tuner.load_profile(path)
    ok = back.matches(prof.fingerprint)
    for c in prof.cells:
        pb = c.get("payload_bytes") or 1
        if back.select(c["kind"], tuple(c["shape"]), "none", pb) != c["algo"]:
            ok = False

    # pin one cell's chosen program bit-for-bit against the baseline on
    # integer-valued payloads (every summation order exact); prefer a
    # non-default cell so the parity covers a genuinely different program
    cell = next((c for c in prof.cells if c["algo"] != algos.DEFAULT),
                prof.cells[0])
    devices = tuple(jax.devices())
    n_dev = len(devices)
    shape = tuple(cell["shape"])
    if len(shape) == 1:
        topo = Topology(n_dev, 1, devices=devices)
        group = ProcessGroup(topo, ("data",))
    else:
        topo = Topology(shape[0], shape[1], devices=devices)
        group = ProcessGroup(topo, ("data", "model"))
    g = group.size
    elems = max(((cell.get("payload_bytes") or 4096) // 4) // g * g, g)
    kw = {"op": ReductionType.SUM}
    if cell["kind"] == "reduce_scatter":
        kw["recv_count"] = elems // g
    rng = np.random.default_rng(0)
    vals = rng.integers(-8, 8, size=(*topo.grid_shape, elems)).astype(np.float32)
    buf = topo.shard_buffer(vals)
    base = algos.build(cell["kind"], group, np.float32, "lax", **kw)
    tuned = algos.build(cell["kind"], group, np.float32, cell["algo"], **kw)
    want = np.asarray(jax.block_until_ready(base(buf)))
    got = np.asarray(jax.block_until_ready(tuned(buf)))
    parity_exact = bool(np.array_equal(got, want))

    print(json.dumps({
        "metric": "algo_profile_roundtrip",
        "ok": bool(ok),
        "profile": path,
        "parity_cell": {"kind": cell["kind"], "shape": cell["shape"],
                        "algo": cell["algo"]},
        "parity_exact": parity_exact,
    }), flush=True)
    if not args.profile_out:
        os.unlink(path)
    return 0 if ok and parity_exact else 1


if __name__ == "__main__":
    sys.exit(main())
