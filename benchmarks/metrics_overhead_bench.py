"""Telemetry-plane overhead bench: the zero-alloc disabled path and the <2%
armed-path contract at the default cadence (ISSUE 15 acceptance).

Three measurements against the per-layer trainer's step floor on the 8-dev
CPU proof mesh:

- **disabled_zero_alloc** — tracemalloc-asserted (the tracer precedent):
  with the registry disarmed, a full training step must attribute ZERO
  allocations to ``obs/metrics.py`` — the disabled path is one module-attr
  load and a None test per site. The bench FAILS (exit 1) if this does not
  hold; it is a correctness gate, not a number.
- **accounted model** (the contract, trace_overhead_bench reasoning: the
  CPU mesh carries ±15% comparative noise, so the per-event costs are
  measured in isolation and composed):
  ``overhead_frac = (observe_ns x events_per_step + tick_ms / EVERY) /
  step_ms`` where events_per_step = one step_ms observe + one
  dispatch-wait + algbw observe per layer, and tick_ms is one full cadence
  tick (loss readback + family snapshot + ring sample + JSONL append).
  Acceptance: < 0.02 at the default ``MLSL_METRICS_EVERY`` (asserted in
  --smoke via the bench_smoke tier-1 test).
- **comparative delta** — armed-vs-off step time, reported but not the
  contract (noise).

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
       python benchmarks/metrics_overhead_bench.py [--smoke]
Prints one JSON row (capture-row shape, metric=metrics_overhead).
"""

import argparse
import json
import os
import sys
import time
import tracemalloc

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

#: the default sampler cadence (obs/metrics.py DEFAULT_EVERY): one cadence
#: tick per this many steps pays the loss readback + snapshot + JSONL append
DEFAULT_EVERY = 20


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast tier-1 mode: fewer iters")
    args = ap.parse_args()

    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    import numpy as np
    import jax
    import jax.numpy as jnp

    import mlsl_tpu as mlsl
    from mlsl_tpu.obs import metrics as obs_metrics

    # the bench owns the registry lifecycle end to end
    obs_metrics.disable()

    warmup, iters = (3, 8) if args.smoke else (5, 20)
    cadences = (1, 5, DEFAULT_EVERY) if args.smoke else (
        1, 5, 10, DEFAULT_EVERY, 100
    )

    # representative shape: same reasoning as sentinel_overhead_bench — the
    # per-step telemetry cost is per LAYER (one dispatch-wait observe each)
    # plus per STEP, so a multi-layer model with a real batch keeps the
    # measured fraction honest
    K, D, B = 6, 512, 8192
    layers = [f"l{i}" for i in range(K)]

    def init_params(key):
        ks = jax.random.split(key, K)
        return {
            f"l{i}": {
                "w": jax.random.normal(k, (D, D)) * 0.05,
                "b": jnp.zeros((D,)),
            }
            for i, k in enumerate(ks)
        }

    def loss_fn(params, batch):
        x, y = batch
        h = x
        for i in range(K):
            h = jnp.tanh(h @ params[f"l{i}"]["w"] + params[f"l{i}"]["b"])
        return jnp.mean((h[:, 0] - y) ** 2)

    from mlsl_tpu.models.train import DataParallelTrainer

    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()
    dist = env.create_distribution(world, 1)
    sess = env.create_session()
    sess.set_global_minibatch_size(B)
    trainer = DataParallelTrainer(
        env, dist, sess, init_params(jax.random.PRNGKey(0)), loss_fn,
        layers, lambda p, n: p[n], lr=0.05, force_graph_path=True,
    )
    rng = np.random.default_rng(0)
    x = rng.normal(size=(B, D)).astype(np.float32)
    y = rng.normal(size=(B,)).astype(np.float32)
    batch = trainer.shard_batch(x, y)

    def timed(fn, n, blocks=3):
        best = float("inf")
        per = max(1, n // blocks)
        for _ in range(blocks):
            t0 = time.perf_counter()
            for _ in range(per):
                fn()
            best = min(best, (time.perf_counter() - t0) / per * 1e3)
        return best

    # -- the step floor (metrics off) --------------------------------------
    assert obs_metrics._registry is None
    for _ in range(warmup):
        jax.block_until_ready(trainer.step(batch))
    step_ms = timed(lambda: jax.block_until_ready(trainer.step(batch)), iters)

    # -- disabled path: zero allocations attributed to obs/metrics.py ------
    obs_dir = os.path.dirname(os.path.abspath(obs_metrics.__file__))
    metrics_file = os.path.join(obs_dir, "metrics.py")
    tracemalloc.start()
    try:
        jax.block_until_ready(trainer.step(batch))
        snap = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    leaks = snap.filter_traces(
        [tracemalloc.Filter(True, metrics_file)]
    ).statistics("filename")
    disabled_zero_alloc = not leaks
    if not disabled_zero_alloc:
        print(f"metrics_overhead: DISABLED PATH ALLOCATED: {leaks}",
              file=sys.stderr)

    # -- accounted per-event costs -----------------------------------------
    reg = obs_metrics.enable(every=DEFAULT_EVERY)
    h = reg.histogram("mlsl_step_ms")
    n_obs = 20000 if not args.smoke else 5000
    t0 = time.perf_counter_ns()
    for _ in range(n_obs):
        h.observe(7.5)
    observe_ns = (time.perf_counter_ns() - t0) / n_obs

    # one full cadence tick: loss readback (device sync) + family snapshot
    # + ring sample + JSONL append — measured through the trainer's own
    # tick path so the model prices what production pays
    loss = trainer.step(batch)
    jax.block_until_ready(loss)
    for _ in range(2):
        trainer._sample_telemetry(reg, loss)
    tick_ms = timed(lambda: trainer._sample_telemetry(reg, loss),
                    8 if args.smoke else 16)

    # events per step on this trainer: one step_ms observe + per-layer
    # (dispatch_wait + algbw) observes from the request layer
    events_per_step = 1 + 2 * K
    curve = {
        str(k): round(
            (observe_ns * events_per_step / 1e6 + tick_ms / k) / step_ms, 5
        )
        for k in cadences
    }

    # -- comparative delta (reported, not the contract) --------------------
    for _ in range(warmup):
        jax.block_until_ready(trainer.step(batch))
    armed_ms = timed(lambda: jax.block_until_ready(trainer.step(batch)),
                     iters)
    obs_metrics.disable()

    row = {
        "metric": "metrics_overhead",
        "devices": world,
        "iters": iters,
        "step_ms": round(step_ms, 3),
        "disabled_zero_alloc": disabled_zero_alloc,
        "observe_ns": round(observe_ns, 1),
        "tick_ms": round(tick_ms, 3),
        "events_per_step": events_per_step,
        "cadence_default": DEFAULT_EVERY,
        "overhead_frac_default": curve[str(DEFAULT_EVERY)],
        "overhead_frac_by_cadence": curve,
        "armed_step_ms": round(armed_ms, 3),
        "delta_frac": round((armed_ms - step_ms) / step_ms, 4),
        "smoke": bool(args.smoke),
    }
    print(json.dumps(row))
    env.finalize()
    if not disabled_zero_alloc:
        return 1
    if row["overhead_frac_default"] >= 0.02:
        print(f"metrics_overhead: armed path {row['overhead_frac_default']}"
              " >= 0.02 of the step at the default cadence", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
