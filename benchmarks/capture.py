"""Durable on-chip perf capture (VERDICT r2 item 1).

Watches the accelerator tunnel; the moment it answers, runs the full perf
suite (bench.py, kernels_on_chip.py, allreduce_curve.py) and appends a
timestamped record to BENCH_MEASURED.json at the repo root so a mid-round
success survives an end-of-round tunnel outage. Re-run after perf-relevant
commits with --once to refresh the record.

Methodology anchor: the reference's isolation-stats capture
(/root/reference/src/mlsl_impl_stats.cpp:387-562) — repeated replay, warmup
skipped, numbers recorded to a durable log rather than reported transiently.

Usage:
    python benchmarks/capture.py            # wait for tunnel, capture, exit
    python benchmarks/capture.py --once     # single probe; exit 3 if dead
    python benchmarks/capture.py --suite quick   # bench.py only
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")

def probe(timeout: float = 90.0):
    """Returns device_kind string if the tunnel answers, else None."""
    kind, _ = probe_device_kind(timeout)
    return kind


def run_step(name, cmd, timeout, env=None):
    """Run one benchmark subprocess; returns a record with parsed JSON lines."""
    t0 = time.time()
    try:
        proc = subprocess.run(
            cmd, cwd=REPO, capture_output=True, text=True, timeout=timeout,
            start_new_session=True, env=env,
        )
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc = -1
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = f"timeout after {timeout}s"
    rows = []
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return {
        "step": name, "rc": rc, "wall_s": round(time.time() - t0, 1),
        "rows": rows,
        "stderr_tail": err[-400:] if rc != 0 else "",
    }


sys.path.insert(0, REPO)
from benchmarks._common import (  # noqa: E402
    append_measurement, git_sha, measured_path, probe_device_kind,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--once", action="store_true",
                    help="single probe; exit 3 if the tunnel is dead")
    ap.add_argument("--suite", choices=["full", "quick", "smoke"], default="full",
                    help="smoke = trimmed bench.py --quick (CI: proves the "
                         "probe->run->persist pipeline on the CPU backend)")
    ap.add_argument("--poll-sleep", type=float, default=180.0)
    ap.add_argument("--max-wait-hours", type=float, default=11.0)
    ap.add_argument("--probe-timeout", type=float, default=90.0)
    args = ap.parse_args()

    deadline = time.monotonic() + args.max_wait_hours * 3600
    attempt = 0
    while True:
        attempt += 1
        kind = probe(timeout=args.probe_timeout)
        if kind:
            break
        print(f"capture: probe {attempt} dead tunnel "
              f"({time.strftime('%H:%M:%S')})", flush=True)
        if args.once:
            sys.exit(3)
        if time.monotonic() > deadline:
            print("capture: gave up waiting for the tunnel", flush=True)
            sys.exit(3)
        time.sleep(args.poll_sleep)

    print(f"capture: tunnel ALIVE, device={kind}; running suite", flush=True)
    env = dict(os.environ)
    env.setdefault("MLSL_BENCH_PROBE_ATTEMPTS", "2")
    # capture.py writes the record itself; stop bench.py double-recording
    env["MLSL_BENCH_NO_PERSIST"] = "1"

    if args.suite == "smoke":
        steps = [("bench", [sys.executable, "bench.py", "--quick",
                            "--iters", "2", "--warmup", "1"], 900)]
    else:
        steps = [("bench", [sys.executable, "bench.py"], 3000)]
    if args.suite == "full":
        steps += [
            ("kernels_on_chip",
             [sys.executable, "benchmarks/kernels_on_chip.py"], 2400),
            ("allreduce_curve",
             [sys.executable, "benchmarks/allreduce_curve.py", "--quant"], 2400),
            ("bucketing",
             [sys.executable, "benchmarks/bucketing_bench.py"], 1200),
            ("quant_bucket",
             [sys.executable, "benchmarks/quant_bucket_bench.py"], 1800),
            ("overlap_compiled",
             [sys.executable, "benchmarks/overlap_compiled_bench.py"], 1800),
            ("trace_overhead",
             [sys.executable, "benchmarks/trace_overhead_bench.py"], 900),
            ("input_pipeline",
             [sys.executable, "benchmarks/input_pipeline_bench.py"], 1200),
            ("sentinel_overhead",
             [sys.executable, "benchmarks/sentinel_overhead_bench.py"], 900),
            ("metrics_overhead",
             [sys.executable, "benchmarks/metrics_overhead_bench.py"], 900),
            ("algo_sweep",
             [sys.executable, "benchmarks/algo_sweep_bench.py", "--quant"],
             1800),
            # r06 headline: the fused pallas ring's measured algbw curve vs
            # the composed lowerings (dense + int8 wire), bidir included —
            # the kernel-quality acceptance for ROADMAP #1. The same run
            # also re-validates the stale BENCH_r05 rows (BASELINE.md
            # "Stale pipeline rows": per_layer_vs_fused, pipeline_step_ms,
            # overlap_fraction) via the bench/overlap_compiled steps above.
            ("pallas_ring",
             [sys.executable, "benchmarks/pallas_ring_bench.py", "--bidir"],
             2400),
            # r06: small-message latency class — the rhd/ring crossover
            # curve and the fused quantized MoE exchange vs the inline lax
            # wire (BASELINE.md "Expected r06 rows")
            ("latency",
             [sys.executable, "benchmarks/latency_bench.py"], 2400),
            # two-tier hierarchical curve: on a single slice this runs the
            # synthetic 2x4 split + DCN simulator (flat-vs-hier ordering);
            # on a real multislice attachment drop the sim and the env
            # override to measure the physical DCN (docs/TUNING.md §17)
            ("hier",
             [sys.executable, "benchmarks/hier_bench.py"], 1800),
            ("grid_collectives",
             [sys.executable, "benchmarks/grid_collectives.py"], 1200),
            ("transformer",
             [sys.executable, "benchmarks/transformer_bench.py"], 2400),
            # serving plane (mlsl_tpu/serve): full offered-load grid —
            # tokens/s, TTFT/TPOT tails, the chaos degraded-not-down row,
            # and the paged-vs-unpaged parity gate (docs/TUNING.md §21)
            ("serving",
             [sys.executable, "benchmarks/serving_bench.py"], 2400),
            # codec lab (mlsl_tpu.codecs): full wire-bytes x codec x size
            # grid + the calibrated-vs-uniform-int8 acceptance row on the
            # ResNet-50-shaped stream (docs/TUNING.md §22)
            ("codec_lab",
             [sys.executable, "benchmarks/codec_lab_bench.py"], 1200),
        ]

    record = {
        # mlsl-lint: disable=A206 -- a wall-clock run id, not a deadline
        "run_id": f"{int(time.time())}-{os.getpid()}",
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_sha": git_sha(),
        "device_kind": kind,
        "steps": [],
    }
    for name, cmd, to in steps:
        print(f"capture: running {name} ...", flush=True)
        rec = run_step(name, cmd, to, env=env)
        print(f"capture: {name} rc={rec['rc']} wall={rec['wall_s']}s "
              f"rows={len(rec['rows'])}", flush=True)
        record["steps"].append(rec)
        # persist after EVERY step so a crash mid-suite loses nothing
        append_measurement(dict(record, partial=(name != steps[-1][0])))

    ok = all(s["rc"] == 0 for s in record["steps"])
    print(f"capture: done ok={ok}; appended to {measured_path()}", flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
