"""Capture an XLA op-level profile of the ResNet-50 train step on the chip.

Writes a jax.profiler trace of a few steps to --logdir, then (if the
tensorboard profile plugin is importable) prints the top-k ops by self time —
the ground truth for where the step's milliseconds go (TUNING.md step 6.3).
"""

import argparse
import glob
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from benchmarks._common import device_sync, setup_chip

jax = setup_chip("profile_step")

import jax.numpy as jnp

from mlsl_tpu.models import resnet


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--logdir", default="/tmp/mlsl_profile")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args()

    params = jax.device_put(resnet.init_resnet50(jax.random.PRNGKey(0), 1000))
    rng = np.random.default_rng(0)
    x = jax.device_put(jnp.asarray(
        rng.normal(size=(args.batch, 224, 224, 3)), jnp.float32))
    y = jax.device_put(jnp.asarray(
        rng.integers(0, 1000, size=(args.batch,)), jnp.int32))
    lr = 0.05

    @jax.jit
    def sgd(p, b):
        loss, g = jax.value_and_grad(resnet.loss_fn)(p, b)
        return loss, jax.tree.map(lambda w, gg: w - lr * gg, p, g)

    _, p2 = sgd(params, (x, y))  # compile + warm
    device_sync(p2)

    jax.profiler.start_trace(args.logdir)
    p = params
    for _ in range(args.steps):
        _, p = sgd(p, (x, y))
    device_sync(p)
    jax.profiler.stop_trace()
    print("trace written to", args.logdir)

    xplanes = sorted(glob.glob(
        os.path.join(args.logdir, "**", "*.xplane.pb"), recursive=True))
    if not xplanes:
        print("no xplane.pb found")
        return
    xp = xplanes[-1]
    try:
        from tensorboard_plugin_profile.convert import raw_to_tool_data
        data, _ = raw_to_tool_data.xspace_to_tool_data(
            [xp], "framework_op_stats^", {})
    except Exception as e:
        print(f"op-stats conversion unavailable ({e}); raw trace at {xp}")
        return
    import csv
    import io
    rows = list(csv.DictReader(io.StringIO(
        data.decode() if isinstance(data, bytes) else data)))
    key = None
    for cand in ("total_self_time_in_us", "self_time_us", "Total self-time (us)"):
        if rows and cand in rows[0]:
            key = cand
            break
    if key is None:
        print("columns:", list(rows[0].keys()) if rows else "none")
        return
    rows.sort(key=lambda r: float(r[key] or 0), reverse=True)
    tot = sum(float(r[key] or 0) for r in rows)
    if tot <= 0:
        print("no nonzero self-time rows")
        return
    print(f"total self time: {tot/1e3:.2f} ms over {args.steps} steps")
    for r in rows[: args.top]:
        us = float(r[key] or 0)
        name = (r.get("operation") or r.get("Operation")
                or r.get("op_name") or "?")[:80]
        cat = r.get("category") or r.get("Type") or ""
        print(f"{us/tot*100:5.1f}%  {us/1e3/args.steps:8.3f} ms/step  "
              f"{cat:<18} {name}")


if __name__ == "__main__":
    main()
