"""Pallas fused-ring benchmark: algbw curve per lowering x wire x size.

The on-chip acceptance for the ``pallas_ring`` lowering (ROADMAP #1): the
measured allreduce algbw curve of the fused kernel against the composed
``lax`` lowerings — dense f32 wire vs ``lax``/``rhd``, int8 wire vs the
composed ``quant_ring`` — plus the parity acceptance rows (dense bit-exact
vs ``lax`` on integer sums; quantized bit-exact vs the ``quant_ring`` oracle
on an exact-scale payload, where every per-hop scale is exactly 1.0 so both
hop engines' arithmetic is exactly representable).

Off-TPU the kernel runs under the Pallas interpreter (armed here via
MLSL_PALLAS_INTERPRET=1 when no TPU is attached): the parity rows are real,
the timing rows are tagged ``backend: interpret`` and are NOT a performance
signal — the interpreter simulates every DMA with gathers. The measured
curve belongs to the next on-chip capture (BENCH r06, benchmarks/capture.py).

Usage: MLSL_TPU_PLATFORM=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
       python benchmarks/pallas_ring_bench.py [--smoke]

--smoke trims sizes/iters for the tier-1 wiring (tests/test_pallas_ring.py,
the ``bench_smoke`` marker). The full grid belongs to the capture run.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# interpret-mode DMAs are simulated with world gathers: smoke sizes must be
# tiny for the tier-1 budget; the full grid assumes a real chip
SMOKE_SIZES = (16 * 1024, 64 * 1024)
FULL_SIZES = (256 * 1024, 2 * 1024 * 1024, 16 * 1024 * 1024,
              64 * 1024 * 1024)
QUANT_BLOCK = 256


def _time(fn, args, iters, warmup=1):
    import jax

    fn = getattr(fn, "_mlsl_inner", fn)
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--iters", type=int, default=0)
    ap.add_argument("--bidir", action="store_true",
                    help="also time the bidirectional dense variant")
    args = ap.parse_args()

    from mlsl_tpu import sysinfo

    sysinfo.apply_platform_override()

    import numpy as np
    import jax

    if not sysinfo.on_tpu():
        # arm the interpreter BEFORE any kernel build: parity is real, the
        # timing rows are tagged
        os.environ.setdefault("MLSL_PALLAS_INTERPRET", "1")

    from mlsl_tpu.comm import algos, quant_ring
    from mlsl_tpu.comm.mesh import ProcessGroup, Topology
    from mlsl_tpu.ops import ring_kernels as rk
    from mlsl_tpu.types import ReductionType

    backend = "tpu" if sysinfo.on_tpu() else (
        "interpret" if rk.interpret_mode() else "cpu")
    sizes = SMOKE_SIZES if args.smoke else FULL_SIZES
    iters = args.iters or (2 if args.smoke else 7)

    n = jax.device_count()
    topo = Topology(n, 1)
    group = ProcessGroup(topo, ("data",))
    if not rk.eligible_quant(group, QUANT_BLOCK):
        print(json.dumps({"metric": "pallas_ring_bench",
                          "error": "pallas_ring not runnable on this "
                                   "backend/group", "backend": backend}))
        return 1
    rng = np.random.default_rng(0)

    def buf(elems, vals=None):
        a = vals if vals is not None else np.zeros(
            (*topo.grid_shape, elems), np.float32)
        return topo.shard_buffer(a)

    def zerr(el):
        return topo.shard_buffer(
            np.zeros((*topo.grid_shape, el), np.float32))

    # -- algbw curve --------------------------------------------------------
    for size_b in sizes:
        elems = max(-(-(size_b // 4) // n) * n, n)
        payload = elems * 4
        row = {"metric": "pallas_ring_bench", "bytes": payload,
               "backend": backend, "devices": n, "us": {}}
        dense_algos = ["lax", "rhd", "pallas_ring"]
        for algo in dense_algos:
            fn = algos.build("allreduce", group, np.float32, algo,
                             op=ReductionType.SUM)
            row["us"][f"dense/{algo}"] = round(
                _time(fn, (buf(elems),), iters) * 1e6, 1)
        if args.bidir:
            from mlsl_tpu.comm.algos import pallas_ring as pr

            fnb = pr.build("allreduce", group, op=ReductionType.SUM,
                           bidir=True)
            row["us"]["dense/pallas_ring+bidir"] = round(
                _time(fnb, (buf(elems),), iters) * 1e6, 1)
        for ring, name in (("lax", "quant_ring"), ("pallas", "pallas_ring")):
            fn, el = quant_ring.build_quantized_collective(
                "allreduce", group, elems, QUANT_BLOCK, ring=ring)
            row["us"][f"int8/{name}"] = round(
                _time(fn, (buf(elems), zerr(el)), iters) * 1e6, 1)
        row["algbw_gbps"] = {
            k: round(payload / (v / 1e6) / 1e9, 4)
            for k, v in row["us"].items() if v
        }
        print(json.dumps(row), flush=True)

    # -- parity acceptance rows --------------------------------------------
    elems = max(-(-(sizes[0] // 4) // n) * n, n)
    ivals = rng.integers(-8, 8,
                         size=(*topo.grid_shape, elems)).astype(np.float32)
    base = algos.build("allreduce", group, np.float32, "lax",
                       op=ReductionType.SUM)
    fused = algos.build("allreduce", group, np.float32, "pallas_ring",
                        op=ReductionType.SUM)
    want = np.asarray(jax.block_until_ready(base(buf(elems, ivals))))
    got = np.asarray(jax.block_until_ready(fused(buf(elems, ivals))))
    dense_ok = bool(np.array_equal(got, want))

    # exact-scale construction: sentinel +-127 at block position 0 on rank
    # 0, small ints elsewhere -> every entry/hop scale is exactly 1.0 and
    # both hop engines' arithmetic is exactly representable
    qelems = n * QUANT_BLOCK * 32
    v = rng.integers(-3, 3, size=(n, qelems)).astype(np.float32)
    v[:, ::QUANT_BLOCK] = 0.0
    v[0, ::QUANT_BLOCK] = 127.0
    qbuf = buf(qelems, v.reshape(*topo.grid_shape, qelems))
    ofn, oel = quant_ring.build_quantized_collective(
        "allreduce", group, qelems, QUANT_BLOCK, ring="lax")
    pfn, pel = quant_ring.build_quantized_collective(
        "allreduce", group, qelems, QUANT_BLOCK, ring="pallas")
    oo, oe = ofn(qbuf, zerr(oel))
    po, pe = pfn(qbuf, zerr(pel))
    oo, oe, po, pe = [np.asarray(jax.block_until_ready(a))
                      for a in (oo, oe, po, pe)]
    quant_ok = bool(np.array_equal(po, oo) and np.array_equal(pe, oe)
                    and oel == pel)

    print(json.dumps({
        "metric": "pallas_ring_parity",
        "backend": backend,
        "dense_int_bitexact_vs_lax": dense_ok,
        "quant_bitexact_vs_quant_ring": quant_ok,
    }), flush=True)
    return 0 if dense_ok and quant_ok else 1


if __name__ == "__main__":
    sys.exit(main())
