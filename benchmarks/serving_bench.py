"""Serving bench: offered-load throughput, TTFT/TPOT tails, chaos soak.

The acceptance instrument for the serving engine (mlsl_tpu/serve/):

- **load row**: requests submitted at a fixed offered rate against one
  engine on the CPU proof mesh — tokens/s, TTFT p50/p99 and TPOT p50/p99
  (per-step wall time over steps that had in-flight work), plus the
  429-rejection count. Two routes ("short"/"long") exercise the per-route
  metric labels.
- **chaos row**: the same load with a ``serve.decode`` hang armed — the
  degraded-not-down proof. A hang is a slow step, not an exception: the
  TPOT window breaches, the SLA ladder sheds, the queue drains, and every
  request still completes with zero unhandled exceptions; idle ticks after
  the drain show the ladder recovering.
- **parity rows**: paged decode bit-exact against the unpaged full-context
  oracle (float32), and the int8-paged variant within tolerance of it
  (the exit code; timing never gates).

Off-TPU the numbers are CPU-mesh proof numbers, tagged ``backend: cpu`` —
scheduling behaviour and parity are real, absolute tokens/s belongs to the
on-chip capture (benchmarks/capture.py).

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
       python benchmarks/serving_bench.py [--smoke]

--smoke trims the request count for the tier-1 wiring (tests/test_serve.py,
the ``bench_smoke`` marker). The full grid belongs to the capture run.
"""

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def _pct(vals, p):
    if not vals:
        return None
    vals = sorted(vals)
    return round(vals[min(len(vals) - 1, int(p * len(vals)))], 3)


def _drive(eng, prompts, max_new, rps):
    """Submit ``prompts`` at ``rps`` offered load while single-threaded
    stepping the engine; returns (reqs, rejected, tpot_samples, wall_s)."""
    import numpy as np

    from mlsl_tpu import serve

    reqs, tpots, rejected = [], [], 0
    t0 = time.monotonic()
    i = 0
    while True:
        now = time.monotonic()
        while i < len(prompts) and now - t0 >= i / rps:
            p = prompts[i]
            try:
                reqs.append(eng.submit(
                    np.asarray(p, np.int32), max_new,
                    route="long" if len(p) > 12 else "short"))
            except serve.ServeOverloadError:
                rejected += 1
            i += 1
        ts = time.monotonic()
        n = eng.step()
        if n > 0:
            tpots.append((time.monotonic() - ts) * 1e3)
        if i >= len(prompts) and n == 0 and not eng._pending:
            break
    return reqs, rejected, tpots, time.monotonic() - t0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--requests", type=int, default=0)
    ap.add_argument("--max-new", type=int, default=0)
    ap.add_argument("--rps", type=float, default=0.0)
    args = ap.parse_args()

    from mlsl_tpu import sysinfo

    sysinfo.apply_platform_override()

    import numpy as np
    import jax

    if not sysinfo.on_tpu():
        os.environ.setdefault("MLSL_PALLAS_INTERPRET", "1")

    from mlsl_tpu import chaos, serve
    from mlsl_tpu.core import stats
    from mlsl_tpu.core.environment import Environment
    from mlsl_tpu.models.transformer import TransformerConfig
    from mlsl_tpu.serve.engine import oracle_generate

    backend = "tpu" if sysinfo.on_tpu() else "cpu"
    n_req = args.requests or (6 if args.smoke else 32)
    max_new = args.max_new or (4 if args.smoke else 12)
    rps = args.rps or (50.0 if args.smoke else 100.0)

    env = Environment.get_env()
    env.init()
    cfg = TransformerConfig(vocab=128, d_model=64, n_heads=8, head_dim=8,
                            n_blocks=2, seq_len=64, dtype="float32")
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab, size=int(rng.integers(4, 25)))
               for _ in range(n_req)]

    # -- load row -----------------------------------------------------------
    eng = serve.InferenceEngine(env, cfg, tp=1, seed=0)
    reqs, rejected, tpots, wall = _drive(eng, prompts, max_new, rps)
    done = [r for r in reqs if r.state == "done"]
    ttfts = [r.ttft_ms for r in reqs if r.ttft_ms is not None]
    tokens = sum(len(r.tokens) for r in reqs)
    print(json.dumps({
        "metric": "serving_bench", "backend": backend,
        "devices": jax.device_count(), "requests": n_req,
        "offered_rps": rps, "max_new": max_new,
        "completed": len(done), "rejected": rejected,
        "tokens_per_s": round(tokens / wall, 1) if wall > 0 else None,
        "ttft_ms": {"p50": _pct(ttfts, 0.5), "p99": _pct(ttfts, 0.99)},
        "tpot_ms": {"p50": _pct(tpots, 0.5), "p99": _pct(tpots, 0.99)},
    }), flush=True)
    eng.close()

    # -- chaos soak row: a wedged decode degrades, never dies ---------------
    stats.reset_serve_counters()
    serve.reset()
    eng = serve.InferenceEngine(env, cfg, tp=1, seed=0,
                                tpot_p99_ms=5.0 if backend == "tpu" else 200.0)
    hang_s = 0.05 if args.smoke else 0.3
    chaos.plan("serve.decode", "hang", seconds=hang_s,
               times=3 if args.smoke else 8)
    unhandled = 0
    try:
        reqs, rejected, _, _ = _drive(eng, prompts, max_new, rps)
    except Exception:
        unhandled = 1
        reqs = []
    for _ in range(40):   # idle ticks: let the ladder climb back down
        eng.step()
    chaos.clear()
    sheds = stats.SERVE_COUNTERS["shed_batch"] \
        + stats.SERVE_COUNTERS["shed_precision"] \
        + stats.SERVE_COUNTERS["shed_admission"]
    completed = sum(1 for r in reqs if r.state == "done")
    failed = sum(1 for r in reqs if r.state == "failed")
    degraded_not_down = bool(unhandled == 0 and failed == 0
                             and completed + rejected == n_req
                             and not eng._pending and not eng._active)
    print(json.dumps({
        "metric": "serving_bench_chaos", "backend": backend,
        "hang_s": hang_s, "completed": completed, "rejected": rejected,
        "failed": failed, "unhandled": unhandled, "sheds": int(sheds),
        "recoveries": int(stats.SERVE_COUNTERS["recoveries"]),
        "final_rung": serve.status()["state"],
        "degraded_not_down": degraded_not_down,
    }), flush=True)
    eng.close()

    # -- parity acceptance rows ---------------------------------------------
    serve.reset()
    eng = serve.InferenceEngine(env, cfg, tp=1, seed=0)
    probe = prompts[0]
    r = eng.submit(np.asarray(probe, np.int32), max_new)
    eng.run()
    paged_ok = r.result() == oracle_generate(eng, probe, max_new)
    eng.close()

    serve.reset()
    qcfg = dataclasses.replace(env.config, serve_kv_quant=True)
    qeng = serve.InferenceEngine(env, cfg, tp=1, seed=0, config=qcfg)
    rq = qeng.submit(np.asarray(probe, np.int32), max_new)
    qeng.run()
    oracle = oracle_generate(qeng, probe, max_new)
    got = rq.result()
    # int8 KV: greedy argmax usually survives the quantization noise on
    # this tiny model; the hard gate is prefix agreement on the first token
    quant_ok = got[0] == oracle[0]
    quant_agree = sum(1 for a, b in zip(got, oracle) if a == b) / len(oracle)
    qeng.close()

    print(json.dumps({
        "metric": "serving_bench_parity", "backend": backend,
        "paged_bitexact_vs_unpaged": bool(paged_ok),
        "quant_first_token_exact": bool(quant_ok),
        "quant_token_agreement": round(quant_agree, 3),
        "chaos_degraded_not_down": degraded_not_down,
    }), flush=True)
    return 0 if paged_ok and quant_ok and degraded_not_down else 1


if __name__ == "__main__":
    sys.exit(main())
