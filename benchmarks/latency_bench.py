"""Small-message latency bench: the kernel family's latency-class curve.

The acceptance instrument for the ``pallas_rhd`` lowering (the recursive
halving/doubling allreduce) and the ``pallas_a2a`` fused MoE exchange:

- **latency curve** (<= 512 KiB, the ``msg_priority_threshold`` class):
  best-of-N wall time per payload for {``lax``, ``rhd``, ``pallas_ring``,
  ``pallas_rhd``} — the regime where hop count (2*log2(G) vs 2*(G-1)),
  not algbw, decides. The ``crossover`` row reports the smallest swept
  payload where the ring overtakes rhd (None = rhd won the whole band).
- **MoE row**: the fused quantized alltoall against the inline ``lax``
  exchange on a dispatch-shaped payload, with the analytic wire-bytes
  ratio (int8 blockwise codec vs f32 inline — <= 1/3 by construction).
- **parity rows**: integer-sum bit-exactness of every timed kernel against
  its lax oracle (the exit code; timing never gates).

Off-TPU the kernels run under the Pallas interpreter (armed here when no
TPU is attached): parity rows are real, timing rows are tagged ``backend:
interpret`` and are NOT a performance signal — interpreter DMAs are
simulated with world gathers. The measured curve belongs to the next
on-chip capture (BENCH r06, benchmarks/capture.py).

Usage: MLSL_TPU_PLATFORM=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
       python benchmarks/latency_bench.py [--smoke]

--smoke trims sizes/iters for the tier-1 wiring (tests/test_pallas_rhd.py,
the ``bench_smoke`` marker). The full grid belongs to the capture run.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# the latency class: everything at or below 512 KiB (the band boundary the
# MLSL_PALLAS_RHD_MAX_BYTES knob carves); smoke keeps interpret-mode wall
# time inside the tier-1 budget
SMOKE_SIZES = (4 * 1024, 32 * 1024)
FULL_SIZES = (4 * 1024, 16 * 1024, 64 * 1024, 256 * 1024, 512 * 1024)
QUANT_BLOCK = 256
MOE_ROWS_PER_DEST = 4          # dispatch-shaped: a few capacity rows/dest


def _time(fn, args, iters, warmup=1):
    import jax

    fn = getattr(fn, "_mlsl_inner", fn)
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--iters", type=int, default=0)
    args = ap.parse_args()

    from mlsl_tpu import sysinfo

    sysinfo.apply_platform_override()

    import numpy as np
    import jax

    if not sysinfo.on_tpu():
        os.environ.setdefault("MLSL_PALLAS_INTERPRET", "1")

    from mlsl_tpu.comm import algos
    from mlsl_tpu.comm.mesh import ProcessGroup, Topology
    from mlsl_tpu.ops import a2a_kernels, rhd_kernels
    from mlsl_tpu.ops import ring_kernels as rk
    from mlsl_tpu.types import ReductionType

    backend = "tpu" if sysinfo.on_tpu() else (
        "interpret" if rk.interpret_mode() else "cpu")
    sizes = SMOKE_SIZES if args.smoke else FULL_SIZES
    iters = args.iters or (2 if args.smoke else 9)

    n = jax.device_count()
    topo = Topology(n, 1)
    group = ProcessGroup(topo, ("data",))
    if not (rhd_kernels.eligible("allreduce", group)
            and a2a_kernels.eligible("alltoall", group)):
        print(json.dumps({"metric": "latency_bench",
                          "error": "kernel family not runnable on this "
                                   "backend/group", "backend": backend}))
        return 1
    rng = np.random.default_rng(0)

    def buf(elems, vals=None):
        a = vals if vals is not None else np.zeros(
            (*topo.grid_shape, elems), np.float32)
        return topo.shard_buffer(a)

    # -- latency curve ------------------------------------------------------
    curve = []
    for size_b in sizes:
        elems = max(-(-(size_b // 4) // n) * n, n)
        payload = elems * 4
        row = {"metric": "latency_bench", "bytes": payload,
               "backend": backend, "devices": n, "us": {}}
        for algo in ("lax", "rhd", "pallas_ring", "pallas_rhd"):
            fn = algos.build("allreduce", group, np.float32, algo,
                             op=ReductionType.SUM)
            row["us"][algo] = round(
                _time(fn, (buf(elems),), iters) * 1e6, 1)
        curve.append(row)
        print(json.dumps(row), flush=True)

    # crossover: the smallest payload where the bandwidth-class ring
    # overtakes the latency-class rhd (None = rhd won the whole band, the
    # expected shape when the band boundary sits above the sweep)
    cross = None
    for row in curve:
        if row["us"]["pallas_ring"] < row["us"]["pallas_rhd"]:
            cross = row["bytes"]
            break
    print(json.dumps({
        "metric": "latency_crossover", "backend": backend, "devices": n,
        "rhd_beats_ring_below_bytes": cross,
        "rhd_wins_band": [r["bytes"] for r in curve
                          if r["us"]["pallas_rhd"] <= r["us"]["pallas_ring"]],
    }), flush=True)

    # -- MoE dispatch row: fused quantized alltoall vs the inline lax wire --
    rc = n * QUANT_BLOCK * MOE_ROWS_PER_DEST // n * n  # per-dest, block grid
    count = n * rc
    fn_lax = algos.build("alltoall", group, np.float32, "lax",
                         send_count=rc)
    fn_a2a = algos.build("alltoall", group, np.float32, "pallas_a2a",
                         block=QUANT_BLOCK, quantized=True)
    moe = {"metric": "latency_bench_moe", "backend": backend, "devices": n,
           "bytes": count * 4, "us": {}}
    moe["us"]["inline_lax/f32"] = round(
        _time(fn_lax, (buf(count),), iters) * 1e6, 1)
    moe["us"]["pallas_a2a/int8"] = round(
        _time(fn_a2a, (buf(count),), iters) * 1e6, 1)
    wire_q = a2a_kernels.wire_bytes(n, count, QUANT_BLOCK, True)
    wire_f = a2a_kernels.wire_bytes(n, count, QUANT_BLOCK, False)
    moe["wire_bytes"] = {"pallas_a2a/int8": wire_q, "inline_lax/f32": wire_f,
                         "ratio": round(wire_q / wire_f, 4)}
    print(json.dumps(moe), flush=True)

    # -- parity acceptance rows (integer sums: exact in both codecs) --------
    elems = max(-(-(sizes[0] // 4) // n) * n, n)
    ivals = rng.integers(-8, 8,
                         size=(*topo.grid_shape, elems)).astype(np.float32)
    base = algos.build("allreduce", group, np.float32, "lax",
                       op=ReductionType.SUM)
    fused = algos.build("allreduce", group, np.float32, "pallas_rhd",
                        op=ReductionType.SUM)
    want = np.asarray(jax.block_until_ready(base(buf(elems, ivals))))
    got = np.asarray(jax.block_until_ready(fused(buf(elems, ivals))))
    rhd_ok = bool(np.array_equal(got, want))

    # integer payload with the +-127 sentinel at every block start on every
    # member: the blockwise scale is exactly 1.0, so the int8 round trip is
    # the identity and the fused wire must match the raw lax exchange
    qv = rng.integers(-10, 10, size=(n, count)).astype(np.float32)
    qv[:, ::QUANT_BLOCK] = 127.0
    qbuf = buf(count, qv.reshape(*topo.grid_shape, count))
    want = np.asarray(jax.block_until_ready(fn_lax(qbuf)))
    got = np.asarray(jax.block_until_ready(fn_a2a(qbuf)))
    a2a_ok = bool(np.array_equal(got, want))

    print(json.dumps({
        "metric": "latency_bench_parity",
        "backend": backend,
        "rhd_int_bitexact_vs_lax": rhd_ok,
        "a2a_int_bitexact_vs_lax": a2a_ok,
        "a2a_wire_ratio_le_third": bool(wire_q * 3 <= wire_f),
    }), flush=True)
    return 0 if rhd_ok and a2a_ok and wire_q * 3 <= wire_f else 1


if __name__ == "__main__":
    sys.exit(main())
