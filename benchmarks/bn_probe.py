"""Probe: how much of the ResNet-50 step is batch-norm statistics?

Compares the real model against (a) single-pass E[x^2]-E[x]^2 variance and
(b) a no-stats affine-only variant (identity stats — NOT valid training, just
an upper bound on what BN tuning could ever recover).

Measured (v5e, batch 32, round 2): two-pass ~16.5 ms, one-pass ~17.1 ms —
at small batch the rewrite did not pay. Re-measured at batch 128 (round 5,
BN_PROBE_BATCH=128): two-pass 58.8 ms, one-pass 49.2-54.2 ms, no-stats
40.8 ms — at the MXU-saturating batch the two-pass form's second activation
read dominates, so the model now uses the one-pass form with a clamped
variance (see mlsl_tpu/models/resnet.py _bn).
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from benchmarks._common import device_sync, setup_chip

jax = setup_chip("bn_probe")

import jax.numpy as jnp
from jax import lax

from mlsl_tpu.models import resnet


def bn_onepass(x, p, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(0, 1, 2), keepdims=True)
    msq = jnp.mean(jnp.square(xf), axis=(0, 1, 2), keepdims=True)
    var = msq - jnp.square(mean)
    inv = lax.rsqrt(var + eps)
    return ((xf - mean) * inv * p["scale"] + p["bias"]).astype(x.dtype)


def bn_nostats(x, p, eps=1e-5):
    return (x.astype(jnp.float32) * p["scale"] + p["bias"]).astype(x.dtype)


def timed_step(bn_impl, params, batch, tag):
    orig = resnet._bn
    resnet._bn = bn_impl
    try:
        lr = 0.05

        @jax.jit
        def sgd(p, b):
            loss, g = jax.value_and_grad(resnet.loss_fn)(p, b)
            return loss, jax.tree.map(lambda w, gg: w - lr * gg, p, g)

        p = jax.tree.map(jnp.copy, params)
        for _ in range(4):
            _, p = sgd(p, batch)
        device_sync(p)
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(8):
                _, p = sgd(p, batch)
            device_sync(p)
            best = min(best, (time.perf_counter() - t0) / 8 * 1e3)
        loss, _ = sgd(p, batch)
        print(f"{tag:12s}: best {best:6.2f} ms   loss {float(loss):.4f}")
        return best
    finally:
        resnet._bn = orig


def bn_twopass(x, p, eps=1e-5):
    # the pre-round-5 model form (resnet._bn is one-pass now): centered
    # variance, second full read of the activation
    mean = jnp.mean(x, axis=(0, 1, 2), dtype=jnp.float32)
    var = jnp.mean(lax.square(x.astype(jnp.float32) - mean), axis=(0, 1, 2))
    a = lax.rsqrt(var + eps) * p["scale"]
    b = p["bias"] - mean * a
    return (x * a + b).astype(x.dtype)


def main():
    BATCH = int(os.environ.get("BN_PROBE_BATCH", "32"))
    print("batch:", BATCH)
    params = jax.device_put(resnet.init_resnet50(jax.random.PRNGKey(0), 1000))
    rng = np.random.default_rng(0)
    x = jax.device_put(jnp.asarray(rng.normal(size=(BATCH, 224, 224, 3)), jnp.float32))
    y = jax.device_put(jnp.asarray(rng.integers(0, 1000, size=(BATCH,)), jnp.int32))
    timed_step(bn_twopass, params, (x, y), "two-pass")
    timed_step(resnet._bn, params, (x, y), "model(1p)")
    timed_step(bn_onepass, params, (x, y), "one-pass")
    timed_step(bn_nostats, params, (x, y), "no-stats")


if __name__ == "__main__":
    main()
