"""Codec-lab benchmark: wire bytes per codec, and calibrated vs uniform int8.

Two measurement families through the registry (mlsl_tpu.codecs) and the
calibration autotuner (tuner/calibrate.py):

1. **wire curve** — per registered codec x payload size: the compressed
   wire image of one full payload (``Codec.wire_len``) and the measured
   encode/decode noise-to-signal on the standard calibration sample. One
   JSON row per (codec, size): where each codec's byte cost sits against
   its noise cost on this machine's numerics.

2. **calibrated-vs-uniform-int8 acceptance row** — a ResNet-50-shaped
   quantized gradient stream (the 161-tensor list quant_bucket_bench.py
   measures) committed twice on the live 8-device mesh: once under
   ``MLSL_TUNE_CODEC``-style calibration (per-set codec x block against the
   NSR budget) and once on the uniform int8 seed wire. The acceptance
   contract: the calibrated assignment carries FEWER total wire bytes per
   round while every calibrated cell's NSR stays under the same budget the
   uniform wire comfortably meets (matched averaged-tail convergence, by
   construction of the budget constraint).

Usage: MLSL_TPU_PLATFORM=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
       python benchmarks/codec_lab_bench.py [--smoke]
--smoke trims the size grid and scales the stream (~1/16 elements, same 161
tensors) — the tier-1 wiring (tests/test_codec_lab.py, the ``bench_smoke``
marker) runs this mode. Full grid runs via benchmarks/capture.py. Prints
one JSON row per measurement (the standard capture-row shape).
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# calibration persists its profile to MLSL_STATS_DIR (default CWD) — route
# it to a scratch dir so a bench run never drops files at the repo root
os.environ.setdefault(
    "MLSL_STATS_DIR", tempfile.mkdtemp(prefix="codec_lab_bench_")
)

from quant_bucket_bench import resnet50_counts  # noqa: E402  (sibling module)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast tier-1 mode: trimmed sizes, scaled stream")
    args = ap.parse_args()

    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    import mlsl_tpu as mlsl
    from mlsl_tpu import codecs
    from mlsl_tpu.tuner import calibrate
    from mlsl_tpu.types import CompressionType, OpType

    # --- 1. wire-bytes x codec x size curve -----------------------------
    sizes = (4096, 65536) if args.smoke else (4096, 65536, 1048576, 4194304)
    for n in sizes:
        x = calibrate.gradient_sample(f"bench/{n}", n)
        for name in codecs.names():
            codec = codecs.get(name)
            print(json.dumps({
                "metric": "codec_wire_bytes",
                "codec": name,
                "elems": n,
                "wire_bytes": int(codec.wire_len(n)),
                "f32_bytes": 4 * n,
                "ratio": round(codec.wire_len(n) / (4 * n), 4),
                "nsr": round(calibrate.measure_nsr(codec, x), 6),
            }))

    # --- 2. calibrated vs uniform int8 on the ResNet-50 stream ----------
    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()
    dist = env.create_distribution(world, 1)
    counts = resnet50_counts(scale=16 if args.smoke else 1)
    budget = env.config.codec_nsr_budget

    def build(tune):
        env.config.tune_codec = tune
        env.config.codec_assignment = {}
        s = env.create_session()
        s.set_global_minibatch_size(8)
        reqs = []
        for c in counts:
            r = s.create_operation_reg_info(OpType.CC)
            r.add_output(8, 4)
            r.add_parameter_set(
                c, 1, compression_type=CompressionType.QUANTIZATION
            )
            op = s.get_operation(s.add_operation(r, dist))
            reqs.append(op.get_parameter_set(0).grad_req)
        s.commit()
        env.config.tune_codec = False
        return reqs

    def wire_bytes(reqs):
        # each request pins its per-round compressed image at setup
        # (request._wire_rec — the same figure stats.record_codec_wire
        # accounts per started round)
        return sum(int(r._wire_rec[1]) for r in reqs if r._wire_rec)

    uniform = wire_bytes(build(tune=False))
    calibrated_reqs = build(tune=True)
    calibrated = wire_bytes(calibrated_reqs)
    cells = env.config.codec_assignment
    worst_nsr = max((c["nsr"] for c in cells.values()), default=0.0)
    by_codec: dict = {}
    for r in calibrated_reqs:
        by_codec[r.codec_name] = by_codec.get(r.codec_name, 0) + 1
    print(json.dumps({
        "metric": "codec_lab_calibrated_vs_int8",
        "tensors": len(counts),
        "params": sum(counts),
        "uniform_int8_bytes": uniform,
        "calibrated_bytes": calibrated,
        "saving": round(1.0 - calibrated / max(uniform, 1), 4),
        "nsr_budget": budget,
        "worst_cell_nsr": round(worst_nsr, 6),
        "assignment": by_codec,
    }))
    env.finalize()


if __name__ == "__main__":
    main()
