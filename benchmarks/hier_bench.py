"""Hierarchical-collective benchmark: size x codec x (flat vs hier) algbw on
the synthetic two-tier mesh, with a DCN bandwidth-delay simulator.

The CPU proof mesh has no slow tier — every virtual device shares one
memory bus — so raw wall clock cannot show WHY the two-tier decomposition
wins. This bench separates the two effects:

- **wall_us** is the measured program time (compute + every hop at local
  speed): what the flat-vs-hier schedule itself costs.
- **sim_us** adds the modeled DCN cost of the bytes each lowering puts on
  the slow tier (``--dcn-gbps`` link bandwidth, ``--dcn-lat-us`` per-hop
  latency — the bandwidth-delay knob): flat lowerings carry the FULL
  payload across the tier boundary 2(G-1)/G times (every ring hop crosses
  it), hier carries the 1/L shard at the DCN codec's wire width once per
  tier peer (comm/algos/hier.dcn_wire_bytes). On a real pod the DCN link
  decides; the simulator makes the CPU mesh show the same ordering.

Rows: per (size x lowering) algbw curve, a ResNet-50-shaped gradient-stream
total (the acceptance workload), and the ``hier_vs_flat`` summary ratio =
best flat simulated stream time / hier-int8 simulated stream time.

Usage: python benchmarks/hier_bench.py [--smoke] [--tiers 2x4]
       [--dcn-gbps 6.25] [--dcn-lat-us 50] [--no-dcn-sim]

--smoke trims sizes/iters for the tier-1 wiring (tests/test_hier.py, the
``bench_smoke`` marker); the full grid belongs to capture.py.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

SMOKE_SIZES = (64 * 1024, 1024 * 1024)
FULL_SIZES = (64 * 1024, 512 * 1024, 4 * 1024 * 1024, 16 * 1024 * 1024)


def _time_fn(fn, args, iters):
    import jax

    fn = getattr(fn, "_mlsl_inner", fn)
    for _ in range(2):
        jax.block_until_ready(fn(*args))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--tiers", default="2x4",
                    help="synthetic TxL split (sets MLSL_MESH_TIERS when the "
                         "env var is unset; on real multislice leave both "
                         "alone and slice_index drives the tier map)")
    ap.add_argument("--dcn-gbps", type=float, default=6.25,
                    help="simulated DCN link bandwidth (GB/s); the "
                         "bandwidth half of the bandwidth-delay knob")
    ap.add_argument("--dcn-lat-us", type=float, default=50.0,
                    help="simulated per-DCN-hop latency (us)")
    ap.add_argument("--no-dcn-sim", action="store_true",
                    help="report raw wall time only (real-pod runs, where "
                         "the DCN is physically in the measurement)")
    ap.add_argument("--iters", type=int, default=0)
    ap.add_argument("--block", type=int, default=256)
    args = ap.parse_args()

    if not os.environ.get("MLSL_MESH_TIERS"):
        os.environ["MLSL_MESH_TIERS"] = args.tiers

    from mlsl_tpu import sysinfo

    sysinfo.apply_platform_override()

    import numpy as np
    import jax

    from mlsl_tpu.comm import algos, quant_ring
    from mlsl_tpu.comm.algos import hier
    from mlsl_tpu.comm.mesh import ProcessGroup, Topology, world_tiers
    from mlsl_tpu.types import ReductionType

    devices = tuple(jax.devices())
    n_dev = len(devices)
    if n_dev < 2:
        print(json.dumps({"metric": "hier_vs_flat", "value": None,
                          "reason": "single-device world"}), flush=True)
        return 0
    tiers = world_tiers(devices)
    if tiers is None:
        print(json.dumps({"metric": "hier_vs_flat", "value": None,
                          "reason": "no tier structure"}), flush=True)
        return 0
    t_cnt, l_cnt = tiers
    topo = Topology(n_dev, 1, devices=devices)
    group = ProcessGroup(topo, ("data",))
    iters = args.iters or (3 if args.smoke else 7)
    block = args.block
    sim = not args.no_dcn_sim
    bw = args.dcn_gbps * 1e9
    lat = args.dcn_lat_us * 1e-6

    def buf(elems):
        return topo.shard_buffer(
            np.zeros((*topo.grid_shape, elems), dtype=np.float32)
        )

    def err(el):
        return topo.shard_buffer(
            np.zeros((*topo.grid_shape, el), dtype=np.float32)
        )

    def flat_dcn(elems, codec):
        """Modeled DCN cost (s) of a FLAT lowering: every ring hop crosses
        the tier boundary, so the full 2(G-1)/G payload rides the slow link
        at the codec's wire width."""
        wpe = 4.0 if codec == "none" else 1.0 + 4.0 / block
        return (2 * (n_dev - 1) / n_dev * elems * wpe / bw
                + 2 * (n_dev - 1) * lat)

    def hier_dcn(elems, codec):
        return (hier.dcn_wire_bytes(elems, tiers, codec, block) / bw
                + hier.dcn_phases(tiers, codec if codec != "none" else "f32")
                * lat)

    # -- contenders: (label, codec, build(elems) -> (fn, extra args fn)) ----
    def dense(algo):
        def make(elems):
            fn = algos.build("allreduce", group, np.float32, algo,
                             op=ReductionType.SUM)
            return fn, (buf(elems),)
        return make

    def quant(ring, **kw):
        def make(elems):
            fn, el = quant_ring.build_quantized_collective(
                "allreduce", group, elems, block, ring=ring, **kw
            )
            return fn, (buf(elems), err(el))
        return make

    contenders = [
        ("lax", "flat", "none", dense("lax")),
        ("rhd", "flat", "none", dense("rhd")),
        ("quant_ring", "flat", "int8", quant("lax")),
        ("hier", "hier", "none", dense("hier")),
        # dcn_codec pinned: the row label must mean int8 even when the
        # caller's environment exports MLSL_HIER_DCN_CODEC
        ("hier+int8", "hier", "int8", quant("hier", dcn_codec="int8")),
    ]

    def sim_s(shape, codec, elems, wall):
        if not sim:
            return wall
        dcn = hier_dcn(elems, codec) if shape == "hier" \
            else flat_dcn(elems, codec)
        return wall + dcn

    # -- size curve ---------------------------------------------------------
    sizes = SMOKE_SIZES if args.smoke else FULL_SIZES
    walls = {}  # (label, elems) -> wall seconds
    for size_b in sizes:
        elems = max(-(-(size_b // 4) // n_dev) * n_dev, n_dev)
        for label, shape, codec, make in contenders:
            fn, fargs = make(elems)
            w = _time_fn(fn, fargs, iters)
            walls[(label, elems)] = w
            s = sim_s(shape, codec, elems, w)
            print(json.dumps({
                "metric": "hier_curve",
                "bytes": elems * 4,
                "lowering": label,
                "tiers": f"{t_cnt}x{l_cnt}",
                "wall_us": round(w * 1e6, 1),
                "sim_us": round(s * 1e6, 1),
                "algbw_gbps": round(elems * 4 / s / 1e9, 4),
            }), flush=True)

    # -- ResNet-50-shaped gradient stream (the acceptance workload) ---------
    from benchmarks.quant_bucket_bench import resnet50_counts

    stream = resnet50_counts(scale=16 if args.smoke else 1)
    stream = [max(-(-c // n_dev) * n_dev, n_dev) for c in stream]
    distinct = sorted(set(stream))
    per_size_counts = {c: stream.count(c) for c in distinct}
    totals = {}
    for label, shape, codec, make in contenders:
        total = 0.0
        for elems in distinct:
            fn, fargs = make(elems)
            w = _time_fn(fn, fargs, max(2, iters - 1))
            total += per_size_counts[elems] * sim_s(shape, codec, elems, w)
        totals[label] = total
        print(json.dumps({
            "metric": "hier_resnet50_stream",
            "lowering": label,
            "tensors": len(stream),
            "sim_ms": round(total * 1e3, 3),
        }), flush=True)

    best_flat = min(
        (lbl for lbl, shape, _, _ in contenders if shape == "flat"),
        key=lambda lbl: totals[lbl],
    )
    ratio = totals[best_flat] / totals["hier+int8"]
    print(json.dumps({
        "metric": "hier_vs_flat",
        "value": round(ratio, 4),
        "best_flat": best_flat,
        "tiers": f"{t_cnt}x{l_cnt}",
        "dcn_sim": {"gbps": args.dcn_gbps, "lat_us": args.dcn_lat_us}
        if sim else None,
        "stream_ms": {k: round(v * 1e3, 3) for k, v in totals.items()},
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
