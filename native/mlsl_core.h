/* mlsl_core — native control plane for the TPU MLSL framework.
 *
 * C++ implementation of the framework's DL-semantics bookkeeping, mirroring the
 * role the reference implements in src/mlsl_impl.{hpp,cpp}: process-grid math,
 * activation peer-connection case selection (the five topology cases,
 * reference src/mlsl_impl.cpp:139-241), CommBlockInfo pack/unpack layouts
 * (:243-347), parameter-set partitioning (:388-444), a newest-first priority
 * dispatch queue (the eplib allreduce_pr scheduling capability,
 * eplib/allreduce_pr.c:76-79) and request storage (src/mlsl_impl.hpp:60-94).
 *
 * The XLA data plane (collective execution) stays in Python/JAX; this library
 * is the graph-builder/scheduler control plane, consumed via ctypes
 * (the reference's flat-C + ctypes binding pattern, src/c_bind.cpp +
 * include/mlsl/mlsl.py).
 */

#ifndef MLSL_CORE_H
#define MLSL_CORE_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define MLSL_OK 0
#define MLSL_FAIL -1

/* ---- grid math (reference src/mlsl_impl.hpp:224-266, + seq axis) ---- */

/* global rank -> (replica, data, seq, model); returns MLSL_OK. */
int mlsl_grid_coords(int64_t rank, int64_t data_parts, int64_t seq_parts,
                     int64_t model_parts, int64_t coords[4]);

/* (replica, data, seq, model) -> global rank. */
int64_t mlsl_grid_rank(const int64_t coords[4], int64_t data_parts,
                       int64_t seq_parts, int64_t model_parts);

/* reference color formulas: fills data_color, model_color, replica_color. */
int mlsl_grid_colors(int64_t rank, int64_t data_parts, int64_t model_parts,
                     int64_t* data_color, int64_t* model_color,
                     int64_t* replica_color);

/* ---- activation peer-connection case selection ---- */

/* Returns 1..5 (the case), or 0 if no comm is needed, or MLSL_FAIL if the
 * topology combination is unsupported. Mirrors reference
 * src/mlsl_impl.cpp:139-241 / mlsl_tpu/core/activation.py. */
int mlsl_select_case(int out_need_reduce, int same_dist, int64_t world,
                     int64_t out_data, int64_t out_model,
                     int64_t in_data, int64_t in_model);

/* ---- CommBlockInfo layouts ---- */

typedef struct {
  int64_t mb_offset;
  int64_t mb_count;
  int64_t fm_offset;
  int64_t fm_count;
  int64_t fm_size;
  int64_t buf_offset;
} mlsl_block_t;

/* Fill pack blocks for ReduceScatter (case 1). n_blocks == model_parts. */
int mlsl_blocks_pack_reduce_scatter(int64_t model_parts, int64_t local_mb,
                                    int64_t local_fm, int64_t fm_size,
                                    mlsl_block_t* out);
int mlsl_blocks_pack_reduce_scatter2(int64_t model_parts, int64_t local_mb,
                                     int64_t local_fm, int64_t fm_size,
                                     mlsl_block_t* out);
int mlsl_blocks_unpack_allgather(int64_t model_parts, int64_t local_mb,
                                 int64_t local_fm, int64_t fm_size,
                                 mlsl_block_t* out);
int mlsl_blocks_unpack_allgather2(int64_t model_parts, int64_t local_mb,
                                  int64_t local_fm, int64_t fm_size,
                                  mlsl_block_t* out);
/* AlltoAll block build (reference :313-347). Returns block count or MLSL_FAIL.
 * out may be NULL to query the count. */
int64_t mlsl_blocks_alltoall(int64_t my_local_mb, int64_t my_local_fm,
                             int64_t my_fm_size, int64_t other_local_mb,
                             int64_t other_local_fm, int64_t other_fm_size,
                             mlsl_block_t* out);

/* ---- parameter-set partitioning (reference src/mlsl_impl.cpp:388-444) ---- */

typedef struct {
  int64_t local_kernel_count;  /* possibly padded when distributed_update */
  int64_t owned_kernel_count;
  int64_t need_comm;           /* 0/1 */
} mlsl_param_part_t;

int mlsl_param_partition(int64_t global_kernel_count, int64_t model_parts,
                         int64_t grad_group_size, int distributed_update,
                         mlsl_param_part_t* out);

/* ---- priority dispatch queue ---- */

/* Opaque scheduler. Requests above `threshold` bytes are deferred and flushed
 * newest-first (LIFO) when lifo != 0, FIFO otherwise; submissions at or below
 * the threshold dispatch immediately (return 1). A resubmitted id supersedes
 * its stale queue entry. */
typedef struct mlsl_sched mlsl_sched_t;

mlsl_sched_t* mlsl_sched_create(int64_t threshold, int lifo);
void mlsl_sched_destroy(mlsl_sched_t* s);
/* returns 1 = dispatch now, 0 = deferred */
int mlsl_sched_submit(mlsl_sched_t* s, uint64_t req_id, int64_t bytes);
/* pops the next deferred request to dispatch; returns 0 when empty */
int mlsl_sched_next(mlsl_sched_t* s, uint64_t* req_id);
int64_t mlsl_sched_pending(mlsl_sched_t* s);

/* ---- request storage (reference src/mlsl_impl.hpp:60-94) ---- */

typedef struct mlsl_reqstore mlsl_reqstore_t;

mlsl_reqstore_t* mlsl_reqstore_create(void);
void mlsl_reqstore_destroy(mlsl_reqstore_t* r);
void mlsl_reqstore_register(mlsl_reqstore_t* r, uint64_t req_id);
void mlsl_reqstore_remove(mlsl_reqstore_t* r, uint64_t req_id);
int64_t mlsl_reqstore_size(mlsl_reqstore_t* r);

/* library version for the ctypes loader's sanity check */
const char* mlsl_core_version(void);

#ifdef __cplusplus
}
#endif

#endif /* MLSL_CORE_H */
