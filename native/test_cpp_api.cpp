/* C++-class-API consumer (the analog of the reference's mlsl_test.cpp usage
 * of the include/mlsl.hpp classes). */

#include <cstdio>
#include <vector>

#include "../include/mlsl_tpu.hpp"

int main() {
  using namespace mlsl_tpu;
  try {
    Environment::GetEnv().Init();
    const int64_t world = Environment::GetEnv().GetProcessCount();
    std::printf("world = %lld\n", (long long)world);

    Distribution dist(world, 1);
    const int64_t n = 8;
    std::vector<float> send(world * n), recv(world * n);
    for (int64_t p = 0; p < world; ++p)
      for (int64_t i = 0; i < n; ++i) send[p * n + i] = (float)(p + 1);
    CommReq req =
        dist.AllReduce(send.data(), n, MLSL_DT_FLOAT, MLSL_RT_SUM, MLSL_GT_DATA);
    while (!req.Test()) { /* poll (Test-then-Wait must deliver) */ }
    req.Wait(recv.data(), n, MLSL_DT_FLOAT);
    const float expect = (float)(world * (world + 1) / 2);
    for (int64_t i = 0; i < n; ++i)
      if (recv[i] != expect) {
        std::fprintf(stderr, "FAILED: allreduce %f != %f\n", recv[i], expect);
        return 1;
      }
    std::printf("allreduce OK (%.0f)\n", expect);

    Session sess;
    sess.SetGlobalMinibatchSize(4 * world);
    OperationRegInfo reg = sess.CreateOperationRegInfo(MLSL_OT_CC);
    reg.AddInput(8, 4, MLSL_DT_FLOAT);
    reg.AddOutput(8, 4, MLSL_DT_FLOAT);
    reg.AddParameterSet(32, 1, MLSL_DT_FLOAT);
    Operation op = sess.AddOperation(reg, dist);
    sess.Commit();
    const int64_t cnt = op.GetParameterLocalCount(0);
    std::vector<float> grads(world * cnt, 2.0f), gout(world * cnt);
    op.StartGradientComm(0, grads.data(), MLSL_DT_FLOAT);
    const int64_t got = op.WaitGradientComm(0, gout.data(), MLSL_DT_FLOAT);
    if (world > 1 && (got != cnt || gout[0] != 2.0f * world)) {
      std::fprintf(stderr, "FAILED: grad sync\n");
      return 1;
    }
    dist.Barrier(MLSL_GT_GLOBAL);
    Environment::GetEnv().Finalize();
    std::printf("CPP API TEST PASSED\n");
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "FAILED: %s\n", e.what());
    return 1;
  }
}
