/* C++-class-API consumer (the analog of the reference's mlsl_test.cpp usage
 * of the include/mlsl.hpp classes). */

#include <cstdio>
#include <vector>

#include "../include/mlsl_tpu.hpp"

int main() {
  using namespace mlsl_tpu;
  try {
    Environment::GetEnv().Init();
    const int64_t world = Environment::GetEnv().GetProcessCount();
    std::printf("world = %lld\n", (long long)world);

    Distribution dist(world, 1);
    const int64_t n = 8;
    std::vector<float> send(world * n), recv(world * n);
    for (int64_t p = 0; p < world; ++p)
      for (int64_t i = 0; i < n; ++i) send[p * n + i] = (float)(p + 1);
    CommReq req =
        dist.AllReduce(send.data(), n, MLSL_DT_FLOAT, MLSL_RT_SUM, MLSL_GT_DATA);
    while (!req.Test()) { /* poll (Test-then-Wait must deliver) */ }
    req.Wait(recv.data(), n, MLSL_DT_FLOAT);
    const float expect = (float)(world * (world + 1) / 2);
    for (int64_t i = 0; i < n; ++i)
      if (recv[i] != expect) {
        std::fprintf(stderr, "FAILED: allreduce %f != %f\n", recv[i], expect);
        return 1;
      }
    std::printf("allreduce OK (%.0f)\n", expect);

    Session sess;
    sess.SetGlobalMinibatchSize(4 * world);
    OperationRegInfo reg = sess.CreateOperationRegInfo(MLSL_OT_CC);
    reg.AddInput(8, 4, MLSL_DT_FLOAT);
    reg.AddOutput(8, 4, MLSL_DT_FLOAT);
    reg.AddParameterSet(32, 1, MLSL_DT_FLOAT);
    Operation op = sess.AddOperation(reg, dist);
    sess.Commit();
    const int64_t cnt = op.GetParameterLocalCount(0);
    std::vector<float> grads(world * cnt, 2.0f), gout(world * cnt);
    op.StartGradientComm(0, grads.data(), MLSL_DT_FLOAT);
    const int64_t got = op.WaitGradientComm(0, gout.data(), MLSL_DT_FLOAT);
    if (world > 1 && (got != cnt || gout[0] != 2.0f * world)) {
      std::fprintf(stderr, "FAILED: grad sync\n");
      return 1;
    }
    /* model-parallel edge through the Activation/ParameterSet/Statistics
     * classes (reference mlsl.hpp:210-341,651-726) */
    if (world >= 4 && world % 2 == 0) {
      const int64_t MP = 2, DP = world / 2, FM = 8, FMS = 4;
      Distribution dmp(DP, MP);
      Session s2;
      s2.SetGlobalMinibatchSize(4 * DP);
      OperationRegInfo ra = s2.CreateOperationRegInfo(MLSL_OT_CC);
      ra.AddInput(FM, FMS, MLSL_DT_FLOAT);
      ra.AddOutput(FM, FMS, MLSL_DT_FLOAT);
      ra.AddParameterSet(FM * FM, 1, MLSL_DT_FLOAT, /*dist_update=*/true);
      Operation oa = s2.AddOperation(ra, dmp);
      OperationRegInfo rb = s2.CreateOperationRegInfo(MLSL_OT_CC);
      rb.AddInput(FM, FMS, MLSL_DT_FLOAT);
      rb.AddOutput(FM, FMS, MLSL_DT_FLOAT);
      rb.AddParameterSet(FM * FM, 1, MLSL_DT_FLOAT);
      Operation ob = s2.AddOperation(rb, dmp);
      oa.SetNext(ob, 0, 0);
      s2.Commit();

      Activation out = oa.GetOutput(0);
      Activation in = ob.GetInput(0);
      if (!out.NeedsComm() || out.GetPackBlockCount() != MP) {
        std::fprintf(stderr, "FAILED: activation metadata\n");
        return 1;
      }
      const int64_t wire = out.GetWireCount();
      std::vector<float> wires(world * wire);
      for (int64_t p = 0; p < world; ++p)
        for (int64_t b = 0; b < out.GetPackBlockCount(); ++b) {
          CommBlockInfo bi = out.GetPackBlock(b);
          int64_t k = 0;
          for (int64_t mb = bi.mb_offset; mb < bi.mb_offset + bi.mb_count; ++mb)
            for (int64_t fm = bi.fm_offset; fm < bi.fm_offset + bi.fm_count; ++fm)
              for (int64_t sp = 0; sp < bi.fm_size; ++sp, ++k)
                wires[p * wire + bi.buf_offset + k] =
                    (float)(p * 1000 + (mb * FM + fm) * FMS + sp);
        }
      out.StartComm(wires.data(), MLSL_DT_FLOAT);
      std::vector<float> arecv(world * wire);
      const int64_t rc = in.WaitComm(arecv.data(), MLSL_DT_FLOAT);
      if (rc != wire / MP) {
        std::fprintf(stderr, "FAILED: fwd recv count\n");
        return 1;
      }
      for (int64_t p = 0; p < world; ++p) {
        const int64_t g0 = (p / MP) * MP, m = p % MP;
        for (int64_t i = 0; i < rc; ++i) {
          float want = 0;
          for (int64_t j = 0; j < MP; ++j)
            want += wires[(g0 + j) * wire + m * rc + i];
          if (arecv[p * rc + i] != want) {
            std::fprintf(stderr, "FAILED: fwd activation value\n");
            return 1;
          }
        }
      }
      std::printf("activation exchange OK\n");

      ParameterSet ps = oa.GetParameterSet(0);
      if (!ps.IsDistributedUpdate() ||
          ps.GetOwnedKernelCount() * DP != ps.GetLocalKernelCount()) {
        std::fprintf(stderr, "FAILED: parameter-set metadata\n");
        return 1;
      }
      Statistics st = s2.GetStats();
      if (st.IsEnabled()) {
        if (st.GetTotalCommSize() <= 0) {
          std::fprintf(stderr, "FAILED: stats bytes\n");
          return 1;
        }
        std::printf("stats OK (bytes=%lld)\n",
                    (long long)st.GetTotalCommSize());
      }
    }

    dist.Barrier(MLSL_GT_GLOBAL);
    Environment::GetEnv().Finalize();
    std::printf("CPP API TEST PASSED\n");
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "FAILED: %s\n", e.what());
    return 1;
  }
}
