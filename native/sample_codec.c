/* Sample user compression codec implementing the reference's dlopen contract
 * (signatures: /root/reference/quant/quant.c:57-65; loaded by quant_load
 * :96-133). Used by tests/test_codec.py to exercise the lib_path plug-in path
 * end-to-end, and as a template for user codecs.
 *
 * Codec: float16 truncation. Block geometry: elem_in_block elements per block,
 * block_size = 2 * elem_in_block bytes (the f16 payload). Error feedback: the
 * caller-supplied diff buffer is added before truncation and receives the new
 * residual (dl_comp semantics).
 *
 * Build:  gcc -shared -fPIC -O2 -o libsample_codec.so sample_codec.c
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

typedef _Float16 f16;

/* int quant(src, dst, count, diff, src_data_type, comp_ratio, method) */
int sample_compress(void* src_buffer, void* dst_buffer, size_t count,
                    void* diff, int src_data_type, size_t comp_ratio,
                    int method) {
  (void)src_data_type; (void)comp_ratio; (void)method;
  const float* src = (const float*)src_buffer;
  float* d = (float*)diff;
  f16* dst = (f16*)dst_buffer;
  for (size_t i = 0; i < count; i++) {
    float v = src[i] + (d ? d[i] : 0.0f);
    f16 t = (f16)v;
    dst[i] = t;
    if (d) d[i] = v - (float)t;
  }
  return 0;
}

/* int dequant(src, dst, count) */
int sample_decompress(void* src_buffer, void* dst_buffer, size_t count) {
  const f16* src = (const f16*)src_buffer;
  float* dst = (float*)dst_buffer;
  for (size_t i = 0; i < count; i++) dst[i] = (float)src[i];
  return 0;
}

/* int reduce_sum(in, inout, block_count): accumulate compressed blocks.
 * Element count = block_count * elem_in_block; since both buffers are flat f16
 * payloads the block geometry only fixes the byte span per block, so we derive
 * the element count from the caller's framework contract: blockCount blocks of
 * ELEM elements. ELEM is baked at compile time to keep the ABI exact. */
#ifndef SAMPLE_ELEM_IN_BLOCK
#define SAMPLE_ELEM_IN_BLOCK 128
#endif

int sample_reduce_sum(const void* in_buffer, void* inout_buffer,
                      size_t block_count) {
  const f16* in = (const f16*)in_buffer;
  f16* io = (f16*)inout_buffer;
  size_t n = block_count * SAMPLE_ELEM_IN_BLOCK;
  for (size_t i = 0; i < n; i++) io[i] = (f16)((float)in[i] + (float)io[i]);
  return 0;
}
