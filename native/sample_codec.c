/* Sample user compression codec implementing the reference's dlopen contract
 * (signatures: /root/reference/quant/quant.c:57-65; loaded by quant_load
 * :96-133). Used by tests/test_codec.py to exercise the lib_path plug-in path
 * end-to-end, and as a template for user codecs.
 *
 * Codec: float16 truncation. Block geometry: elem_in_block elements per block,
 * block_size = 2 * elem_in_block bytes (the f16 payload). Error feedback: the
 * caller-supplied diff buffer is added before truncation and receives the new
 * residual (dl_comp semantics).
 *
 * The f16 <-> f32 conversion is done in software (bit manipulation) rather
 * than via the _Float16 C type: _Float16 is an optional target feature gcc
 * rejects on several x86 baselines, and a sample codec must build anywhere
 * `gcc -shared` runs. Round-to-nearest-even, same as hardware conversion.
 *
 * Build:  gcc -shared -fPIC -O2 -o libsample_codec.so sample_codec.c
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

typedef uint16_t f16;

static f16 f32_to_f16(float value) {
  uint32_t x;
  memcpy(&x, &value, sizeof(x));
  uint16_t sign = (uint16_t)((x >> 16) & 0x8000u);
  int32_t exp = (int32_t)((x >> 23) & 0xff) - 127 + 15;
  uint32_t mant = x & 0x7fffffu;
  if (exp >= 0x1f) {               /* overflow / inf / nan */
    if (((x >> 23) & 0xff) == 0xff && mant)
      return (f16)(sign | 0x7e00u); /* nan */
    return (f16)(sign | 0x7c00u);   /* inf */
  }
  if (exp <= 0) {                  /* subnormal or zero */
    if (exp < -10) return sign;
    mant |= 0x800000u;             /* implicit leading 1 */
    uint32_t shift = (uint32_t)(14 - exp);
    uint32_t half = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1u);
    uint32_t mid = 1u << (shift - 1);
    if (rem > mid || (rem == mid && (half & 1u))) half++;
    return (f16)(sign | half);
  }
  uint32_t half = ((uint32_t)exp << 10) | (mant >> 13);
  uint32_t rem = mant & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1u))) half++;
  return (f16)(sign | half);
}

static float f16_to_f32(f16 h) {
  uint32_t sign = (uint32_t)(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1fu;
  uint32_t mant = h & 0x3ffu;
  uint32_t x;
  if (exp == 0x1f) {               /* inf / nan */
    x = sign | 0x7f800000u | (mant << 13);
  } else if (exp == 0) {
    if (mant == 0) {
      x = sign;                    /* zero */
    } else {                       /* subnormal: normalize */
      exp = 127 - 15 + 1;
      while (!(mant & 0x400u)) { mant <<= 1; exp--; }
      mant &= 0x3ffu;
      x = sign | (exp << 23) | (mant << 13);
    }
  } else {
    x = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float out;
  memcpy(&out, &x, sizeof(out));
  return out;
}

/* int quant(src, dst, count, diff, src_data_type, comp_ratio, method) */
int sample_compress(void* src_buffer, void* dst_buffer, size_t count,
                    void* diff, int src_data_type, size_t comp_ratio,
                    int method) {
  (void)src_data_type; (void)comp_ratio; (void)method;
  const float* src = (const float*)src_buffer;
  float* d = (float*)diff;
  f16* dst = (f16*)dst_buffer;
  for (size_t i = 0; i < count; i++) {
    float v = src[i] + (d ? d[i] : 0.0f);
    f16 t = f32_to_f16(v);
    dst[i] = t;
    if (d) d[i] = v - f16_to_f32(t);
  }
  return 0;
}

/* int dequant(src, dst, count) */
int sample_decompress(void* src_buffer, void* dst_buffer, size_t count) {
  const f16* src = (const f16*)src_buffer;
  float* dst = (float*)dst_buffer;
  for (size_t i = 0; i < count; i++) dst[i] = f16_to_f32(src[i]);
  return 0;
}

/* int reduce_sum(in, inout, block_count): accumulate compressed blocks.
 * Element count = block_count * elem_in_block; since both buffers are flat f16
 * payloads the block geometry only fixes the byte span per block, so we derive
 * the element count from the caller's framework contract: blockCount blocks of
 * ELEM elements. ELEM is baked at compile time to keep the ABI exact. */
#ifndef SAMPLE_ELEM_IN_BLOCK
#define SAMPLE_ELEM_IN_BLOCK 128
#endif

int sample_reduce_sum(const void* in_buffer, void* inout_buffer,
                      size_t block_count) {
  const f16* in = (const f16*)in_buffer;
  f16* io = (f16*)inout_buffer;
  size_t n = block_count * SAMPLE_ELEM_IN_BLOCK;
  for (size_t i = 0; i < n; i++)
    io[i] = f32_to_f16(f16_to_f32(in[i]) + f16_to_f32(io[i]));
  return 0;
}
