/* C API implementation: embeds CPython and delegates to mlsl_tpu.c_shim.
 *
 * The reference binds C over its C++ core (src/c_bind.cpp); here the core is
 * Python/JAX, so this translation unit owns the interpreter lifecycle (the
 * inverse binding). Every entry point grabs the GIL, calls one flat shim
 * function, and converts the result — no Python types leak to callers.
 */

#include "../include/mlsl_tpu.h"

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdio>
#include <mutex>

#include <string>

namespace {

PyObject* g_shim = nullptr;
std::once_flag g_init_flag;
bool g_owns_interpreter = false;
std::mutex g_err_mu;
std::string g_last_error;

void record_error_locked_gil() {
  /* Capture the pending Python exception as a string (GIL must be held). */
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    const char* tname = "";
    if (type != nullptr && PyType_Check(type))
      tname = reinterpret_cast<PyTypeObject*>(type)->tp_name;
    const char* text = nullptr;
    if (s != nullptr) text = PyUnicode_AsUTF8(s);
    {
      std::lock_guard<std::mutex> lk(g_err_mu);
      g_last_error = std::string(tname) + ": " +
                     (text != nullptr ? text : "<unprintable error>");
    }
    Py_XDECREF(s);
  }
  PyErr_Restore(type, value, tb);
  PyErr_Print();
}

void interpreter_init() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_owns_interpreter = true;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  g_shim = PyImport_ImportModule("mlsl_tpu.c_shim");
  if (g_shim == nullptr) {
    record_error_locked_gil();  // the most common failure: module not on path
    std::fprintf(stderr,
                 "mlsl_tpu: failed to import mlsl_tpu.c_shim "
                 "(is mlsl_tpu on PYTHONPATH?)\n");
  }
  PyGILState_Release(gil);
  if (g_owns_interpreter) {
    // Py_InitializeEx leaves this thread holding the GIL; release it so other
    // threads' PyGILState_Ensure can acquire (async start/test/wait from
    // multiple threads is the expected usage pattern).
    PyEval_SaveThread();
  }
}

/* Call shim.<name>(args...) where every arg and the result are int64. */
int64_t call_i(const char* name, std::initializer_list<int64_t> args,
               int64_t fail = MLSL_TPU_FAILURE) {
  std::call_once(g_init_flag, interpreter_init);
  if (g_shim == nullptr) return fail;
  PyGILState_STATE gil = PyGILState_Ensure();
  int64_t result = fail;
  PyObject* tuple = PyTuple_New(static_cast<Py_ssize_t>(args.size()));
  if (tuple == nullptr) {
    record_error_locked_gil();
    PyGILState_Release(gil);
    return fail;
  }
  Py_ssize_t i = 0;
  for (int64_t a : args) {
    PyObject* item = PyLong_FromLongLong(a);
    if (item == nullptr) {
      record_error_locked_gil();
      Py_DECREF(tuple);
      PyGILState_Release(gil);
      return fail;
    }
    PyTuple_SET_ITEM(tuple, i++, item);
  }
  PyObject* fn = PyObject_GetAttrString(g_shim, name);
  if (fn != nullptr) {
    PyObject* res = PyObject_CallObject(fn, tuple);
    if (res != nullptr) {
      result = PyLong_AsLongLong(res);
      if (PyErr_Occurred()) {
        record_error_locked_gil();
        result = fail;
      }
      Py_DECREF(res);
    } else {
      record_error_locked_gil();
    }
    Py_DECREF(fn);
  } else {
    record_error_locked_gil();
  }
  Py_DECREF(tuple);
  PyGILState_Release(gil);
  return result;
}

/* shim.dist_collective_start(dist, kind, addr, count, dt, op, root, group) */
mlsl_handle_t collective_start(mlsl_handle_t dist, const char* kind,
                               const void* send, int64_t count, int64_t dt,
                               int64_t op, int64_t root, int64_t group) {
  std::call_once(g_init_flag, interpreter_init);
  if (g_shim == nullptr) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  mlsl_handle_t handle = 0;
  PyObject* res = PyObject_CallMethod(
      g_shim, "dist_collective_start", "LsLLLLLL", (long long)dist, kind,
      (long long)(intptr_t)send, (long long)count, (long long)dt, (long long)op,
      (long long)root, (long long)group);
  if (res != nullptr) {
    handle = (mlsl_handle_t)PyLong_AsUnsignedLongLong(res);
    if (PyErr_Occurred()) {
      record_error_locked_gil();
      handle = 0;
    }
    Py_DECREF(res);
  } else {
    record_error_locked_gil();
  }
  PyGILState_Release(gil);
  return handle;
}

}  // namespace

extern "C" {

int mlsl_environment_init(void) {
  return (int)call_i("env_init", {});
}

int mlsl_environment_finalize(void) {
  return (int)call_i("env_finalize", {});
}

int64_t mlsl_environment_get_process_count(void) {
  return call_i("env_process_count", {});
}

mlsl_handle_t mlsl_environment_create_distribution(int64_t d, int64_t m,
                                                   int64_t s) {
  return (mlsl_handle_t)call_i("env_create_distribution", {d, m, s}, 0);
}

mlsl_handle_t mlsl_environment_create_session(void) {
  return (mlsl_handle_t)call_i("env_create_session", {}, 0);
}

mlsl_handle_t mlsl_environment_create_distribution_with_colors(
    const int64_t* data_colors, const int64_t* model_colors, int64_t n) {
  return (mlsl_handle_t)call_i(
      "env_create_distribution_with_colors",
      {(int64_t)(intptr_t)data_colors, (int64_t)(intptr_t)model_colors, n}, 0);
}

int mlsl_environment_set_quantization_params(
    const char* lib_path, const char* quant_name, const char* dequant_name,
    const char* reduce_name, int64_t block_size, int64_t elem_in_block) {
  std::call_once(g_init_flag, interpreter_init);
  if (g_shim == nullptr) return MLSL_TPU_FAILURE;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = MLSL_TPU_FAILURE;
  /* "z" maps NULL -> None, so absent names reach the core as defaults */
  PyObject* res = PyObject_CallMethod(
      g_shim, "env_set_quantization_params", "zzzzLL", lib_path, quant_name,
      dequant_name, reduce_name, (long long)block_size,
      (long long)elem_in_block);
  if (res != nullptr) {
    rc = (int)PyLong_AsLongLong(res);
    if (PyErr_Occurred()) {
      record_error_locked_gil();
      rc = MLSL_TPU_FAILURE;
    }
    Py_DECREF(res);
  } else {
    record_error_locked_gil();
  }
  PyGILState_Release(gil);
  return rc;
}

int64_t mlsl_distribution_get_process_count(mlsl_handle_t dist,
                                            mlsl_group_type_t group) {
  return call_i("dist_process_count", {(int64_t)dist, (int64_t)group});
}

int64_t mlsl_distribution_get_process_idx(mlsl_handle_t dist,
                                          mlsl_group_type_t group,
                                          int64_t global_idx) {
  return call_i("dist_process_idx", {(int64_t)dist, (int64_t)group, global_idx});
}

mlsl_handle_t mlsl_distribution_all_reduce(mlsl_handle_t dist, const void* send,
                                           int64_t count, mlsl_data_type_t dt,
                                           mlsl_reduction_t op,
                                           mlsl_group_type_t group) {
  return collective_start(dist, "allreduce", send, count, dt, op, 0, group);
}

mlsl_handle_t mlsl_distribution_bcast(mlsl_handle_t dist, const void* send,
                                      int64_t count, mlsl_data_type_t dt,
                                      int64_t root, mlsl_group_type_t group) {
  return collective_start(dist, "bcast", send, count, dt, 0, root, group);
}

mlsl_handle_t mlsl_distribution_all_gather(mlsl_handle_t dist, const void* send,
                                           int64_t send_count,
                                           mlsl_data_type_t dt,
                                           mlsl_group_type_t group) {
  return collective_start(dist, "allgather", send, send_count, dt, 0, 0, group);
}

mlsl_handle_t mlsl_distribution_reduce_scatter(
    mlsl_handle_t dist, const void* send, int64_t send_count,
    mlsl_data_type_t dt, mlsl_reduction_t op, mlsl_group_type_t group) {
  return collective_start(dist, "reduce_scatter", send, send_count, dt, op, 0,
                          group);
}

mlsl_handle_t mlsl_distribution_all_to_all(mlsl_handle_t dist, const void* send,
                                           int64_t send_count,
                                           mlsl_data_type_t dt,
                                           mlsl_group_type_t group) {
  return collective_start(dist, "alltoall", send, send_count, dt, 0, 0, group);
}

mlsl_handle_t mlsl_distribution_reduce(mlsl_handle_t dist, const void* send,
                                       int64_t count, mlsl_data_type_t dt,
                                       mlsl_reduction_t op, int64_t root,
                                       mlsl_group_type_t group) {
  return collective_start(dist, "reduce", send, count, dt, op, root, group);
}

mlsl_handle_t mlsl_distribution_gather(mlsl_handle_t dist, const void* send,
                                       int64_t send_count, mlsl_data_type_t dt,
                                       int64_t root, mlsl_group_type_t group) {
  return collective_start(dist, "gather", send, send_count, dt, 0, root, group);
}

mlsl_handle_t mlsl_distribution_scatter(mlsl_handle_t dist, const void* send,
                                        int64_t send_count, mlsl_data_type_t dt,
                                        int64_t root, mlsl_group_type_t group) {
  return collective_start(dist, "scatter", send, send_count, dt, 0, root, group);
}

mlsl_handle_t mlsl_distribution_send_recv_list(mlsl_handle_t dist,
                                               const void* send, int64_t count,
                                               mlsl_data_type_t dt,
                                               const int64_t* pairs,
                                               int64_t n_pairs,
                                               mlsl_group_type_t group) {
  return (mlsl_handle_t)call_i(
      "dist_send_recv_list",
      {(int64_t)dist, (int64_t)(intptr_t)send, count, (int64_t)dt,
       (int64_t)(intptr_t)pairs, n_pairs, (int64_t)group},
      0);
}

int mlsl_distribution_barrier(mlsl_handle_t dist, mlsl_group_type_t group) {
  return (int)call_i("dist_barrier", {(int64_t)dist, (int64_t)group});
}

int mlsl_request_wait(mlsl_handle_t req, void* recv, int64_t recv_count,
                      mlsl_data_type_t dt) {
  return (int)call_i("request_wait",
                     {(int64_t)req, (int64_t)(intptr_t)recv, recv_count,
                      (int64_t)dt});
}

int mlsl_request_test(mlsl_handle_t req) {
  return (int)call_i("request_test", {(int64_t)req});
}

int mlsl_session_set_global_minibatch_size(mlsl_handle_t sess, int64_t size) {
  return (int)call_i("session_set_minibatch", {(int64_t)sess, size});
}

mlsl_handle_t mlsl_session_create_operation_reg_info(mlsl_handle_t sess,
                                                     mlsl_op_type_t op_type) {
  return (mlsl_handle_t)call_i("session_create_reginfo",
                               {(int64_t)sess, (int64_t)op_type}, 0);
}

int64_t mlsl_operation_reg_info_add_input(mlsl_handle_t reg, int64_t count,
                                          int64_t size, mlsl_data_type_t dt) {
  return call_i("reginfo_add_input", {(int64_t)reg, count, size, (int64_t)dt});
}

int64_t mlsl_operation_reg_info_add_output(mlsl_handle_t reg, int64_t count,
                                           int64_t size, mlsl_data_type_t dt) {
  return call_i("reginfo_add_output", {(int64_t)reg, count, size, (int64_t)dt});
}

int64_t mlsl_operation_reg_info_add_parameter_set(
    mlsl_handle_t reg, int64_t kernel_count, int64_t kernel_size,
    mlsl_data_type_t dt, int dist_update, mlsl_compression_t comp) {
  return call_i("reginfo_add_parameter_set",
                {(int64_t)reg, kernel_count, kernel_size, (int64_t)dt,
                 (int64_t)dist_update, (int64_t)comp});
}

mlsl_handle_t mlsl_session_add_operation(mlsl_handle_t sess, mlsl_handle_t reg,
                                         mlsl_handle_t dist) {
  return (mlsl_handle_t)call_i(
      "session_add_operation", {(int64_t)sess, (int64_t)reg, (int64_t)dist}, 0);
}

int mlsl_session_commit(mlsl_handle_t sess) {
  return (int)call_i("session_commit", {(int64_t)sess});
}

int mlsl_operation_set_next(mlsl_handle_t op, mlsl_handle_t next,
                            int64_t out_idx, int64_t in_idx) {
  return (int)call_i("operation_set_next",
                     {(int64_t)op, (int64_t)next, out_idx, in_idx});
}

int mlsl_operation_set_prev(mlsl_handle_t op, mlsl_handle_t prev,
                            int64_t in_idx, int64_t prev_out_idx) {
  return (int)call_i("operation_set_prev",
                     {(int64_t)op, (int64_t)prev, in_idx, prev_out_idx});
}

int64_t mlsl_operation_get_local_minibatch_size(mlsl_handle_t op) {
  return call_i("operation_local_minibatch", {(int64_t)op});
}

int64_t mlsl_operation_get_global_minibatch_size(mlsl_handle_t op) {
  return call_i("operation_global_minibatch", {(int64_t)op});
}

int64_t mlsl_operation_get_parameter_local_count(mlsl_handle_t op,
                                                 int64_t idx) {
  return call_i("operation_param_local_count", {(int64_t)op, idx});
}

int64_t mlsl_operation_get_parameter_owned_count(mlsl_handle_t op,
                                                 int64_t idx) {
  return call_i("operation_param_owned_count", {(int64_t)op, idx});
}

mlsl_handle_t mlsl_distribution_all_gatherv(mlsl_handle_t dist,
                                            const void* send,
                                            int64_t send_count,
                                            const int64_t* recv_counts,
                                            mlsl_data_type_t dt,
                                            mlsl_group_type_t group) {
  return (mlsl_handle_t)call_i(
      "dist_all_gatherv",
      {(int64_t)dist, (int64_t)(intptr_t)send, send_count,
       (int64_t)(intptr_t)recv_counts, (int64_t)dt, (int64_t)group},
      0);
}

mlsl_handle_t mlsl_distribution_all_to_allv(mlsl_handle_t dist,
                                            const void* send, int64_t send_len,
                                            const int64_t* send_counts,
                                            const int64_t* send_offsets,
                                            const int64_t* recv_offsets,
                                            mlsl_data_type_t dt,
                                            mlsl_group_type_t group) {
  return (mlsl_handle_t)call_i(
      "dist_all_to_allv",
      {(int64_t)dist, (int64_t)(intptr_t)send, send_len,
       (int64_t)(intptr_t)send_counts, (int64_t)(intptr_t)send_offsets,
       (int64_t)(intptr_t)recv_offsets, (int64_t)dt, (int64_t)group},
      0);
}

mlsl_handle_t mlsl_distribution_all_to_allv_full(
    mlsl_handle_t dist, const void* send, int64_t send_len,
    const int64_t* send_counts, const int64_t* send_offsets,
    const int64_t* recv_counts, const int64_t* recv_offsets,
    mlsl_data_type_t dt, mlsl_group_type_t group) {
  return (mlsl_handle_t)call_i(
      "dist_all_to_allv_full",
      {(int64_t)dist, (int64_t)(intptr_t)send, send_len,
       (int64_t)(intptr_t)send_counts, (int64_t)(intptr_t)send_offsets,
       (int64_t)(intptr_t)recv_counts, (int64_t)(intptr_t)recv_offsets,
       (int64_t)dt, (int64_t)group},
      0);
}

int64_t mlsl_operation_get_input_count(mlsl_handle_t op) {
  return call_i("operation_input_count", {(int64_t)op});
}

int64_t mlsl_operation_get_output_count(mlsl_handle_t op) {
  return call_i("operation_output_count", {(int64_t)op});
}

mlsl_handle_t mlsl_operation_get_input(mlsl_handle_t op, int64_t idx) {
  return (mlsl_handle_t)call_i("operation_get_input", {(int64_t)op, idx}, 0);
}

mlsl_handle_t mlsl_operation_get_output(mlsl_handle_t op, int64_t idx) {
  return (mlsl_handle_t)call_i("operation_get_output", {(int64_t)op, idx}, 0);
}

int64_t mlsl_activation_get_global_fm_count(mlsl_handle_t act) {
  return call_i("activation_query", {(int64_t)act, 0});
}

int64_t mlsl_activation_get_local_fm_count(mlsl_handle_t act) {
  return call_i("activation_query", {(int64_t)act, 1});
}

int64_t mlsl_activation_get_fm_size(mlsl_handle_t act) {
  return call_i("activation_query", {(int64_t)act, 2});
}

int64_t mlsl_activation_get_global_fm_offset(mlsl_handle_t act,
                                             int64_t model_idx) {
  return call_i("activation_fm_offset", {(int64_t)act, model_idx});
}

int mlsl_activation_needs_comm(mlsl_handle_t act) {
  return (int)call_i("activation_query", {(int64_t)act, 6});
}

int64_t mlsl_activation_get_wire_count(mlsl_handle_t act) {
  return call_i("activation_query", {(int64_t)act, 7});
}

int64_t mlsl_activation_get_recv_count(mlsl_handle_t act) {
  return call_i("activation_query", {(int64_t)act, 8});
}

int64_t mlsl_activation_get_pack_block_count(mlsl_handle_t act) {
  return call_i("activation_query", {(int64_t)act, 3});
}

int64_t mlsl_activation_get_unpack_block_count(mlsl_handle_t act) {
  return call_i("activation_query", {(int64_t)act, 4});
}

int64_t mlsl_activation_get_pack_block(mlsl_handle_t act, int64_t idx,
                                       int field) {
  return call_i("activation_block_query", {(int64_t)act, 0, idx, (int64_t)field});
}

int64_t mlsl_activation_get_unpack_block(mlsl_handle_t act, int64_t idx,
                                         int field) {
  return call_i("activation_block_query", {(int64_t)act, 1, idx, (int64_t)field});
}

int mlsl_activation_start_comm(mlsl_handle_t act, const void* buf,
                               mlsl_data_type_t dt) {
  return (int)call_i("activation_start_comm",
                     {(int64_t)act, (int64_t)(intptr_t)buf, (int64_t)dt});
}

int64_t mlsl_activation_wait_comm(mlsl_handle_t act, void* recv,
                                  mlsl_data_type_t dt) {
  return call_i("activation_wait_comm",
                {(int64_t)act, (int64_t)(intptr_t)recv, (int64_t)dt});
}

int mlsl_parameter_set_test_gradient_comm(mlsl_handle_t op, int64_t ps_idx) {
  return (int)call_i("param_test_gradient_comm", {(int64_t)op, ps_idx});
}

int mlsl_parameter_set_start_increment_comm(mlsl_handle_t op, int64_t ps_idx,
                                            const void* incs,
                                            mlsl_data_type_t dt) {
  return (int)call_i(
      "param_start_increment_comm",
      {(int64_t)op, ps_idx, (int64_t)(intptr_t)incs, (int64_t)dt});
}

int64_t mlsl_parameter_set_wait_increment_comm(mlsl_handle_t op, int64_t ps_idx,
                                               void* recv,
                                               mlsl_data_type_t dt) {
  return call_i("param_wait_increment_comm",
                {(int64_t)op, ps_idx, (int64_t)(intptr_t)recv, (int64_t)dt});
}

int64_t mlsl_parameter_set_get_global_kernel_count(mlsl_handle_t op,
                                                   int64_t ps_idx) {
  return call_i("param_query", {(int64_t)op, ps_idx, 0});
}

int64_t mlsl_parameter_set_get_local_kernel_count(mlsl_handle_t op,
                                                  int64_t ps_idx) {
  return call_i("param_query", {(int64_t)op, ps_idx, 1});
}

int64_t mlsl_parameter_set_get_owned_kernel_count(mlsl_handle_t op,
                                                  int64_t ps_idx) {
  return call_i("param_query", {(int64_t)op, ps_idx, 2});
}

int64_t mlsl_parameter_set_get_owned_kernel_offset(mlsl_handle_t op,
                                                   int64_t ps_idx,
                                                   int64_t data_idx) {
  return call_i("param_owned_offset", {(int64_t)op, ps_idx, data_idx});
}

int64_t mlsl_parameter_set_get_kernel_size(mlsl_handle_t op, int64_t ps_idx) {
  return call_i("param_query", {(int64_t)op, ps_idx, 3});
}

int mlsl_parameter_set_is_distributed_update(mlsl_handle_t op, int64_t ps_idx) {
  return (int)call_i("param_query", {(int64_t)op, ps_idx, 4});
}

mlsl_handle_t mlsl_session_get_stats(mlsl_handle_t sess) {
  return (mlsl_handle_t)call_i("session_get_stats", {(int64_t)sess}, 0);
}

int mlsl_statistics_start(mlsl_handle_t stats) {
  return (int)call_i("stats_control", {(int64_t)stats, 0});
}

int mlsl_statistics_stop(mlsl_handle_t stats) {
  return (int)call_i("stats_control", {(int64_t)stats, 1});
}

int mlsl_statistics_reset(mlsl_handle_t stats) {
  return (int)call_i("stats_control", {(int64_t)stats, 2});
}

int mlsl_statistics_is_enabled(mlsl_handle_t stats) {
  return (int)call_i("stats_control", {(int64_t)stats, 3});
}

int mlsl_statistics_is_started(mlsl_handle_t stats) {
  return (int)call_i("stats_control", {(int64_t)stats, 4});
}

int64_t mlsl_statistics_get_comm_size(mlsl_handle_t stats, int64_t op_idx) {
  return call_i("stats_query", {(int64_t)stats, 0, op_idx});
}

int64_t mlsl_statistics_get_comm_cycles(mlsl_handle_t stats, int64_t op_idx) {
  return call_i("stats_query", {(int64_t)stats, 1, op_idx});
}

int64_t mlsl_statistics_get_compute_cycles(mlsl_handle_t stats,
                                           int64_t op_idx) {
  return call_i("stats_query", {(int64_t)stats, 2, op_idx});
}

int64_t mlsl_statistics_get_isolation_comm_cycles(mlsl_handle_t stats,
                                                  int64_t op_idx) {
  return call_i("stats_query", {(int64_t)stats, 3, op_idx});
}

int64_t mlsl_statistics_get_total_comm_size(mlsl_handle_t stats) {
  return call_i("stats_query", {(int64_t)stats, 0, -1});
}

int64_t mlsl_statistics_get_total_comm_cycles(mlsl_handle_t stats) {
  return call_i("stats_query", {(int64_t)stats, 1, -1});
}

int64_t mlsl_statistics_get_total_compute_cycles(mlsl_handle_t stats) {
  return call_i("stats_query", {(int64_t)stats, 2, -1});
}

int64_t mlsl_statistics_get_total_isolation_comm_cycles(mlsl_handle_t stats) {
  return call_i("stats_query", {(int64_t)stats, 3, -1});
}

int64_t mlsl_statistics_get_overlap_permille(mlsl_handle_t stats,
                                              int64_t op_idx) {
  return call_i("stats_query", {(int64_t)stats, 4, op_idx}, -1);
}

int mlsl_statistics_print(mlsl_handle_t stats) {
  return (int)call_i("stats_print", {(int64_t)stats});
}

int mlsl_parameter_set_start_gradient_comm(mlsl_handle_t op, int64_t ps_idx,
                                           const void* grads,
                                           mlsl_data_type_t dt) {
  return (int)call_i(
      "param_start_gradient_comm",
      {(int64_t)op, ps_idx, (int64_t)(intptr_t)grads, (int64_t)dt});
}

int64_t mlsl_parameter_set_wait_gradient_comm(mlsl_handle_t op, int64_t ps_idx,
                                              void* recv, mlsl_data_type_t dt) {
  return call_i("param_wait_gradient_comm",
                {(int64_t)op, ps_idx, (int64_t)(intptr_t)recv, (int64_t)dt});
}

int mlsl_handle_release(mlsl_handle_t h) {
  return (int)call_i("handle_release", {(int64_t)h});
}

const char* mlsl_get_last_error(void) {
  // Copy under the lock into a thread-local so the returned pointer stays
  // valid for this thread even if another thread's failure reassigns the
  // shared string concurrently.
  static thread_local std::string tl_copy;
  {
    std::lock_guard<std::mutex> lk(g_err_mu);
    tl_copy = g_last_error;
  }
  return tl_copy.c_str();
}

}  /* extern "C" */
