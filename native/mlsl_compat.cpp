/* mlsl_compat.cpp — MLSL-compatible rank-thread runtime (include/mlsl.hpp).
 *
 * Bridges the reference's per-rank MPI programming model (reference
 * include/mlsl.hpp:82-913; one OS process per rank, rank-local void* buffers)
 * onto the single-controller SPMD core, through the flat C API
 * (include/mlsl_tpu.h). Each "rank" is a thread; every communication call
 * rendezvouses the rank threads, the LAST arrival assembles the rank-local
 * buffers into one (world, count) staging buffer and issues the collective
 * once, and each rank receives a pointer to its slice of the result. Graph
 * construction calls (CreateDistribution, AddOperation, Commit, ...) execute
 * exactly once per matched call site via the same rendezvous.
 *
 * Semantics preserved from the reference:
 *  - in-place Bcast (Environment::Wait writes the result back into the
 *    caller's buffer);
 *  - Activation::WaitComm waits the PEER's transfer and returns a wire-buffer
 *    pointer (reference src/mlsl_impl.cpp:377-380);
 *  - ParameterSet::StartIncrementComm takes the FULL local parameter buffer
 *    and gathers each data rank's owned shard back into it in place
 *    (reference usage tests/examples/mlsl_test/mlsl_test.cpp:526);
 *  - Wait with nothing started returns NULL (empty-request no-op).
 *
 * Constraint inherited from SPMD: all ranks must issue collective and
 * construction calls congruently (same order) — the same requirement MPI
 * collectives impose. Result pointers returned by a Wait are valid until the
 * same entity's next-but-one Start (double-buffered rounds).
 *
 * This layer is a compatibility surface, not the hot path: per-call staging
 * copies are the cost of exact rank-local pointer semantics. Performance
 * work lives in the Python/JAX core.
 */

#include "../include/mlsl.hpp"
#include "../include/mlsl_tpu.h"

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <mutex>
#include <stdexcept>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace MLSL {
namespace {

int g_world = 0;
thread_local int tl_rank = -1;

size_t dt_size(int dt) { return dt == DT_DOUBLE ? 8 : dt == DT_BYTE ? 1 : 4; }

[[noreturn]] void die(const std::string& msg) {
  std::fprintf(stderr, "mlsl_compat: %s (last error: %s)\n", msg.c_str(),
               mlsl_get_last_error());
  std::abort();
}

/* ---- shared_call: execute fn exactly once across the world ------------- */

/* Rendezvous watchdog limit (MLSL_COMPAT_WATCHDOG_S, default 180, 0
 * disables): shared by the construction-phase slots below and the comm
 * channels — a ported program whose ranks diverge from congruent call order
 * must die with a diagnostic, not hang (the reference dies loudly via MPI). */
long watchdog_secs() {
  static const long v = [] {
    const char* e = std::getenv("MLSL_COMPAT_WATCHDOG_S");
    return e != nullptr ? std::atol(e) : 180L;
  }();
  return v;
}

/* cv-wait with the divergence watchdog, shared by the construction-phase
 * slots and the AlltoAllv count gather: waits for pred (caller holds lk),
 * dying with diag() on timeout. The comm channels use watched_wait below,
 * which adds bounded re-arming for slow-but-progressing collectives. */
template <typename Pred>
void watched_slot_wait(std::unique_lock<std::mutex>& lk,
                       std::condition_variable& cv, Pred pred,
                       const std::function<std::string()>& diag) {
  const long limit = watchdog_secs();
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::seconds(limit > 0 ? limit : 0);
  while (!pred()) {
    if (limit <= 0) {
      cv.wait(lk);
      continue;
    }
    if (cv.wait_until(lk, deadline) == std::cv_status::timeout && !pred())
      die(diag());
  }
}

struct SharedSlot {
  std::mutex mu;
  std::condition_variable cv;
  int arrived = 0;
  bool done = false;
  uint64_t result = 0;
};

std::deque<SharedSlot> g_slots;
std::mutex g_slots_mu;
thread_local size_t tl_shared_seq = 0;

SharedSlot& slot_at(size_t i) {
  std::lock_guard<std::mutex> lk(g_slots_mu);
  while (g_slots.size() <= i) g_slots.emplace_back();
  return g_slots[i];
}

/* All ranks arrive (in matched program order); the last arrival runs fn; all
 * ranks observe the result. Construction-phase rendezvous. */
uint64_t shared_call(const std::function<uint64_t()>& fn) {
  size_t idx = tl_shared_seq++;
  SharedSlot& s = slot_at(idx);
  std::unique_lock<std::mutex> lk(s.mu);
  s.arrived++;
  if (s.arrived == g_world) {
    s.result = fn();
    s.done = true;
    s.cv.notify_all();
  } else {
    // A slow fn cannot false-abort here: the last arriver executes fn while
    // holding s.mu, so an expired waiter stays blocked on mutex
    // reacquisition until fn returns — at which point s.done is true and
    // the loop exits. A timeout observed with the lock held therefore
    // means ranks genuinely diverged (arrived < world).
    watched_slot_wait(lk, s.cv, [&] { return s.done; }, [&] {
      return "rendezvous watchdog: rank " + std::to_string(tl_rank) +
             " stuck in construction-phase call #" + std::to_string(idx) +
             " (arrived=" + std::to_string(s.arrived) + "/" +
             std::to_string(g_world) +
             ") — ranks issued API calls in divergent order";
    });
  }
  return s.result;
}

/* ---- Channel: one comm entity's rendezvous + round state --------------- */

struct DistImpl;
std::atomic<uint64_t> g_channel_ids{1};

/* Live-channel registry BY ID: one-shot channels are deleted once every rank
 * consumed them, but the CommReq handles handed to callers (GenReq below)
 * outlive them and must be able to tell "my channel is gone" apart from a
 * recycled allocation at the same address (ABA). Ids are monotonic and never
 * reused. Channels register on construction, deregister on destruction. */
std::unordered_map<uint64_t, struct Channel*> g_live_by_id;
std::mutex g_live_mu;

Channel* channel_by_id(uint64_t id) {
  std::lock_guard<std::mutex> lk(g_live_mu);
  auto it = g_live_by_id.find(id);
  return it == g_live_by_id.end() ? nullptr : it->second;
}

struct Channel {
  Channel() {
    std::lock_guard<std::mutex> lk(g_live_mu);
    g_live_by_id.emplace(id, this);
  }
  ~Channel() {
    std::lock_guard<std::mutex> lk(g_live_mu);
    g_live_by_id.erase(id);
  }
  const uint64_t id = g_channel_ids.fetch_add(1);  // stable key across reuse
  std::mutex mu;
  std::condition_variable cv;
  int arrived = 0;
  long dispatched_rounds = 0;
  long completed_rounds = 0;
  bool waiting = false;  // one thread at a time executes the global wait
  /* one-shot (generic collective) channels are reclaimed (deleted) after
   * every rank consumed their single round — a training loop issuing
   * Distribution collectives per step must not accumulate channels. A second
   * Environment::Wait/Test on the completed CommReq* (a legal MPI no-op) is
   * made safe by the live-channel registry above, not by keeping the
   * object. */
  bool one_shot = false;
  int consumed = 0;
  DistImpl* owner = nullptr;
  long seq = -1;
  /* per-rank Start/Wait counts for the rendezvous watchdog's diagnostic */
  std::vector<long> started_by, waited_by;

  /* recv/user state is round-parity double-buffered: the FIRST depositor of
   * round N+1 resets slot (N+1)&1 while a lagging rank may still be reading
   * round N's slot N&1 inside channel_wait — single-buffered state would be
   * clobbered under it. Round N+2 cannot start before every rank finished
   * waiting round N (each rank's deposits are ordered after its waits), so
   * two slots suffice. */
  std::vector<char> send_buf;            // (world, send_elems) staging
  std::vector<char> recv_buf[2];         // round-parity double buffer
  int64_t recv_n[2] = {0, 0};            // per-rank elems actually received
  std::vector<void*> user_ptr[2];        // per-rank in-place write-back target
  std::vector<int64_t> user_cap[2];      // per-rank write-back cap (-1 = all):
                                         // ragged v-collectives stage padded
                                         // rows but must not overrun an
                                         // MPI-sized user buffer
  /* per-rank custom write-back (user_ptr, staging slice): offset-mode
   * v-collectives copy only their valid blocks, leaving the gap bytes MPI
   * guarantees untouched */
  std::vector<std::function<void(void*, const char*)>> user_wb[2];
  uint64_t c_req = 0;                    // generic request handle (if any)
  size_t esize = 4;

  std::function<void(const void*)> start_fn;  // issues the global collective
  std::function<int64_t(void*)> wait_fn;      // completes it; returns per-rank n
};

struct TLCounts {
  long started = 0;
  long waited = 0;
};
/* keyed by channel id, not pointer: a reclaimed channel's address can be
 * reused, and stale counts under the old pointer would corrupt round math */
thread_local std::unordered_map<uint64_t, TLCounts> tl_counts;

void reclaim_one_shot(Channel& ch);  // defined after DistImpl

/* On timeout in a channel rendezvous, abort with per-rank Start/Wait counts
 * so the diverging rank is identifiable. */
[[noreturn]] void watchdog_abort(Channel& ch, const char* where, long round) {
  std::string msg = "rendezvous watchdog: rank " + std::to_string(tl_rank) +
                    " stuck in " + where + " on channel " +
                    std::to_string(ch.id) + " round " + std::to_string(round) +
                    " (arrived=" + std::to_string(ch.arrived) + "/" +
                    std::to_string(g_world) +
                    ", dispatched=" + std::to_string(ch.dispatched_rounds) +
                    ", completed=" + std::to_string(ch.completed_rounds) +
                    "; per-rank started/waited:";
  for (int r = 0; r < g_world; r++) {
    long s = r < (int)ch.started_by.size() ? ch.started_by[r] : 0;
    long w = r < (int)ch.waited_by.size() ? ch.waited_by[r] : 0;
    msg += " " + std::to_string(r) + ":" + std::to_string(s) + "/" +
           std::to_string(w);
  }
  msg += ") — ranks issued collectives in divergent order";
  die(msg);
}

/* cv.wait with the watchdog: caller holds lk; pred checked under the lock. */
template <typename Pred>
void watched_wait(Channel& ch, std::unique_lock<std::mutex>& lk,
                  const char* where, long round, Pred pred,
                  const std::function<bool()>& progress = nullptr) {
  const long limit = watchdog_secs();
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::seconds(limit > 0 ? limit : 0);
  // A collective actively executing (progress() true) is slow, not divergent
  // — an MPI program would block there too — so the deadline re-arms. But the
  // re-arm is BOUNDED: a wait_fn that never returns (dead peer, hung
  // transport) must still abort with diagnostics rather than hang the job
  // silently forever.
  const int max_rearms = 10;
  int rearms = 0;
  while (!pred()) {
    if (limit <= 0) {
      ch.cv.wait(lk);
      continue;
    }
    if (ch.cv.wait_until(lk, deadline) == std::cv_status::timeout && !pred()) {
      if (progress && progress() && rearms < max_rearms) {
        rearms++;
        std::fprintf(stderr,
                     "mlsl compat: rank %d: %s round %ld still executing "
                     "after %lds; watchdog re-armed (%d/%d)\n",
                     tl_rank, where, round, (long)rearms * limit, rearms,
                     max_rearms);
        deadline = std::chrono::steady_clock::now() +
                   std::chrono::seconds(limit);
        continue;
      }
      watchdog_abort(ch, where, round);
    }
  }
}

/* Deposit this rank's send data (src may be null: no payload, e.g. non-root
 * scatter) and this rank's write-back pointer; the last depositor issues the
 * collective. recv_elems sizes the result staging buffer (upper bound).
 * src_elems (default: elems) is how many elements THIS rank actually copies
 * into its (world, elems) staging slot — v-collectives deposit ragged counts
 * into uniform slots. */
void channel_start(Channel& ch, const void* src, size_t elems,
                   size_t esize, int64_t recv_elems, void* user_ptr,
                   std::function<void(const void*)> start_fn,
                   std::function<int64_t(void*)> wait_fn,
                   int64_t src_elems = -1, int64_t user_elems = -1,
                   std::function<void(void*, const char*)> writer = nullptr) {
  TLCounts& tl = tl_counts[ch.id];
  std::unique_lock<std::mutex> lk(ch.mu);
  long round = tl.started;
  tl.started++;
  if (ch.started_by.empty()) ch.started_by.assign(g_world, 0);
  if (ch.waited_by.empty()) ch.waited_by.assign(g_world, 0);
  ch.started_by[tl_rank] = tl.started;
  if (ch.arrived == 0) {
    ch.send_buf.assign((size_t)g_world * elems * esize, 0);
    ch.user_ptr[round & 1].assign(g_world, nullptr);
    ch.user_cap[round & 1].assign(g_world, -1);
    ch.user_wb[round & 1].assign(g_world, nullptr);
    ch.esize = esize;
    ch.start_fn = std::move(start_fn);
    ch.wait_fn = std::move(wait_fn);
    ch.recv_buf[round & 1].assign(
        (size_t)g_world * (recv_elems > 0 ? (size_t)recv_elems : 1) * esize, 0);
  }
  size_t copy_elems = src_elems >= 0 ? (size_t)src_elems : elems;
  if (src != nullptr && copy_elems > 0)
    std::memcpy(ch.send_buf.data() + (size_t)tl_rank * elems * esize, src,
                copy_elems * esize);
  ch.user_ptr[round & 1][tl_rank] = user_ptr;
  ch.user_cap[round & 1][tl_rank] = user_elems;
  ch.user_wb[round & 1][tl_rank] = std::move(writer);
  ch.arrived++;
  if (ch.arrived == g_world) {
    ch.arrived = 0;
    ch.start_fn(ch.send_buf.data());
    ch.dispatched_rounds = round + 1;
    ch.cv.notify_all();
  } else {
    watched_wait(ch, lk, "Start (waiting for all ranks to arrive)", round,
                 [&] { return ch.dispatched_rounds > round; });
  }
}

/* Complete this rank's oldest outstanding round. Returns the rank's slice (or
 * the registered user pointer after in-place write-back); null when nothing
 * is pending or the collective produced nothing (no-comm degenerate group). */
void* channel_wait(Channel& ch) {
  TLCounts& tl = tl_counts[ch.id];
  if (tl.waited == tl.started) return nullptr;  // nothing pending on this rank
  long round = tl.waited;
  tl.waited++;
  std::unique_lock<std::mutex> lk(ch.mu);
  if (!ch.waited_by.empty()) ch.waited_by[tl_rank] = tl.waited;
  while (ch.completed_rounds <= round) {
    if (!ch.waiting) {
      ch.waiting = true;
      std::function<int64_t(void*)> wfn = ch.wait_fn;
      char* dst = ch.recv_buf[round & 1].data();
      lk.unlock();
      int64_t n = wfn(dst);
      lk.lock();
      ch.recv_n[round & 1] = n;
      ch.completed_rounds = round + 1;
      ch.waiting = false;
      ch.cv.notify_all();
    } else {
      // another rank's thread is executing the global wait. While it is
      // actively inside wait_fn that is progress (a slow collective), so the
      // watchdog re-arms; if that thread is itself stuck in a rendezvous its
      // own watchdog catches the divergence.
      watched_wait(ch, lk, "Wait (waiting for round completion)", round,
                   [&] { return ch.completed_rounds > round || !ch.waiting; },
                   [&] { return ch.waiting; });
    }
  }
  int64_t n = ch.recv_n[round & 1];
  char* mine = nullptr;
  void* up = nullptr;
  int64_t cap = -1;
  std::function<void(void*, const char*)> wb;
  if (n > 0) {
    mine = ch.recv_buf[round & 1].data() + (size_t)tl_rank * n * ch.esize;
    up = ch.user_ptr[round & 1][tl_rank];
    cap = ch.user_cap[round & 1][tl_rank];
    wb = ch.user_wb[round & 1][tl_rank];
  }
  lk.unlock();
  if (up != nullptr) {
    if (wb) {
      wb(up, mine);
    } else {
      int64_t ncopy = (cap >= 0 && cap < n) ? cap : n;
      std::memcpy(up, mine, (size_t)ncopy * ch.esize);
    }
  }
  if (ch.one_shot) {
    /* consume accounting LAST — for one-shot channels the rank that brings
     * consumed to world reclaims the channel, so every other rank must have
     * finished touching it (including the memcpy above) by then. The slice
     * pointer is not handed out for one-shot channels (results land in the
     * caller's registered buffer), so freeing recv_buf here is safe. */
    tl_counts.erase(ch.id);
    bool last;
    {
      std::lock_guard<std::mutex> lk2(ch.mu);
      ch.consumed++;
      last = ch.consumed == g_world;
    }
    if (last) reclaim_one_shot(ch);
    return up;  // internal slice must not escape a reclaimed channel
  }
  return up != nullptr ? up : mine;
}

/* Non-consuming poll + consume-on-complete (reference TestGradientComm
 * semantics: NULL until complete, then the result pointer). */
void* channel_test(Channel& ch, const std::function<int(void)>& test_fn,
                   bool* is_completed) {
  TLCounts& tl = tl_counts[ch.id];
  if (tl.waited == tl.started) {  // nothing in flight: trivially complete
    *is_completed = true;
    return nullptr;
  }
  long round = tl.waited;
  {
    std::unique_lock<std::mutex> lk(ch.mu);
    if (ch.completed_rounds <= round) {
      if (ch.waiting) {  // someone is already completing it; poll again later
        *is_completed = false;
        return nullptr;
      }
      lk.unlock();
      int done = test_fn();
      if (done <= 0) {
        *is_completed = false;
        return nullptr;
      }
      /* complete: fall through to channel_wait, which performs the (now
       * immediate) global wait and consumes this rank's round */
    }
  }
  *is_completed = true;
  return channel_wait(ch);
}

/* ---- impl structs (pimpl-by-reinterpret, the reference's own pattern:
 * public classes carry no data, methods downcast to *Impl) ---------------- */

struct BlockImpl {
  size_t mb_off, mb_cnt, fm_off, fm_cnt, fm_size, buf_off;
  int dt;
};

struct SessImpl;
struct OpImpl;

/* The CommReq* returned for a generic collective. A tiny stable handle that
 * OUTLIVES its (one-shot, reclaimed-on-consume) channel: it resolves the
 * channel by never-reused id, and tracks per-rank consumption so a second
 * Wait/Test is an MPI no-op without ever dereferencing channel memory —
 * immune to both address reuse (ABA) and reclaim races. Freed at
 * DeleteDistribution. */
struct GenReq {
  uint64_t chan_id = 0;
  /* per-rank consumption flags; each slot written by its own rank, read
   * cross-rank only through that rank's own Wait/Test, hence atomic */
  std::vector<std::atomic<char>> consumed;
  explicit GenReq(uint64_t id) : chan_id(id), consumed(g_world) {
    for (auto& c : consumed) c.store(0, std::memory_order_relaxed);
  }
};

/* Handles retired by DeleteDistribution; freed at Finalize. Keeping them
 * alive for the Environment's lifetime makes Wait/Test on ANY handle issued
 * since Init memory-safe (~150 B per generic collective — graph-edge comms
 * use cached per-edge requests, so generic handles are rare). The reference
 * instead frees requests on first Wait and UBs on any reuse. */
std::vector<GenReq*> g_retired_reqs;
std::mutex g_retired_mu;

struct DistImpl {
  uint64_t h = 0;
  /* generic-collective channels, keyed by per-rank call sequence (congruent
   * program order makes the k-th call on every rank the same collective) */
  std::map<long, Channel*> gen;
  std::map<long, GenReq*> gen_reqs;  // handles live until DeleteDistribution
  std::mutex gen_mu;
  Channel& gen_channel(long seq) {
    std::lock_guard<std::mutex> lk(gen_mu);
    Channel*& c = gen[seq];
    if (c == nullptr) {
      c = new Channel();
      c->one_shot = true;
      c->owner = this;
      c->seq = seq;
    }
    return *c;
  }
  GenReq& gen_req(long seq, uint64_t chan_id) {
    std::lock_guard<std::mutex> lk(gen_mu);
    GenReq*& r = gen_reqs[seq];
    if (r == nullptr) r = new GenReq(chan_id);
    return *r;
  }
};
thread_local std::unordered_map<const void*, long> tl_gen_seq;

void reclaim_one_shot(Channel& ch) {
  DistImpl* owner = ch.owner;
  if (owner != nullptr) {
    std::lock_guard<std::mutex> lk(owner->gen_mu);
    auto it = owner->gen.find(ch.seq);
    if (it != owner->gen.end() && it->second == &ch) owner->gen.erase(it);
  }
  delete &ch;  // a later Wait/Test on this pointer is caught by the registry
}

struct ActImpl {
  uint64_t h = 0;
  OpImpl* op = nullptr;
  bool is_input = false;
  ActImpl* peer = nullptr;
  Channel ch;
  std::vector<BlockImpl> pack, unpack;
  std::vector<std::vector<char>> comm_bufs;  // per-rank GetCommBuf storage
  std::mutex bufs_mu;
  size_t wire = 0;    // per-rank wire elems for StartComm
  size_t recvn = 0;   // per-rank result elems of this act's request
  int dt = DT_FLOAT;
  size_t global_fm = 0, local_fm = 0, fm_size = 0;
};

struct PSImpl {
  uint64_t oph = 0;
  int idx = 0;
  OpImpl* op = nullptr;
  Channel grad_ch, inc_ch;
  int dt = DT_FLOAT;
};

struct OpImpl {
  uint64_t h = 0;
  DistImpl* dist = nullptr;
  SessImpl* sess = nullptr;
  std::string name;
  int op_type = OT_CC;
  std::vector<ActImpl*> ins, outs;
  std::vector<PSImpl*> pss;
};

struct RegImpl {
  uint64_t h = 0;
  SessImpl* sess = nullptr;
  int op_type = OT_CC;
  std::string name;
  int n_in = 0, n_out = 0, n_ps = 0;
  std::vector<int> in_dt, out_dt, ps_dt;
};

struct StatsImpl {
  uint64_t h = 0;
};

struct SessImpl {
  uint64_t h = 0;
  size_t global_mb = 0;
  std::vector<OpImpl*> ops;
  StatsImpl* stats = nullptr;
};

struct EnvState {
  bool initialized = false;
  QuantParams quant = {};
  bool quant_set = false;
};
EnvState g_env;
std::mutex g_quant_mu;  // serializes quant-params set/get across rank threads

Environment g_env_obj;  // the singleton facade (stateless; state lives above)

/* ---- rank-thread launcher --------------------------------------------- */

}  // namespace

int RunRanks(int argc, char** argv, int (*rankMain)(int, char**),
             int worldOverride) {
  if (mlsl_environment_init() != MLSL_TPU_SUCCESS)
    die("environment init failed");
  int devs = (int)mlsl_environment_get_process_count();
  g_world = worldOverride > 0 ? worldOverride : devs;
  if (g_world > devs) die("worldOverride exceeds device count");
  g_env.initialized = true;
  std::atomic<int> rc{0};
  std::vector<std::thread> threads;
  threads.reserve(g_world);
  for (int r = 0; r < g_world; r++) {
    threads.emplace_back([&, r] {
      tl_rank = r;
      int ret = rankMain(argc, argv);
      if (ret != 0) {
        int expected = 0;
        rc.compare_exchange_strong(expected, ret);
      }
    });
  }
  for (auto& t : threads) t.join();
  return rc.load();
}

/* ---- Environment ------------------------------------------------------- */

Environment& Environment::GetEnv() { return g_env_obj; }
int Environment::GetVersion() {
  return MLSL_VERSION(MLSL_MAJOR_VERSION, MLSL_MINOR_VERSION);
}
namespace {
/* gather one value per rank thread ahead of a shared_call: every rank stores
 * its slot before arriving at the barrier, so the last arriver sees the
 * complete vector. Dies outside a RunRanks rank thread. */
template <typename T>
void gather_per_rank(std::vector<T>& vec, std::mutex& mu, T value,
                     const char* what) {
  if (tl_rank < 0)
    die(std::string(what) + " outside a RunRanks rank thread");
  std::lock_guard<std::mutex> lk(mu);
  if (vec.empty()) vec.assign(g_world, T());
  vec[tl_rank] = value;
}

std::vector<long> g_cfg_colors;
std::mutex g_cfg_mu;
}  // namespace

void Environment::Configure(const char* config) {
  /* Reference semantics (src/mlsl.cpp:620-647): ranks sharing a color form
   * the new global group — i.e. heterogeneous colors split the world into
   * independent MLSL instances. The single-controller compat runtime serves
   * exactly ONE world, so the homogeneous case (all ranks same color — the
   * common "restrict to my job's ranks" usage) is a validated no-op and
   * heterogeneous colors fail loudly instead of being silently ignored. */
  if (config == nullptr) return;
  std::string s(config);
  size_t eq = s.find("color=");
  if (eq == std::string::npos)
    die("Configure: unsupported configuration string '" + s + "'");
  long color = std::atol(s.c_str() + eq + 6);
  gather_per_rank(g_cfg_colors, g_cfg_mu, color, "Environment::Configure");
  shared_call([&]() -> uint64_t {
    std::lock_guard<std::mutex> lk(g_cfg_mu);
    for (long c : g_cfg_colors)
      if (c != g_cfg_colors[0])
        die("Configure: per-color sub-worlds are not supported by the "
            "single-controller compat runtime; all ranks must pass the same "
            "color");
    return 0;
  });
}
void Environment::Init(int*, char***) {
  /* the runtime is brought up once by RunRanks; per-rank Init is bookkeeping */
  if (tl_rank < 0) die("Environment::Init outside a RunRanks rank thread");
}
void Environment::Finalize() {
  shared_call([] {
    std::lock_guard<std::mutex> lk(g_retired_mu);
    for (GenReq* r : g_retired_reqs) delete r;
    g_retired_reqs.clear();
    return (uint64_t)mlsl_environment_finalize();
  });
}
bool Environment::IsInitialized() { return g_env.initialized; }
size_t Environment::GetProcessIdx() { return (size_t)tl_rank; }
size_t Environment::GetProcessCount() { return (size_t)g_world; }

void* Environment::Alloc(size_t size, size_t alignment) {
  void* p = nullptr;
  if (alignment < sizeof(void*)) alignment = sizeof(void*);
  if (posix_memalign(&p, alignment, size ? size : alignment) != 0) return nullptr;
  return p;
}
void Environment::Free(void* ptr) { free(ptr); }

void Environment::SetQuantizationParams(QuantParams* params) {
  /* Forward the full request — including lib_path — to the core (reference
   * src/mlsl.cpp:798 -> quant_load, quant/quant.c:96-133). The core dlopens
   * the named library via its ctypes trampoline; a codec that cannot be
   * honored fails LOUDLY here, exactly like the reference's ASSERT-on-load.
   *
   * Deliberately NOT a shared_call rendezvous: in the reference this call is
   * process-local (each rank dlopens independently), so ported programs may
   * call it from any subset of ranks at rank-dependent points. The core's
   * registration is global and idempotent; a mutex serializes racing ranks. */
  if (params == nullptr) return;
  std::lock_guard<std::mutex> lk(g_quant_mu);
  g_env.quant = *params;
  g_env.quant_set = true;
  int rc = mlsl_environment_set_quantization_params(
      params->lib_path, params->quant_buffer_func_name,
      params->dequant_buffer_func_name, params->reduce_sum_func_name,
      (int64_t)params->block_size, (int64_t)params->elem_in_block);
  if (rc != MLSL_TPU_SUCCESS)
    die("SetQuantizationParams failed (lib_path codec could not be loaded)");
}
QuantParams* Environment::GetQuantizationParams() {
  /* Copy under the setter's mutex into a thread-local, so the caller's reads
   * through the returned pointer cannot race a concurrent registration (the
   * reference's signature forces returning a pointer; a pointer into g_env
   * would be torn-readable after unlock). */
  static thread_local QuantParams copy;
  std::lock_guard<std::mutex> lk(g_quant_mu);
  if (!g_env.quant_set) return nullptr;
  copy = g_env.quant;
  return &copy;
}

Distribution* Environment::CreateDistribution(size_t dataPartitions,
                                              size_t modelPartitions) {
  uint64_t r = shared_call([&]() -> uint64_t {
    DistImpl* d = new DistImpl();
    d->h = mlsl_environment_create_distribution((int64_t)dataPartitions,
                                                (int64_t)modelPartitions, 1);
    if (d->h == 0) die("CreateDistribution failed");
    return (uint64_t)(uintptr_t)d;
  });
  return (Distribution*)(uintptr_t)r;
}

namespace {
std::vector<int64_t> g_dist_dcolors, g_dist_mcolors;
std::mutex g_dist_colors_mu;
}  // namespace

Distribution* Environment::CreateDistributionWithColors(int dataColor,
                                                        int modelColor) {
  /* Reference include/mlsl.hpp:864: each rank passes ITS colors; ranks with
   * the same dataColor form a data group (same for model). Gather per-rank
   * colors, then the last arriver creates the colored distribution once.
   * Unequal partitions are served by the core's padded ragged-group
   * contract (docs/DESIGN.md). */
  gather_per_rank(g_dist_dcolors, g_dist_colors_mu, (int64_t)dataColor,
                  "Environment::CreateDistributionWithColors");
  gather_per_rank(g_dist_mcolors, g_dist_colors_mu, (int64_t)modelColor,
                  "Environment::CreateDistributionWithColors");
  uint64_t r = shared_call([&]() -> uint64_t {
    std::lock_guard<std::mutex> lk(g_dist_colors_mu);
    DistImpl* d = new DistImpl();
    d->h = mlsl_environment_create_distribution_with_colors(
        g_dist_dcolors.data(), g_dist_mcolors.data(), (int64_t)g_world);
    if (d->h == 0) die("CreateDistributionWithColors failed");
    g_dist_dcolors.clear();  // next call gathers afresh
    g_dist_mcolors.clear();
    return (uint64_t)(uintptr_t)d;
  });
  return (Distribution*)(uintptr_t)r;
}

void Environment::DeleteDistribution(Distribution* distribution) {
  shared_call([&]() -> uint64_t {
    DistImpl* d = (DistImpl*)distribution;
    if (d != nullptr) {
      mlsl_handle_release(d->h);
      /* every rank has arrived here (shared_call), so no channel is in use.
       * Handles are RETIRED, not freed: a Wait/Test on a request outstanding
       * across DeleteDistribution stays a memory-safe no-op (its channel id
       * resolves to nothing); Finalize reclaims the retired handles. */
      for (auto& kv : d->gen) delete kv.second;
      {
        std::lock_guard<std::mutex> lk(g_retired_mu);
        for (auto& kv : d->gen_reqs) g_retired_reqs.push_back(kv.second);
      }
      delete d;
    }
    return 0;
  });
}

Session* Environment::CreateSession(PhaseType) {
  uint64_t r = shared_call([]() -> uint64_t {
    SessImpl* s = new SessImpl();
    s->h = mlsl_environment_create_session();
    if (s->h == 0) die("CreateSession failed");
    return (uint64_t)(uintptr_t)s;
  });
  return (Session*)(uintptr_t)r;
}

void Environment::DeleteSession(Session* session) {
  shared_call([&]() -> uint64_t {
    SessImpl* s = (SessImpl*)session;
    if (s != nullptr) mlsl_handle_release(s->h);
    return 0;
  });
}

void Environment::Wait(CommReq* req) {
  if (req == nullptr) return;
  GenReq* r = (GenReq*)req;
  if (r->consumed[tl_rank].load(std::memory_order_acquire))
    return;  // MPI no-op on a completed request
  /* this rank has NOT consumed its round, so the one-shot channel cannot
   * have been reclaimed (reclaim requires all ranks consumed) — the id
   * lookup is race-free, not a check-then-use on raw memory */
  Channel* ch = channel_by_id(r->chan_id);
  if (ch == nullptr) return;  // defensive: invalidated by DeleteDistribution
  channel_wait(*ch);
  r->consumed[tl_rank].store(1, std::memory_order_release);
}

void Environment::Test(CommReq* req, bool* isCompleted) {
  if (req == nullptr) {
    *isCompleted = true;
    return;
  }
  GenReq* r = (GenReq*)req;
  if (r->consumed[tl_rank].load(std::memory_order_acquire)) {
    *isCompleted = true;  // MPI no-op on a completed request
    return;
  }
  Channel* ch = channel_by_id(r->chan_id);
  if (ch == nullptr) {
    *isCompleted = true;
    return;
  }
  channel_test(
      *ch, [ch] { return mlsl_request_test(ch->c_req); }, isCompleted);
  if (*isCompleted) r->consumed[tl_rank].store(1, std::memory_order_release);
}

/* ---- Distribution ------------------------------------------------------ */

namespace {

DistImpl* D(Distribution* d) { return (DistImpl*)d; }

size_t group_size(DistImpl* d, GroupType g) {
  int64_t n = mlsl_distribution_get_process_count(d->h, (mlsl_group_type_t)g);
  return n > 0 ? (size_t)n : 1;
}

/* Start a generic collective through the flat C API. The per-round request
 * handle is captured by the wait closure. */
CommReq* generic_start(DistImpl* d, const void* src, size_t send_elems,
                       int dt, int64_t recv_elems, void* user_recv,
                       std::function<uint64_t(const void*)> issue,
                       int64_t src_elems = -1, int64_t user_elems = -1,
                       std::function<void(void*, const char*)> writer = nullptr) {
  long seq = tl_gen_seq[d]++;
  Channel& ch = d->gen_channel(seq);
  Channel* chp = &ch;
  channel_start(
      ch, src, send_elems, dt_size(dt), recv_elems, user_recv,
      [issue, chp](const void* world) {
        chp->c_req = issue(world);  // written under ch.mu (dispatch path)
        if (chp->c_req == 0) die("generic collective start failed");
      },
      [chp, recv_elems, dt](void* dst) -> int64_t {
        if (mlsl_request_wait(chp->c_req, dst, recv_elems,
                              (mlsl_data_type_t)dt) != MLSL_TPU_SUCCESS)
          die("generic collective wait failed");
        return recv_elems;
      },
      src_elems, user_elems, std::move(writer));
  return (CommReq*)&d->gen_req(seq, ch.id);
}

}  // namespace

size_t Distribution::GetProcessIdx(GroupType groupType) {
  return (size_t)mlsl_distribution_get_process_idx(
      D(this)->h, (mlsl_group_type_t)groupType, tl_rank);
}

size_t Distribution::GetProcessCount(GroupType groupType) {
  return group_size(D(this), groupType);
}

CommReq* Distribution::Bcast(void* buffer, size_t count, DataType dataType,
                             size_t rootIdx, GroupType groupType) {
  DistImpl* d = D(this);
  uint64_t h = d->h;
  return generic_start(
      d, buffer, count, dataType, (int64_t)count, buffer,
      [h, count, dataType, rootIdx, groupType](const void* world) {
        return mlsl_distribution_bcast(h, world, (int64_t)count,
                                       (mlsl_data_type_t)dataType,
                                       (int64_t)rootIdx,
                                       (mlsl_group_type_t)groupType);
      });
}

CommReq* Distribution::AllReduce(void* sendBuffer, void* recvBuffer,
                                 size_t count, DataType dataType,
                                 ReductionType redType, GroupType groupType) {
  DistImpl* d = D(this);
  uint64_t h = d->h;
  return generic_start(
      d, sendBuffer, count, dataType, (int64_t)count, recvBuffer,
      [h, count, dataType, redType, groupType](const void* world) {
        return mlsl_distribution_all_reduce(h, world, (int64_t)count,
                                            (mlsl_data_type_t)dataType,
                                            (mlsl_reduction_t)redType,
                                            (mlsl_group_type_t)groupType);
      });
}

CommReq* Distribution::Reduce(void* sendBuffer, void* recvBuffer, size_t count,
                              DataType dataType, ReductionType redType,
                              size_t rootIdx, GroupType groupType) {
  DistImpl* d = D(this);
  uint64_t h = d->h;
  bool is_root = GetProcessIdx(groupType) == rootIdx;
  return generic_start(
      d, sendBuffer, count, dataType, (int64_t)count,
      is_root ? recvBuffer : nullptr,  // MPI: recv meaningful at root only
      [h, count, dataType, redType, rootIdx, groupType](const void* world) {
        return mlsl_distribution_reduce(h, world, (int64_t)count,
                                        (mlsl_data_type_t)dataType,
                                        (mlsl_reduction_t)redType,
                                        (int64_t)rootIdx,
                                        (mlsl_group_type_t)groupType);
      });
}

CommReq* Distribution::AllGather(void* sendBuffer, size_t sendCount,
                                 void* recvBuffer, DataType dataType,
                                 GroupType groupType) {
  DistImpl* d = D(this);
  uint64_t h = d->h;
  size_t g = group_size(d, groupType);
  return generic_start(
      d, sendBuffer, sendCount, dataType, (int64_t)(sendCount * g), recvBuffer,
      [h, sendCount, dataType, groupType](const void* world) {
        return mlsl_distribution_all_gather(h, world, (int64_t)sendCount,
                                            (mlsl_data_type_t)dataType,
                                            (mlsl_group_type_t)groupType);
      });
}

CommReq* Distribution::AllGatherv(void* sendBuffer, size_t sendCount,
                                  void* recvBuffer, size_t* recvCounts,
                                  DataType dataType, GroupType groupType) {
  /* reference include/mlsl.hpp:470: recvCounts[group_size], identical on
   * every rank (MPI same-counts-everywhere mode); rank at group position i
   * sends sendCount == recvCounts[i] elements; every rank receives the
   * sum(recvCounts)-element concatenation. */
  DistImpl* d = D(this);
  uint64_t h = d->h;
  size_t g = group_size(d, groupType);
  std::vector<int64_t> counts(g);
  int64_t maxc = 0, total = 0;
  for (size_t j = 0; j < g; j++) {
    counts[j] = (int64_t)recvCounts[j];
    if (counts[j] > maxc) maxc = counts[j];
    total += counts[j];
  }
  if ((int64_t)sendCount != counts[GetProcessIdx(groupType)])
    die("AllGatherv: sendCount does not match recvCounts[myIdx]");
  /* uniform staging slots of maxc elements; this rank deposits sendCount */
  return generic_start(
      d, sendBuffer, (size_t)maxc, dataType, total, recvBuffer,
      [h, maxc, counts, dataType, groupType](const void* world) {
        return mlsl_distribution_all_gatherv(h, world, maxc, counts.data(),
                                             (mlsl_data_type_t)dataType,
                                             (mlsl_group_type_t)groupType);
      },
      (int64_t)sendCount);
}

namespace {

/* Per-call gather of every rank's AlltoAllv count/offset rows into the full
 * (world, group) tables the engine's per-rank mode consumes (reference MPI
 * generality: each rank passes its own vectors to pairwise Isend/Irecv,
 * src/comm_ep.cpp:1188-1265). Keyed by per-rank call sequence like the comm
 * channels: congruent program order makes the k-th AlltoAllv on every rank
 * the same exchange. The last arriver computes the uniform staging extents;
 * the state is kept alive by the issue lambda's shared_ptr. */
struct A2AVState {
  std::mutex mu;
  std::condition_variable cv;
  int arrived = 0;
  bool done = false;
  std::vector<int64_t> sc, soff, rc, roff;  // (world * group), row-major
  int64_t send_slot = 0, recv_slot = 0;     // uniform staging row extents
};
std::map<std::pair<const void*, long>, std::shared_ptr<A2AVState>> g_a2av;
std::mutex g_a2av_mu;
thread_local std::unordered_map<const void*, long> tl_a2av_seq;

std::shared_ptr<A2AVState> a2av_state(DistImpl* d, long seq, size_t g) {
  std::lock_guard<std::mutex> lk(g_a2av_mu);
  auto key = std::make_pair((const void*)d, seq);
  auto& sp = g_a2av[key];
  if (!sp) {
    sp = std::make_shared<A2AVState>();
    size_t n = (size_t)g_world * g;
    sp->sc.assign(n, 0);
    sp->soff.assign(n, 0);
    sp->rc.assign(n, 0);
    sp->roff.assign(n, 0);
  }
  return sp;
}

}  // namespace

CommReq* Distribution::AlltoAllv(void* sendBuffer, size_t* sendCounts,
                                 size_t* sendOffsets, void* recvBuffer,
                                 size_t* recvCounts, size_t* recvOffsets,
                                 DataType dataType, GroupType groupType) {
  DistImpl* d = D(this);
  uint64_t h = d->h;
  size_t g = group_size(d, groupType);
  size_t esz = dt_size(dataType);

  if (recvCounts != nullptr) {
    /* General per-rank mode (reference include/mlsl.hpp:432 with each rank
     * passing its OWN arrays — full MPI_Ialltoallv generality): gather every
     * rank's rows into (world, group) tables, then issue the engine's
     * per-rank exchange once. The engine validates the MPI pairwise
     * invariant (recv_counts = transposed send geometry) at setup and fails
     * loudly on a mismatch — the case the old rank-uniform mode die()d on is
     * now simply a valid exchange. Missing offsets default to the packed
     * layout per rank, matching MPI displacement semantics. */
    long seq = tl_a2av_seq[d]++;
    auto st = a2av_state(d, seq, g);
    std::vector<int64_t> myrc(g), myroff(g);
    int64_t my_send = 0, my_recv = 0;
    {
      std::unique_lock<std::mutex> lk(st->mu);
      int64_t acc_s = 0, acc_r = 0;
      for (size_t j = 0; j < g; j++) {
        int64_t s = (int64_t)sendCounts[j];
        int64_t so = sendOffsets != nullptr ? (int64_t)sendOffsets[j] : acc_s;
        int64_t r = (int64_t)recvCounts[j];
        int64_t ro = recvOffsets != nullptr ? (int64_t)recvOffsets[j] : acc_r;
        acc_s += s;
        acc_r += r;
        st->sc[(size_t)tl_rank * g + j] = s;
        st->soff[(size_t)tl_rank * g + j] = so;
        st->rc[(size_t)tl_rank * g + j] = r;
        st->roff[(size_t)tl_rank * g + j] = ro;
        myrc[j] = r;
        myroff[j] = ro;
        my_send = std::max(my_send, so + s);
        my_recv = std::max(my_recv, ro + r);
      }
      st->arrived++;
      if (st->arrived == g_world) {
        for (int w = 0; w < g_world; w++) {
          int64_t se = 0, re = 0;
          for (size_t j = 0; j < g; j++) {
            se = std::max(se, st->soff[(size_t)w * g + j] +
                                  st->sc[(size_t)w * g + j]);
            re = std::max(re, st->roff[(size_t)w * g + j] +
                                  st->rc[(size_t)w * g + j]);
          }
          st->send_slot = std::max(st->send_slot, se);
          st->recv_slot = std::max(st->recv_slot, re);
        }
        if (st->send_slot == 0) st->send_slot = 1;
        if (st->recv_slot == 0) st->recv_slot = 1;
        st->done = true;
        st->cv.notify_all();
        std::lock_guard<std::mutex> lk2(g_a2av_mu);
        g_a2av.erase(std::make_pair((const void*)d, seq));
      } else {
        watched_slot_wait(lk, st->cv, [&] { return st->done; }, [&] {
          return "rendezvous watchdog: rank " + std::to_string(tl_rank) +
                 " stuck gathering AlltoAllv counts (arrived=" +
                 std::to_string(st->arrived) + "/" + std::to_string(g_world) +
                 ") — ranks issued collectives in divergent order";
        });
      }
    }
    /* block-accurate write-back: copy ONLY this rank's valid blocks; gap
     * bytes between blocks are left untouched, as MPI guarantees */
    std::function<void(void*, const char*)> writer =
        [myrc, myroff, esz, g](void* up, const char* src) {
          for (size_t j = 0; j < g; j++)
            std::memcpy((char*)up + (size_t)myroff[j] * esz,
                        src + (size_t)myroff[j] * esz,
                        (size_t)myrc[j] * esz);
        };
    int64_t send_slot = st->send_slot, recv_slot = st->recv_slot;
    return generic_start(
        d, sendBuffer, (size_t)send_slot, dataType, recv_slot, recvBuffer,
        [h, st, send_slot, dataType, groupType](const void* world) {
          return mlsl_distribution_all_to_allv_full(
              h, world, send_slot, st->sc.data(), st->soff.data(),
              st->rc.data(), st->roff.data(), (mlsl_data_type_t)dataType,
              (mlsl_group_type_t)groupType);
        },
        my_send, my_recv, std::move(writer));
  }

  /* Legacy rank-uniform (1-D, same arrays on every rank) mode, kept for
   * callers that pass no recvCounts: member j receives sendCounts[j] from
   * every peer. The engine's staging rows are padded to max(sendCounts), so
   * the write-back into the caller's buffer is capped at THIS rank's
   * MPI-sized receive extent — a ported program's recvBuffer sized per the
   * reference contract is never overrun. */
  std::vector<int64_t> sc(g), soff, roff;
  int64_t send_len = 0, maxc = 0;
  for (size_t j = 0; j < g; j++) {
    sc[j] = (int64_t)sendCounts[j];
    if (sc[j] > maxc) maxc = sc[j];
  }
  if (sendOffsets != nullptr) {
    soff.resize(g);
    for (size_t j = 0; j < g; j++) {
      soff[j] = (int64_t)sendOffsets[j];
      send_len = std::max(send_len, soff[j] + sc[j]);
    }
  } else {
    for (size_t j = 0; j < g; j++) send_len += sc[j];
  }
  /* recv_len is the engine's PADDED staging extent (uniform across ranks);
   * my_recv is THIS rank's MPI-sized receive extent — the write-back cap. */
  int64_t mine = sc[GetProcessIdx(groupType)];
  int64_t recv_len, my_recv;
  std::function<void(void*, const char*)> writer;  // offset mode only
  if (recvOffsets != nullptr) {
    roff.resize(g);
    int64_t maxoff = 0;
    for (size_t j = 0; j < g; j++) {
      roff[j] = (int64_t)recvOffsets[j];
      maxoff = std::max(maxoff, roff[j]);
    }
    recv_len = maxoff + maxc;
    my_recv = maxoff + mine;
    /* block-accurate write-back: copy ONLY the valid block from each peer
     * (staging rows sit at the same roff[j]); gap bytes between blocks are
     * left untouched, as MPI guarantees */
    writer = [roff, mine, esz](void* up, const char* src) {
      for (int64_t o : roff)
        std::memcpy((char*)up + (size_t)o * esz, src + (size_t)o * esz,
                    (size_t)mine * esz);
    };
  } else {
    recv_len = (int64_t)g * maxc;  // packed rows padded to the max count
    my_recv = (int64_t)g * mine;   // my packed rows are the contiguous prefix
  }
  return generic_start(
      d, sendBuffer, (size_t)send_len, dataType, recv_len, recvBuffer,
      [h, send_len, sc, soff, roff, dataType, groupType](const void* world) {
        return mlsl_distribution_all_to_allv(
            h, world, send_len, sc.data(),
            soff.empty() ? nullptr : soff.data(),
            roff.empty() ? nullptr : roff.data(), (mlsl_data_type_t)dataType,
            (mlsl_group_type_t)groupType);
      },
      -1, my_recv, std::move(writer));
}

CommReq* Distribution::Gather(void* sendBuffer, size_t sendCount,
                              void* recvBuffer, DataType dataType,
                              size_t rootIdx, GroupType groupType) {
  DistImpl* d = D(this);
  uint64_t h = d->h;
  size_t g = group_size(d, groupType);
  bool is_root = GetProcessIdx(groupType) == rootIdx;
  return generic_start(
      d, sendBuffer, sendCount, dataType, (int64_t)(sendCount * g),
      is_root ? recvBuffer : nullptr,
      [h, sendCount, dataType, rootIdx, groupType](const void* world) {
        return mlsl_distribution_gather(h, world, (int64_t)sendCount,
                                        (mlsl_data_type_t)dataType,
                                        (int64_t)rootIdx,
                                        (mlsl_group_type_t)groupType);
      });
}

CommReq* Distribution::Scatter(void* sendBuffer, void* recvBuffer,
                               size_t recvCount, DataType dataType,
                               size_t rootIdx, GroupType groupType) {
  DistImpl* d = D(this);
  uint64_t h = d->h;
  size_t g = group_size(d, groupType);
  size_t send_elems = recvCount * g;
  /* MPI: the send buffer is significant at root ONLY — a non-root rank may
   * pass a small or uninitialized pointer, so its staging memcpy must be
   * skipped (null src), not read send_elems from it. */
  bool is_root = GetProcessIdx(groupType) == rootIdx;
  return generic_start(
      d, is_root ? sendBuffer : nullptr, send_elems, dataType,
      (int64_t)recvCount, recvBuffer,
      [h, send_elems, dataType, rootIdx, groupType](const void* world) {
        return mlsl_distribution_scatter(h, world, (int64_t)send_elems,
                                         (mlsl_data_type_t)dataType,
                                         (int64_t)rootIdx,
                                         (mlsl_group_type_t)groupType);
      });
}

CommReq* Distribution::AlltoAll(void* sendBuffer, size_t sendCount,
                                void* recvBuffer, DataType dataType,
                                GroupType groupType) {
  DistImpl* d = D(this);
  uint64_t h = d->h;
  size_t g = group_size(d, groupType);
  size_t total = sendCount * g;
  return generic_start(
      d, sendBuffer, total, dataType, (int64_t)total, recvBuffer,
      [h, total, dataType, groupType](const void* world) {
        return mlsl_distribution_all_to_all(h, world, (int64_t)total,
                                            (mlsl_data_type_t)dataType,
                                            (mlsl_group_type_t)groupType);
      });
}

CommReq* Distribution::ReduceScatter(void* sendBuffer, void* recvBuffer,
                                     size_t recvCount, DataType dataType,
                                     ReductionType redType,
                                     GroupType groupType) {
  DistImpl* d = D(this);
  uint64_t h = d->h;
  size_t g = group_size(d, groupType);
  size_t send_elems = recvCount * g;
  return generic_start(
      d, sendBuffer, send_elems, dataType, (int64_t)recvCount, recvBuffer,
      [h, send_elems, dataType, redType, groupType](const void* world) {
        return mlsl_distribution_reduce_scatter(h, world, (int64_t)send_elems,
                                                (mlsl_data_type_t)dataType,
                                                (mlsl_reduction_t)redType,
                                                (mlsl_group_type_t)groupType);
      });
}

void Distribution::Barrier(GroupType groupType) {
  DistImpl* d = D(this);
  uint64_t h = d->h;
  shared_call([h, groupType]() -> uint64_t {
    mlsl_distribution_barrier(h, (mlsl_group_type_t)groupType);
    return 0;
  });
}

/* ---- OperationRegInfo -------------------------------------------------- */

namespace {
RegImpl* R(OperationRegInfo* r) { return (RegImpl*)r; }
}  // namespace

void OperationRegInfo::SetName(const char* name) {
  std::string n = name != nullptr ? name : "";
  shared_call([this, n]() -> uint64_t {
    R(this)->name = n;
    return 0;
  });
}

size_t OperationRegInfo::AddInput(size_t featureMapCount, size_t featureMapSize,
                                  DataType dataType) {
  return (size_t)shared_call([&]() -> uint64_t {
    RegImpl* r = R(this);
    int64_t idx = mlsl_operation_reg_info_add_input(
        r->h, (int64_t)featureMapCount, (int64_t)featureMapSize,
        (mlsl_data_type_t)dataType);
    if (idx < 0) die("AddInput failed");
    r->n_in++;
    r->in_dt.push_back(dataType);
    return (uint64_t)idx;
  });
}

size_t OperationRegInfo::AddOutput(size_t featureMapCount,
                                   size_t featureMapSize, DataType dataType) {
  return (size_t)shared_call([&]() -> uint64_t {
    RegImpl* r = R(this);
    int64_t idx = mlsl_operation_reg_info_add_output(
        r->h, (int64_t)featureMapCount, (int64_t)featureMapSize,
        (mlsl_data_type_t)dataType);
    if (idx < 0) die("AddOutput failed");
    r->n_out++;
    r->out_dt.push_back(dataType);
    return (uint64_t)idx;
  });
}

size_t OperationRegInfo::AddParameterSet(size_t kernelCount, size_t kernelSize,
                                         DataType dataType,
                                         bool distributedUpdate,
                                         CompressionType compressType) {
  return (size_t)shared_call([&]() -> uint64_t {
    RegImpl* r = R(this);
    int64_t idx = mlsl_operation_reg_info_add_parameter_set(
        r->h, (int64_t)kernelCount, (int64_t)kernelSize,
        (mlsl_data_type_t)dataType, distributedUpdate ? 1 : 0,
        (mlsl_compression_t)compressType);
    if (idx < 0) die("AddParameterSet failed");
    r->n_ps++;
    r->ps_dt.push_back(dataType);
    return (uint64_t)idx;
  });
}

void OperationRegInfo::Validate(Distribution*) {}

/* ---- Session ----------------------------------------------------------- */

namespace {
SessImpl* S(Session* s) { return (SessImpl*)s; }
}  // namespace

void Session::SetGlobalMinibatchSize(size_t globalMinibatchSize) {
  shared_call([&]() -> uint64_t {
    SessImpl* s = S(this);
    if (mlsl_session_set_global_minibatch_size(
            s->h, (int64_t)globalMinibatchSize) != MLSL_TPU_SUCCESS)
      die("SetGlobalMinibatchSize failed");
    s->global_mb = globalMinibatchSize;
    return 0;
  });
}

size_t Session::GetGlobalMinibatchSize() { return S(this)->global_mb; }
PhaseType Session::GetPhaseType() { return PT_TRAIN; }

OperationRegInfo* Session::CreateOperationRegInfo(OpType opType) {
  uint64_t r = shared_call([&]() -> uint64_t {
    RegImpl* reg = new RegImpl();
    reg->h = mlsl_session_create_operation_reg_info(S(this)->h,
                                                    (mlsl_op_type_t)opType);
    if (reg->h == 0) die("CreateOperationRegInfo failed");
    reg->sess = S(this);
    reg->op_type = opType;
    return (uint64_t)(uintptr_t)reg;
  });
  return (OperationRegInfo*)(uintptr_t)r;
}

void Session::DeleteOperationRegInfo(OperationRegInfo* info) {
  shared_call([&]() -> uint64_t {
    RegImpl* r = R(info);
    if (r != nullptr) mlsl_handle_release(r->h);
    return 0;
  });
}

size_t Session::AddOperation(OperationRegInfo* info, Distribution* dist) {
  return (size_t)shared_call([&]() -> uint64_t {
    SessImpl* s = S(this);
    RegImpl* reg = R(info);
    DistImpl* d = (DistImpl*)dist;
    uint64_t oph = mlsl_session_add_operation(s->h, reg->h,
                                              d != nullptr ? d->h : 0);
    if (oph == 0) die("AddOperation failed");
    OpImpl* op = new OpImpl();
    op->h = oph;
    op->dist = d;
    op->sess = s;
    op->name = reg->name;
    op->op_type = reg->op_type;
    for (int i = 0; i < reg->n_in; i++) {
      ActImpl* a = new ActImpl();
      a->h = mlsl_operation_get_input(oph, i);
      if (a->h == 0) die("GetInput failed");
      a->op = op;
      a->is_input = true;
      a->dt = reg->in_dt[i];
      a->comm_bufs.resize(g_world);
      /* shapes are fixed at operation registration; wire layout at Commit */
      a->global_fm = (size_t)mlsl_activation_get_global_fm_count(a->h);
      a->local_fm = (size_t)mlsl_activation_get_local_fm_count(a->h);
      a->fm_size = (size_t)mlsl_activation_get_fm_size(a->h);
      op->ins.push_back(a);
    }
    for (int i = 0; i < reg->n_out; i++) {
      ActImpl* a = new ActImpl();
      a->h = mlsl_operation_get_output(oph, i);
      if (a->h == 0) die("GetOutput failed");
      a->op = op;
      a->is_input = false;
      a->dt = reg->out_dt[i];
      a->comm_bufs.resize(g_world);
      a->global_fm = (size_t)mlsl_activation_get_global_fm_count(a->h);
      a->local_fm = (size_t)mlsl_activation_get_local_fm_count(a->h);
      a->fm_size = (size_t)mlsl_activation_get_fm_size(a->h);
      op->outs.push_back(a);
    }
    for (int i = 0; i < reg->n_ps; i++) {
      PSImpl* p = new PSImpl();
      p->oph = oph;
      p->idx = i;
      p->op = op;
      p->dt = reg->ps_dt[i];
      op->pss.push_back(p);
    }
    s->ops.push_back(op);
    return (uint64_t)(s->ops.size() - 1);
  });
}

void Session::RemoveOperations() {
  shared_call([&]() -> uint64_t {
    S(this)->ops.clear();  // handles released with the session
    return 0;
  });
}

size_t Session::GetOperationCount() { return S(this)->ops.size(); }

Operation* Session::GetOperation(size_t idx) {
  SessImpl* s = S(this);
  return idx < s->ops.size() ? (Operation*)s->ops[idx] : nullptr;
}

void Session::Commit() {
  shared_call([&]() -> uint64_t {
    SessImpl* s = S(this);
    if (mlsl_session_commit(s->h) != MLSL_TPU_SUCCESS) die("Commit failed");
    /* post-commit: snapshot the per-edge wire layouts for every activation */
    for (OpImpl* op : s->ops) {
      std::vector<ActImpl*> acts = op->ins;
      acts.insert(acts.end(), op->outs.begin(), op->outs.end());
      for (ActImpl* a : acts) {
        a->wire = (size_t)mlsl_activation_get_wire_count(a->h);
        int64_t rn = mlsl_activation_get_recv_count(a->h);
        a->recvn = rn > 0 ? (size_t)rn : 0;
        int64_t np = mlsl_activation_get_pack_block_count(a->h);
        for (int64_t i = 0; i < np; i++) {
          BlockImpl b;
          b.mb_off = (size_t)mlsl_activation_get_pack_block(a->h, i, 0);
          b.mb_cnt = (size_t)mlsl_activation_get_pack_block(a->h, i, 1);
          b.fm_off = (size_t)mlsl_activation_get_pack_block(a->h, i, 2);
          b.fm_cnt = (size_t)mlsl_activation_get_pack_block(a->h, i, 3);
          b.fm_size = (size_t)mlsl_activation_get_pack_block(a->h, i, 4);
          b.buf_off = (size_t)mlsl_activation_get_pack_block(a->h, i, 5);
          b.dt = a->dt;
          a->pack.push_back(b);
        }
        int64_t nu = mlsl_activation_get_unpack_block_count(a->h);
        for (int64_t i = 0; i < nu; i++) {
          BlockImpl b;
          b.mb_off = (size_t)mlsl_activation_get_unpack_block(a->h, i, 0);
          b.mb_cnt = (size_t)mlsl_activation_get_unpack_block(a->h, i, 1);
          b.fm_off = (size_t)mlsl_activation_get_unpack_block(a->h, i, 2);
          b.fm_cnt = (size_t)mlsl_activation_get_unpack_block(a->h, i, 3);
          b.fm_size = (size_t)mlsl_activation_get_unpack_block(a->h, i, 4);
          b.buf_off = (size_t)mlsl_activation_get_unpack_block(a->h, i, 5);
          b.dt = a->dt;
          a->unpack.push_back(b);
        }
      }
    }
    return 0;
  });
}

Statistics* Session::GetStats() {
  uint64_t r = shared_call([&]() -> uint64_t {
    SessImpl* s = S(this);
    if (s->stats == nullptr) {
      StatsImpl* st = new StatsImpl();
      st->h = mlsl_session_get_stats(s->h);
      if (st->h == 0) die("GetStats failed");
      s->stats = st;
    }
    return (uint64_t)(uintptr_t)s->stats;
  });
  return (Statistics*)(uintptr_t)r;
}

/* ---- Operation --------------------------------------------------------- */

namespace {
OpImpl* O(Operation* o) { return (OpImpl*)o; }
}  // namespace

void Operation::SetDistribution(Distribution* dist) {
  O(this)->dist = (DistImpl*)dist;
}
Distribution* Operation::GetDistribution() {
  return (Distribution*)O(this)->dist;
}
Session* Operation::GetSession() { return (Session*)O(this)->sess; }
OpType Operation::GetOpType() { return (OpType)O(this)->op_type; }
const char* Operation::GetName() { return O(this)->name.c_str(); }

void Operation::SetPrev(Operation* prev, size_t actIdx, size_t prevOpActIdx) {
  shared_call([&]() -> uint64_t {
    OpImpl* cur = O(this);
    OpImpl* p = O(prev);
    if (mlsl_operation_set_prev(cur->h, p->h, (int64_t)actIdx,
                                (int64_t)prevOpActIdx) != MLSL_TPU_SUCCESS)
      die("SetPrev failed");
    cur->ins[actIdx]->peer = p->outs[prevOpActIdx];
    p->outs[prevOpActIdx]->peer = cur->ins[actIdx];
    return 0;
  });
}

void Operation::SetNext(Operation* next, size_t actIdx, size_t nextOpActIdx) {
  shared_call([&]() -> uint64_t {
    OpImpl* cur = O(this);
    OpImpl* n = O(next);
    if (mlsl_operation_set_next(cur->h, n->h, (int64_t)actIdx,
                                (int64_t)nextOpActIdx) != MLSL_TPU_SUCCESS)
      die("SetNext failed");
    cur->outs[actIdx]->peer = n->ins[nextOpActIdx];
    n->ins[nextOpActIdx]->peer = cur->outs[actIdx];
    return 0;
  });
}

size_t Operation::GetGlobalMinibatchSize() {
  return (size_t)mlsl_operation_get_global_minibatch_size(O(this)->h);
}
size_t Operation::GetLocalMinibatchSize() {
  return (size_t)mlsl_operation_get_local_minibatch_size(O(this)->h);
}
size_t Operation::GetGlobalMinibatchOffset() {
  OpImpl* op = O(this);
  size_t data_idx = (size_t)mlsl_distribution_get_process_idx(
      op->dist->h, MLSL_GT_DATA, tl_rank);
  return GetLocalMinibatchSize() * data_idx;
}

size_t Operation::GetInputCount() { return O(this)->ins.size(); }
Activation* Operation::GetInput(size_t idx) {
  OpImpl* op = O(this);
  return idx < op->ins.size() ? (Activation*)op->ins[idx] : nullptr;
}
size_t Operation::GetOutputCount() { return O(this)->outs.size(); }
Activation* Operation::GetOutput(size_t idx) {
  OpImpl* op = O(this);
  return idx < op->outs.size() ? (Activation*)op->outs[idx] : nullptr;
}
bool Operation::HasParameterSets() { return !O(this)->pss.empty(); }
size_t Operation::GetParameterSetCount() { return O(this)->pss.size(); }
ParameterSet* Operation::GetParameterSet(size_t idx) {
  OpImpl* op = O(this);
  return idx < op->pss.size() ? (ParameterSet*)op->pss[idx] : nullptr;
}

/* ---- CommBlockInfo ----------------------------------------------------- */

namespace {
BlockImpl* B(CommBlockInfo* b) { return (BlockImpl*)b; }
}  // namespace

size_t CommBlockInfo::GetMbOffset() { return B(this)->mb_off; }
size_t CommBlockInfo::GetMbCount() { return B(this)->mb_cnt; }
size_t CommBlockInfo::GetFmOffset() { return B(this)->fm_off; }
size_t CommBlockInfo::GetFmCount() { return B(this)->fm_cnt; }
size_t CommBlockInfo::GetFmSize() { return B(this)->fm_size; }
DataType CommBlockInfo::GetDataType() { return (DataType)B(this)->dt; }
size_t CommBlockInfo::GetBufOffset() { return B(this)->buf_off; }

/* ---- Activation -------------------------------------------------------- */

namespace {
ActImpl* A(Activation* a) { return (ActImpl*)a; }
}  // namespace

size_t Activation::GetGlobalFmCount() { return A(this)->global_fm; }
size_t Activation::GetLocalFmCount() { return A(this)->local_fm; }
size_t Activation::GetFmSize() { return A(this)->fm_size; }
DataType Activation::GetDataType() { return (DataType)A(this)->dt; }

size_t Activation::GetGlobalFmOffset() {
  ActImpl* a = A(this);
  int64_t model_idx = mlsl_distribution_get_process_idx(
      a->op->dist->h, MLSL_GT_MODEL, tl_rank);
  return (size_t)mlsl_activation_get_global_fm_offset(a->h, model_idx);
}

size_t Activation::GetPackBlockCount() { return A(this)->pack.size(); }
size_t Activation::GetUnpackBlockCount() { return A(this)->unpack.size(); }
CommBlockInfo* Activation::GetPackBlock(size_t idx) {
  ActImpl* a = A(this);
  return idx < a->pack.size() ? (CommBlockInfo*)&a->pack[idx] : nullptr;
}
CommBlockInfo* Activation::GetUnpackBlock(size_t idx) {
  ActImpl* a = A(this);
  return idx < a->unpack.size() ? (CommBlockInfo*)&a->unpack[idx] : nullptr;
}

size_t Activation::GetCommBufSize() {
  ActImpl* a = A(this);
  return a->wire * dt_size(a->dt);
}

void* Activation::GetCommBuf() {
  ActImpl* a = A(this);
  if (a->wire == 0) return nullptr;
  std::lock_guard<std::mutex> lk(a->bufs_mu);
  std::vector<char>& b = a->comm_bufs[tl_rank];
  if (b.empty()) b.resize(a->wire * dt_size(a->dt));
  return b.data();
}

void Activation::StartComm(void* buf) {
  ActImpl* a = A(this);
  if (a->wire == 0) return;  // no comm on this edge (reference: empty request)
  uint64_t my_h = a->h;
  uint64_t peer_h = a->peer != nullptr ? a->peer->h : 0;
  int dt = a->dt;
  channel_start(
      a->ch, buf, a->wire, dt_size(dt), (int64_t)a->recvn, nullptr,
      [my_h, dt](const void* world) {
        if (mlsl_activation_start_comm(my_h, world, (mlsl_data_type_t)dt) !=
            MLSL_TPU_SUCCESS)
          die("StartComm failed");
      },
      [peer_h, dt](void* dst) -> int64_t {
        /* the PEER owns the wait side (reference src/mlsl_impl.cpp:377-380) */
        int64_t n = mlsl_activation_wait_comm(peer_h, dst,
                                              (mlsl_data_type_t)dt);
        if (n < 0) die("WaitComm failed");
        return n;
      });
}

void* Activation::WaitComm() {
  ActImpl* a = A(this);
  ActImpl* started = a->peer;  // waits the peer's transfer
  if (started == nullptr || started->wire == 0) return nullptr;
  return channel_wait(started->ch);
}

/* ---- ParameterSet ------------------------------------------------------ */

namespace {
PSImpl* P(ParameterSet* p) { return (PSImpl*)p; }

int64_t ps_q(PSImpl* p, int what) {
  switch (what) {
    case 0: return mlsl_parameter_set_get_global_kernel_count(p->oph, p->idx);
    case 1: return mlsl_parameter_set_get_local_kernel_count(p->oph, p->idx);
    case 2: return mlsl_parameter_set_get_owned_kernel_count(p->oph, p->idx);
    case 3: return mlsl_parameter_set_get_kernel_size(p->oph, p->idx);
    default: return mlsl_parameter_set_is_distributed_update(p->oph, p->idx);
  }
}
}  // namespace

size_t ParameterSet::GetGlobalKernelCount() { return (size_t)ps_q(P(this), 0); }
size_t ParameterSet::GetLocalKernelCount() { return (size_t)ps_q(P(this), 1); }
size_t ParameterSet::GetOwnedKernelCount() { return (size_t)ps_q(P(this), 2); }
size_t ParameterSet::GetKernelSize() { return (size_t)ps_q(P(this), 3); }
bool ParameterSet::IsDistributedUpdate() { return ps_q(P(this), 4) != 0; }
DataType ParameterSet::GetDataType() { return (DataType)P(this)->dt; }

size_t ParameterSet::GetGlobalKernelOffset() {
  PSImpl* p = P(this);
  int64_t model_idx = mlsl_distribution_get_process_idx(
      p->op->dist->h, MLSL_GT_MODEL, tl_rank);
  return GetLocalKernelCount() * (size_t)model_idx;
}

size_t ParameterSet::GetOwnedKernelOffset() {
  PSImpl* p = P(this);
  int64_t data_idx = mlsl_distribution_get_process_idx(
      p->op->dist->h, MLSL_GT_DATA, tl_rank);
  return (size_t)mlsl_parameter_set_get_owned_kernel_offset(p->oph, p->idx,
                                                            data_idx);
}

void ParameterSet::StartGradientComm(void* buf) {
  PSImpl* p = P(this);
  size_t local = GetLocalKernelCount() * GetKernelSize();
  size_t owned = GetOwnedKernelCount() * GetKernelSize();
  int64_t recvn =
      (int64_t)(IsDistributedUpdate() ? owned : local);  // rs vs allreduce
  uint64_t oph = p->oph;
  int idx = p->idx, dt = p->dt;
  channel_start(
      p->grad_ch, buf, local, dt_size(dt), recvn, nullptr,
      [oph, idx, dt](const void* world) {
        if (mlsl_parameter_set_start_gradient_comm(
                oph, idx, world, (mlsl_data_type_t)dt) != MLSL_TPU_SUCCESS)
          die("StartGradientComm failed");
      },
      [oph, idx, dt](void* dst) -> int64_t {
        int64_t n = mlsl_parameter_set_wait_gradient_comm(
            oph, idx, dst, (mlsl_data_type_t)dt);
        if (n < 0) die("WaitGradientComm failed");
        return n;
      });
}

void* ParameterSet::WaitGradientComm() { return channel_wait(P(this)->grad_ch); }

void* ParameterSet::TestGradientComm(bool* isCompleted) {
  PSImpl* p = P(this);
  uint64_t oph = p->oph;
  int idx = p->idx;
  return channel_test(
      p->grad_ch,
      [oph, idx] { return mlsl_parameter_set_test_gradient_comm(oph, idx); },
      isCompleted);
}

void ParameterSet::StartIncrementComm(void* buf) {
  PSImpl* p = P(this);
  if (!IsDistributedUpdate()) {
    /* reference: the increment request is empty without distributed update —
     * Start/Wait are no-ops (src/mlsl_impl.cpp:388-444) */
    return;
  }
  size_t ksize = GetKernelSize();
  size_t owned = GetOwnedKernelCount() * ksize;
  size_t local = GetLocalKernelCount() * ksize;
  size_t esz = dt_size(p->dt);
  /* the caller passes the FULL local parameter buffer; this rank contributes
   * its owned shard and the gathered result lands back in the full buffer
   * (in-place AllGather, reference mlsl_test.cpp:521-526) */
  const char* shard = (const char*)buf + GetOwnedKernelOffset() * ksize * esz;
  uint64_t oph = p->oph;
  int idx = p->idx, dt = p->dt;
  channel_start(
      p->inc_ch, shard, owned, esz, (int64_t)local, buf,
      [oph, idx, dt](const void* world) {
        if (mlsl_parameter_set_start_increment_comm(
                oph, idx, world, (mlsl_data_type_t)dt) != MLSL_TPU_SUCCESS)
          die("StartIncrementComm failed");
      },
      [oph, idx, dt](void* dst) -> int64_t {
        int64_t n = mlsl_parameter_set_wait_increment_comm(
            oph, idx, dst, (mlsl_data_type_t)dt);
        if (n < 0) die("WaitIncrementComm failed");
        return n;
      });
}

void* ParameterSet::WaitIncrementComm() {
  PSImpl* p = P(this);
  if (!IsDistributedUpdate()) return nullptr;
  return channel_wait(p->inc_ch);
}

/* ---- Statistics -------------------------------------------------------- */

namespace {
StatsImpl* ST(Statistics* s) { return (StatsImpl*)s; }
}  // namespace

void Statistics::Start() {
  shared_call([&]() -> uint64_t { return mlsl_statistics_start(ST(this)->h); });
}
void Statistics::Stop() {
  shared_call([&]() -> uint64_t { return mlsl_statistics_stop(ST(this)->h); });
}
void Statistics::Reset() {
  shared_call([&]() -> uint64_t { return mlsl_statistics_reset(ST(this)->h); });
}
bool Statistics::IsStarted() {
  return mlsl_statistics_is_started(ST(this)->h) == 1;
}
bool Statistics::IsEnabled() {
  return mlsl_statistics_is_enabled(ST(this)->h) == 1;
}
void Statistics::Print() {
  shared_call([&]() -> uint64_t { return mlsl_statistics_print(ST(this)->h); });
}
unsigned long long Statistics::GetIsolationCommCycles(size_t opIdx) {
  return (unsigned long long)mlsl_statistics_get_isolation_comm_cycles(
      ST(this)->h, (int64_t)opIdx);
}
size_t Statistics::GetCommSize(size_t opIdx) {
  return (size_t)mlsl_statistics_get_comm_size(ST(this)->h, (int64_t)opIdx);
}
unsigned long long Statistics::GetCommCycles(size_t opIdx) {
  return (unsigned long long)mlsl_statistics_get_comm_cycles(ST(this)->h,
                                                             (int64_t)opIdx);
}
unsigned long long Statistics::GetComputeCycles(size_t opIdx) {
  return (unsigned long long)mlsl_statistics_get_compute_cycles(ST(this)->h,
                                                                (int64_t)opIdx);
}
unsigned long long Statistics::GetTotalIsolationCommCycles() {
  return (unsigned long long)mlsl_statistics_get_total_isolation_comm_cycles(
      ST(this)->h);
}
size_t Statistics::GetTotalCommSize() {
  return (size_t)mlsl_statistics_get_total_comm_size(ST(this)->h);
}
unsigned long long Statistics::GetTotalCommCycles() {
  return (unsigned long long)mlsl_statistics_get_total_comm_cycles(ST(this)->h);
}
unsigned long long Statistics::GetTotalComputeCycles() {
  return (unsigned long long)mlsl_statistics_get_total_compute_cycles(
      ST(this)->h);
}

}  // namespace MLSL
