/* End-to-end C consumer of the mlsl_tpu C API: allreduce through a
 * Distribution + a 2-op Session with gradient sync — the same flow as the
 * reference's cmlsl_test (tests/examples/mlsl_test/cmlsl_test.c), compressed.
 * Exits 0 on success; prints FAILED lines otherwise. */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "../include/mlsl_tpu.h"

#define CHECK(cond, msg)                              \
  do {                                                \
    if (!(cond)) {                                    \
      fprintf(stderr, "FAILED: %s\n", msg);           \
      return 1;                                       \
    }                                                 \
  } while (0)

int main(void) {
  CHECK(mlsl_environment_init() == MLSL_TPU_SUCCESS, "env init");
  int64_t world = mlsl_environment_get_process_count();
  CHECK(world >= 1, "process count");
  printf("world = %lld\n", (long long)world);

  mlsl_handle_t dist = mlsl_environment_create_distribution(world, 1, 1);
  CHECK(dist != 0, "create distribution");
  CHECK(mlsl_distribution_get_process_count(dist, MLSL_GT_DATA) == world,
        "data group size");

  /* allreduce: rank p contributes p+1 everywhere; expect world*(world+1)/2 */
  const int64_t n = 16;
  float* send = malloc(sizeof(float) * world * n);
  float* recv = malloc(sizeof(float) * world * n);
  for (int64_t p = 0; p < world; ++p)
    for (int64_t i = 0; i < n; ++i) send[p * n + i] = (float)(p + 1);
  mlsl_handle_t req = mlsl_distribution_all_reduce(dist, send, n, MLSL_DT_FLOAT,
                                                   MLSL_RT_SUM, MLSL_GT_DATA);
  CHECK(req != 0, "allreduce start");
  CHECK(mlsl_request_wait(req, recv, n, MLSL_DT_FLOAT) == MLSL_TPU_SUCCESS,
        "allreduce wait");
  float expect = (float)(world * (world + 1) / 2);
  for (int64_t p = 0; p < world; ++p)
    for (int64_t i = 0; i < n; ++i)
      CHECK(recv[p * n + i] == expect, "allreduce value");
  printf("allreduce OK (%.0f)\n", expect);

  /* session graph with per-layer gradient sync */
  mlsl_handle_t sess = mlsl_environment_create_session();
  CHECK(sess != 0, "create session");
  CHECK(mlsl_session_set_global_minibatch_size(sess, 4 * world) == 0, "set mb");

  mlsl_handle_t reg1 = mlsl_session_create_operation_reg_info(sess, MLSL_OT_CC);
  mlsl_operation_reg_info_add_input(reg1, 8, 4, MLSL_DT_FLOAT);
  mlsl_operation_reg_info_add_output(reg1, 8, 4, MLSL_DT_FLOAT);
  mlsl_operation_reg_info_add_parameter_set(reg1, 64, 1, MLSL_DT_FLOAT, 0,
                                            MLSL_CT_NONE);
  mlsl_handle_t op1 = mlsl_session_add_operation(sess, reg1, dist);
  CHECK(op1 != 0, "add op1");

  mlsl_handle_t reg2 = mlsl_session_create_operation_reg_info(sess, MLSL_OT_CC);
  mlsl_operation_reg_info_add_input(reg2, 8, 4, MLSL_DT_FLOAT);
  mlsl_operation_reg_info_add_output(reg2, 8, 4, MLSL_DT_FLOAT);
  mlsl_operation_reg_info_add_parameter_set(reg2, 64, 1, MLSL_DT_FLOAT, 1,
                                            MLSL_CT_NONE);
  mlsl_handle_t op2 = mlsl_session_add_operation(sess, reg2, dist);
  CHECK(op2 != 0, "add op2");
  CHECK(mlsl_operation_set_next(op1, op2, 0, 0) == 0, "wire edge");
  CHECK(mlsl_session_commit(sess) == 0, "commit");

  int64_t cnt = mlsl_operation_get_parameter_local_count(op1, 0);
  CHECK(cnt == 64, "param local count");
  float* grads = malloc(sizeof(float) * world * cnt);
  for (int64_t p = 0; p < world; ++p)
    for (int64_t i = 0; i < cnt; ++i) grads[p * cnt + i] = (float)i;
  CHECK(mlsl_parameter_set_start_gradient_comm(op1, 0, grads, MLSL_DT_FLOAT) ==
            0, "start grad comm");
  float* gout = malloc(sizeof(float) * world * cnt);
  int64_t got = mlsl_parameter_set_wait_gradient_comm(op1, 0, gout,
                                                      MLSL_DT_FLOAT);
  if (world > 1) {
    CHECK(got == cnt, "grad recv count");
    for (int64_t i = 0; i < cnt; ++i)
      CHECK(gout[i] == (float)(i * world), "grad value");
    printf("grad allreduce OK\n");
    /* distributed-update op: reduce-scattered owned shard */
    int64_t owned = mlsl_operation_get_parameter_owned_count(op2, 0);
    int64_t local2 = mlsl_operation_get_parameter_local_count(op2, 0);
    CHECK(owned * world == local2, "owned partitioning");
    CHECK(mlsl_parameter_set_start_gradient_comm(op2, 0, grads, MLSL_DT_FLOAT)
          == 0, "du start");
    int64_t got2 = mlsl_parameter_set_wait_gradient_comm(op2, 0, gout,
                                                         MLSL_DT_FLOAT);
    CHECK(got2 == owned, "du recv count");
    printf("distributed-update reduce-scatter OK (owned=%lld)\n",
           (long long)owned);
  } else {
    CHECK(got == 0, "no comm on single process");
    printf("single-process no-comm OK\n");
  }

  /* full collective surface: reduce, gather, scatter, send_recv_list */
  if (world > 1) {
    mlsl_handle_t r = mlsl_distribution_reduce(dist, send, n, MLSL_DT_FLOAT,
                                               MLSL_RT_MAX, 0, MLSL_GT_DATA);
    CHECK(r != 0 && mlsl_request_wait(r, recv, n, MLSL_DT_FLOAT) == 0, "reduce");
    CHECK(recv[0] == (float)world, "reduce max value");

    mlsl_handle_t g = mlsl_distribution_gather(dist, send, n, MLSL_DT_FLOAT, 0,
                                               MLSL_GT_DATA);
    float* gout2 = malloc(sizeof(float) * world * world * n);
    CHECK(g != 0 && mlsl_request_wait(g, gout2, world * n, MLSL_DT_FLOAT) == 0,
          "gather");
    CHECK(gout2[0] == 1.0f && gout2[n] == 2.0f, "gather layout");

    mlsl_handle_t sc = mlsl_distribution_scatter(dist, gout2, world * n,
                                                 MLSL_DT_FLOAT, 0, MLSL_GT_DATA);
    CHECK(sc != 0 && mlsl_request_wait(sc, recv, n, MLSL_DT_FLOAT) == 0,
          "scatter");
    CHECK(recv[0] == 1.0f && recv[(world - 1) * n] == (float)world,
          "scatter placement");

    int64_t* pairs = malloc(sizeof(int64_t) * 2 * world);
    for (int64_t i = 0; i < world; ++i) {
      pairs[2 * i] = i;
      pairs[2 * i + 1] = (i + 1) % world;  /* ring shift */
    }
    mlsl_handle_t sr = mlsl_distribution_send_recv_list(
        dist, send, n, MLSL_DT_FLOAT, pairs, world, MLSL_GT_DATA);
    CHECK(sr != 0 && mlsl_request_wait(sr, recv, n, MLSL_DT_FLOAT) == 0,
          "send_recv_list");
    CHECK(recv[0] == (float)world, "ring shift value"); /* rank0 <- rank world-1 */
    printf("reduce/gather/scatter/sendrecv OK\n");
    free(gout2);
    free(pairs);
  }

  CHECK(mlsl_distribution_barrier(dist, MLSL_GT_GLOBAL) == 0, "barrier");
  CHECK(mlsl_environment_finalize() == 0, "finalize");
  printf("C API TEST PASSED\n");
  free(send); free(recv); free(grads); free(gout);
  return 0;
}
