/* End-to-end C consumer of the mlsl_tpu C API: allreduce through a
 * Distribution + a 2-op Session with gradient sync — the same flow as the
 * reference's cmlsl_test (tests/examples/mlsl_test/cmlsl_test.c), compressed.
 * Exits 0 on success; prints FAILED lines otherwise. */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "../include/mlsl_tpu.h"

#define CHECK(cond, msg)                              \
  do {                                                \
    if (!(cond)) {                                    \
      fprintf(stderr, "FAILED: %s\n", msg);           \
      return 1;                                       \
    }                                                 \
  } while (0)

int main(void) {
  CHECK(mlsl_environment_init() == MLSL_TPU_SUCCESS, "env init");
  int64_t world = mlsl_environment_get_process_count();
  CHECK(world >= 1, "process count");
  printf("world = %lld\n", (long long)world);

  mlsl_handle_t dist = mlsl_environment_create_distribution(world, 1, 1);
  CHECK(dist != 0, "create distribution");
  CHECK(mlsl_distribution_get_process_count(dist, MLSL_GT_DATA) == world,
        "data group size");

  /* allreduce: rank p contributes p+1 everywhere; expect world*(world+1)/2 */
  const int64_t n = 16;
  float* send = malloc(sizeof(float) * world * n);
  float* recv = malloc(sizeof(float) * world * n);
  for (int64_t p = 0; p < world; ++p)
    for (int64_t i = 0; i < n; ++i) send[p * n + i] = (float)(p + 1);
  mlsl_handle_t req = mlsl_distribution_all_reduce(dist, send, n, MLSL_DT_FLOAT,
                                                   MLSL_RT_SUM, MLSL_GT_DATA);
  CHECK(req != 0, "allreduce start");
  CHECK(mlsl_request_wait(req, recv, n, MLSL_DT_FLOAT) == MLSL_TPU_SUCCESS,
        "allreduce wait");
  float expect = (float)(world * (world + 1) / 2);
  for (int64_t p = 0; p < world; ++p)
    for (int64_t i = 0; i < n; ++i)
      CHECK(recv[p * n + i] == expect, "allreduce value");
  printf("allreduce OK (%.0f)\n", expect);

  /* session graph with per-layer gradient sync */
  mlsl_handle_t sess = mlsl_environment_create_session();
  CHECK(sess != 0, "create session");
  CHECK(mlsl_session_set_global_minibatch_size(sess, 4 * world) == 0, "set mb");

  mlsl_handle_t reg1 = mlsl_session_create_operation_reg_info(sess, MLSL_OT_CC);
  mlsl_operation_reg_info_add_input(reg1, 8, 4, MLSL_DT_FLOAT);
  mlsl_operation_reg_info_add_output(reg1, 8, 4, MLSL_DT_FLOAT);
  mlsl_operation_reg_info_add_parameter_set(reg1, 64, 1, MLSL_DT_FLOAT, 0,
                                            MLSL_CT_NONE);
  mlsl_handle_t op1 = mlsl_session_add_operation(sess, reg1, dist);
  CHECK(op1 != 0, "add op1");

  mlsl_handle_t reg2 = mlsl_session_create_operation_reg_info(sess, MLSL_OT_CC);
  mlsl_operation_reg_info_add_input(reg2, 8, 4, MLSL_DT_FLOAT);
  mlsl_operation_reg_info_add_output(reg2, 8, 4, MLSL_DT_FLOAT);
  mlsl_operation_reg_info_add_parameter_set(reg2, 64, 1, MLSL_DT_FLOAT, 1,
                                            MLSL_CT_NONE);
  mlsl_handle_t op2 = mlsl_session_add_operation(sess, reg2, dist);
  CHECK(op2 != 0, "add op2");
  CHECK(mlsl_operation_set_next(op1, op2, 0, 0) == 0, "wire edge");
  CHECK(mlsl_session_commit(sess) == 0, "commit");

  int64_t cnt = mlsl_operation_get_parameter_local_count(op1, 0);
  CHECK(cnt == 64, "param local count");
  float* grads = malloc(sizeof(float) * world * cnt);
  for (int64_t p = 0; p < world; ++p)
    for (int64_t i = 0; i < cnt; ++i) grads[p * cnt + i] = (float)i;
  CHECK(mlsl_parameter_set_start_gradient_comm(op1, 0, grads, MLSL_DT_FLOAT) ==
            0, "start grad comm");
  float* gout = malloc(sizeof(float) * world * cnt);
  int64_t got = mlsl_parameter_set_wait_gradient_comm(op1, 0, gout,
                                                      MLSL_DT_FLOAT);
  if (world > 1) {
    CHECK(got == cnt, "grad recv count");
    for (int64_t i = 0; i < cnt; ++i)
      CHECK(gout[i] == (float)(i * world), "grad value");
    printf("grad allreduce OK\n");
    /* distributed-update op: reduce-scattered owned shard */
    int64_t owned = mlsl_operation_get_parameter_owned_count(op2, 0);
    int64_t local2 = mlsl_operation_get_parameter_local_count(op2, 0);
    CHECK(owned * world == local2, "owned partitioning");
    CHECK(mlsl_parameter_set_start_gradient_comm(op2, 0, grads, MLSL_DT_FLOAT)
          == 0, "du start");
    int64_t got2 = mlsl_parameter_set_wait_gradient_comm(op2, 0, gout,
                                                         MLSL_DT_FLOAT);
    CHECK(got2 == owned, "du recv count");
    printf("distributed-update reduce-scatter OK (owned=%lld)\n",
           (long long)owned);
  } else {
    CHECK(got == 0, "no comm on single process");
    printf("single-process no-comm OK\n");
  }

  /* full collective surface: reduce, gather, scatter, send_recv_list */
  if (world > 1) {
    mlsl_handle_t r = mlsl_distribution_reduce(dist, send, n, MLSL_DT_FLOAT,
                                               MLSL_RT_MAX, 0, MLSL_GT_DATA);
    CHECK(r != 0 && mlsl_request_wait(r, recv, n, MLSL_DT_FLOAT) == 0, "reduce");
    CHECK(recv[0] == (float)world, "reduce max value");

    mlsl_handle_t g = mlsl_distribution_gather(dist, send, n, MLSL_DT_FLOAT, 0,
                                               MLSL_GT_DATA);
    float* gout2 = malloc(sizeof(float) * world * world * n);
    CHECK(g != 0 && mlsl_request_wait(g, gout2, world * n, MLSL_DT_FLOAT) == 0,
          "gather");
    CHECK(gout2[0] == 1.0f && gout2[n] == 2.0f, "gather layout");

    mlsl_handle_t sc = mlsl_distribution_scatter(dist, gout2, world * n,
                                                 MLSL_DT_FLOAT, 0, MLSL_GT_DATA);
    CHECK(sc != 0 && mlsl_request_wait(sc, recv, n, MLSL_DT_FLOAT) == 0,
          "scatter");
    CHECK(recv[0] == 1.0f && recv[(world - 1) * n] == (float)world,
          "scatter placement");

    int64_t* pairs = malloc(sizeof(int64_t) * 2 * world);
    for (int64_t i = 0; i < world; ++i) {
      pairs[2 * i] = i;
      pairs[2 * i + 1] = (i + 1) % world;  /* ring shift */
    }
    mlsl_handle_t sr = mlsl_distribution_send_recv_list(
        dist, send, n, MLSL_DT_FLOAT, pairs, world, MLSL_GT_DATA);
    CHECK(sr != 0 && mlsl_request_wait(sr, recv, n, MLSL_DT_FLOAT) == 0,
          "send_recv_list");
    CHECK(recv[0] == (float)world, "ring shift value"); /* rank0 <- rank world-1 */
    printf("reduce/gather/scatter/sendrecv OK\n");
    free(gout2);
    free(pairs);
  }

  /* v-collectives: AllGatherv + AlltoAllv (reference mlsl.hpp:418-471) */
  if (world > 1) {
    int64_t* vcounts = malloc(sizeof(int64_t) * world);
    int64_t vtotal = 0;
    for (int64_t i = 0; i < world; ++i) { vcounts[i] = i % 3 + 1; vtotal += vcounts[i]; }
    mlsl_handle_t agv = mlsl_distribution_all_gatherv(
        dist, send, n, vcounts, MLSL_DT_FLOAT, MLSL_GT_DATA);
    float* vout = malloc(sizeof(float) * world * vtotal);
    CHECK(agv != 0, "allgatherv start");
    CHECK(mlsl_request_wait(agv, vout, vtotal, MLSL_DT_FLOAT) == 0,
          "allgatherv wait");
    /* rank0's view: first vcounts[0] elems are rank0's (=1.0), next vcounts[1]
     * are rank1's (=2.0) */
    CHECK(vout[0] == 1.0f && vout[vcounts[0]] == 2.0f, "allgatherv layout");

    int64_t* a2acnt = malloc(sizeof(int64_t) * world);
    for (int64_t i = 0; i < world; ++i) a2acnt[i] = 2;  /* 2 elems to each */
    mlsl_handle_t a2av = mlsl_distribution_all_to_allv(
        dist, send, 2 * world, a2acnt, NULL, NULL, MLSL_DT_FLOAT, MLSL_GT_DATA);
    float* a2aout = malloc(sizeof(float) * world * 2 * world);
    CHECK(a2av != 0 &&
              mlsl_request_wait(a2av, a2aout, 2 * world, MLSL_DT_FLOAT) == 0,
          "alltoallv");
    /* rank0 receives 2 elems from each rank q, value q+1 */
    for (int64_t q = 0; q < world; ++q)
      CHECK(a2aout[2 * q] == (float)(q + 1), "alltoallv value");
    printf("allgatherv/alltoallv OK\n");
    free(vcounts); free(vout); free(a2acnt); free(a2aout);
  }

  /* general per-rank AlltoAllv through the dedicated full-matrix entry
   * (mlsl_distribution_all_to_allv_full) on MODEL subgroups of a 2-model
   * distribution, so world != group and the engine's true per-rank
   * (world, group) table path runs (different instances exchange different
   * geometries). S[w][j] = (3w + j) % 2 + 1 varies per WORLD rank; member j
   * of w's instance is world rank base+j (model-minor layout, base = w -
   * w%2); recv geometry R[w][j] = S[base+j][w%2] supplied explicitly.
   * Payload: rank w's send buffer = w*100 + idx. */
  if (world > 1 && world % 2 == 0) {
    const int64_t G = 2;
    mlsl_handle_t mdist =
        mlsl_environment_create_distribution(world / G, G, 1);
    CHECK(mdist != 0, "alltoallv_full distribution");
    int64_t* S = malloc(sizeof(int64_t) * world * G);
    int64_t* R = malloc(sizeof(int64_t) * world * G);
    int64_t send_slot = 0, recv_slot = 0;
    for (int64_t w = 0; w < world; ++w) {
      int64_t base = w - (w % G), ssum = 0, rsum = 0;
      for (int64_t j = 0; j < G; ++j) {
        S[w * G + j] = (3 * w + j) % 2 + 1;
        R[w * G + j] = (3 * (base + j) + (w % G)) % 2 + 1; /* = S[base+j][w%G] */
        ssum += S[w * G + j];
        rsum += R[w * G + j];
      }
      if (ssum > send_slot) send_slot = ssum;
      if (rsum > recv_slot) recv_slot = rsum;
    }
    float* fsend = malloc(sizeof(float) * world * send_slot);
    for (int64_t w = 0; w < world; ++w)
      for (int64_t i = 0; i < send_slot; ++i)
        fsend[w * send_slot + i] = (float)(w * 100 + i);
    mlsl_handle_t fh = mlsl_distribution_all_to_allv_full(
        mdist, fsend, send_slot, S, NULL, R, NULL, MLSL_DT_FLOAT,
        MLSL_GT_MODEL);
    float* fout = malloc(sizeof(float) * world * recv_slot);
    CHECK(fh != 0 &&
              mlsl_request_wait(fh, fout, recv_slot, MLSL_DT_FLOAT) == 0,
          "alltoallv_full");
    /* every rank's packed receive blocks: block from its instance member j
     * (world rank q = base + j) has S[q][w%G] elems, values q*100 + (q's
     * packed offset toward position w%G) + k */
    for (int64_t w = 0; w < world; ++w) {
      int64_t base = w - (w % G), roff = 0;
      for (int64_t j = 0; j < G; ++j) {
        int64_t q = base + j, qoff = 0;
        for (int64_t t = 0; t < w % G; ++t) qoff += (3 * q + t) % 2 + 1;
        for (int64_t k = 0; k < S[q * G + (w % G)]; ++k)
          CHECK(fout[w * recv_slot + roff + k] == (float)(q * 100 + qoff + k),
                "alltoallv_full value");
        roff += S[q * G + (w % G)];
      }
    }
    printf("alltoallv_full per-rank OK\n");
    mlsl_handle_release(mdist);
    free(S); free(R); free(fsend); free(fout);
  }

  /* ---- model-parallel training through the activation API: the reference
   * cmlsl_test flow (pack via queried blocks -> StartComm -> peer WaitComm ->
   * unpack; case-1 ReduceScatter fwd / AllGather bwd) ---- */
  if (world >= 4 && world % 2 == 0) {
    const int64_t MP = 2, DP = world / 2, FM = 8, FMS = 4;
    mlsl_handle_t dmp = mlsl_environment_create_distribution(DP, MP, 1);
    CHECK(dmp != 0, "create mp distribution");
    mlsl_handle_t s2 = mlsl_environment_create_session();
    CHECK(mlsl_session_set_global_minibatch_size(s2, 4 * DP) == 0, "mp mb");
    mlsl_handle_t rga = mlsl_session_create_operation_reg_info(s2, MLSL_OT_CC);
    mlsl_operation_reg_info_add_input(rga, FM, FMS, MLSL_DT_FLOAT);
    mlsl_operation_reg_info_add_output(rga, FM, FMS, MLSL_DT_FLOAT);
    mlsl_operation_reg_info_add_parameter_set(rga, FM * FM, 1, MLSL_DT_FLOAT, 0,
                                              MLSL_CT_NONE);
    mlsl_handle_t opa = mlsl_session_add_operation(s2, rga, dmp);
    mlsl_handle_t rgb = mlsl_session_create_operation_reg_info(s2, MLSL_OT_CC);
    mlsl_operation_reg_info_add_input(rgb, FM, FMS, MLSL_DT_FLOAT);
    mlsl_operation_reg_info_add_output(rgb, FM, FMS, MLSL_DT_FLOAT);
    mlsl_operation_reg_info_add_parameter_set(rgb, FM * FM, 1, MLSL_DT_FLOAT, 1,
                                              MLSL_CT_NONE);
    mlsl_handle_t opb = mlsl_session_add_operation(s2, rgb, dmp);
    CHECK(mlsl_operation_set_next(opa, opb, 0, 0) == 0, "mp wire");
    CHECK(mlsl_session_commit(s2) == 0, "mp commit");

    mlsl_handle_t oact = mlsl_operation_get_output(opa, 0);
    mlsl_handle_t iact = mlsl_operation_get_input(opb, 0);
    CHECK(oact != 0 && iact != 0, "activation handles");
    CHECK(mlsl_activation_needs_comm(oact) == 1, "out needs comm");
    CHECK(mlsl_activation_get_global_fm_count(oact) == FM, "fm count");
    CHECK(mlsl_activation_get_local_fm_count(iact) == FM / MP, "in local fm");
    int64_t lmb = mlsl_operation_get_local_minibatch_size(opa);
    CHECK(lmb == 4, "mp local minibatch");
    int64_t wire = mlsl_activation_get_wire_count(oact);
    CHECK(wire == lmb * FM * FMS, "wire count");

    /* forward: every rank's activation act[mb][fm][sp] = rank*1000 + linear;
     * pack through the QUERIED CommBlockInfo blocks, exactly like the
     * reference's PackBuffer (mlsl_test.cpp:214-233) */
    int64_t nblk = mlsl_activation_get_pack_block_count(oact);
    CHECK(nblk == MP, "pack block count");
    float* wires = malloc(sizeof(float) * world * wire);
    for (int64_t p = 0; p < world; ++p) {
      for (int64_t b = 0; b < nblk; ++b) {
        int64_t mbo = mlsl_activation_get_pack_block(oact, b, 0);
        int64_t mbc = mlsl_activation_get_pack_block(oact, b, 1);
        int64_t fmo = mlsl_activation_get_pack_block(oact, b, 2);
        int64_t fmc = mlsl_activation_get_pack_block(oact, b, 3);
        int64_t fms = mlsl_activation_get_pack_block(oact, b, 4);
        int64_t off = mlsl_activation_get_pack_block(oact, b, 5);
        int64_t k = 0;
        for (int64_t mb = mbo; mb < mbo + mbc; ++mb)
          for (int64_t fm = fmo; fm < fmo + fmc; ++fm)
            for (int64_t sp = 0; sp < fms; ++sp, ++k)
              wires[p * wire + off + k] =
                  (float)(p * 1000 + (mb * FM + fm) * FMS + sp);
      }
    }
    CHECK(mlsl_activation_start_comm(oact, wires, MLSL_DT_FLOAT) == 0,
          "activation start comm");
    float* arecv = malloc(sizeof(float) * world * wire);
    int64_t rc = mlsl_activation_wait_comm(iact, arecv, MLSL_DT_FLOAT);
    CHECK(rc == wire / MP, "fwd recv count");
    /* oracle: model group of p = {g0, g0+1}, g0 = (p/MP)*MP (model minor);
     * ReduceScatter hands member m slice m of the group sum */
    for (int64_t p = 0; p < world; ++p) {
      int64_t g0 = (p / MP) * MP, m = p % MP;
      for (int64_t i = 0; i < rc; ++i) {
        float want = 0;
        for (int64_t j = 0; j < MP; ++j)
          want += wires[(g0 + j) * wire + m * rc + i];
        CHECK(arecv[p * rc + i] == want, "fwd activation value");
      }
    }
    printf("activation fwd ReduceScatter OK\n");

    /* backward: input-activation grads AllGather back to the output side */
    float* bsend = malloc(sizeof(float) * world * rc);
    for (int64_t p = 0; p < world; ++p)
      for (int64_t i = 0; i < rc; ++i)
        bsend[p * rc + i] = (float)(p * 100 + i);
    CHECK(mlsl_activation_start_comm(iact, bsend, MLSL_DT_FLOAT) == 0,
          "bwd start");
    float* brecv = malloc(sizeof(float) * world * wire);
    int64_t brc = mlsl_activation_wait_comm(oact, brecv, MLSL_DT_FLOAT);
    CHECK(brc == wire, "bwd recv count");
    for (int64_t p = 0; p < world; ++p) {
      int64_t g0 = (p / MP) * MP;
      for (int64_t j = 0; j < MP; ++j)
        for (int64_t i = 0; i < rc; ++i)
          CHECK(brecv[p * wire + j * rc + i] == bsend[(g0 + j) * rc + i],
                "bwd activation value");
    }
    printf("activation bwd AllGather OK\n");

    /* distributed-update increments: ReduceScatter'd grads were checked above;
     * here the owned-shard AllGather (reference mlsl.hpp:318-331) */
    int64_t owned = mlsl_parameter_set_get_owned_kernel_count(opb, 0) *
                    mlsl_parameter_set_get_kernel_size(opb, 0);
    int64_t local = mlsl_parameter_set_get_local_kernel_count(opb, 0) *
                    mlsl_parameter_set_get_kernel_size(opb, 0);
    CHECK(mlsl_parameter_set_is_distributed_update(opb, 0) == 1, "du flag");
    float* incs = malloc(sizeof(float) * world * owned);
    for (int64_t p = 0; p < world; ++p)
      for (int64_t i = 0; i < owned; ++i) incs[p * owned + i] = (float)(p + 1);
    CHECK(mlsl_parameter_set_start_increment_comm(opb, 0, incs, MLSL_DT_FLOAT)
              == 0, "inc start");
    float* irecv = malloc(sizeof(float) * world * local);
    int64_t inc_n = mlsl_parameter_set_wait_increment_comm(opb, 0, irecv,
                                                           MLSL_DT_FLOAT);
    CHECK(inc_n == local, "inc recv count");
    /* grad group = data axis (model minor layout): member j of p's data group
     * is world rank j*MP + (p%MP) */
    for (int64_t p = 0; p < world; ++p)
      for (int64_t j = 0; j < DP; ++j)
        CHECK(irecv[p * local + j * owned] == (float)(j * MP + p % MP + 1),
              "inc value");
    printf("distributed-update increment AllGather OK\n");

    /* statistics queries (reference mlsl.hpp:651-726) */
    mlsl_handle_t st = mlsl_session_get_stats(s2);
    CHECK(st != 0, "stats handle");
    if (mlsl_statistics_is_enabled(st) == 1) {
      CHECK(mlsl_statistics_get_total_comm_size(st) > 0, "stats bytes");
      CHECK(mlsl_statistics_get_total_comm_cycles(st) >= 0, "stats cycles");
      CHECK(mlsl_statistics_get_comm_size(st, 0) +
                mlsl_statistics_get_comm_size(st, 1) ==
            mlsl_statistics_get_total_comm_size(st), "stats per-op sum");
      CHECK(mlsl_statistics_print(st) == 0, "stats print");
      {
        /* isolation stats were collected at commit (MLSL_STATS=1) and grad
         * comms were accounted above, so the total must be measurable */
        long long ov = (long long)mlsl_statistics_get_overlap_permille(st, -1);
        CHECK(ov >= 0 && ov <= 1000, "overlap permille measurable");
        CHECK(mlsl_statistics_get_overlap_permille(st, 99) == -1,
              "overlap out-of-range sentinel");
      }
      printf("statistics queries OK (bytes=%lld)\n",
             (long long)mlsl_statistics_get_total_comm_size(st));
    }
    free(wires); free(arecv); free(bsend); free(brecv); free(incs); free(irecv);
  }

  /* quantization params: defaults accepted; a bogus lib_path must FAIL
   * loudly, not be silently swallowed (reference quant_load ASSERTs). */
  CHECK(mlsl_environment_set_quantization_params(NULL, NULL, NULL, NULL,
                                                 256, 256) == 0,
        "quant params defaults");
  CHECK(mlsl_environment_set_quantization_params(
            "/nonexistent/libcodec.so", "c", "d", "r", 256, 256) != 0,
        "bogus codec lib must fail");
  printf("quantization params OK\n");

  CHECK(mlsl_distribution_barrier(dist, MLSL_GT_GLOBAL) == 0, "barrier");
  CHECK(mlsl_environment_finalize() == 0, "finalize");
  printf("C API TEST PASSED\n");
  free(send); free(recv); free(grads); free(gout);
  return 0;
}
