/* mlsl_core implementation — see mlsl_core.h for the contract and the
 * reference file:line provenance of each algorithm. */

#include "mlsl_core.h"

#include <algorithm>
#include <deque>
#include <mutex>
#include <unordered_set>

/* ---- grid math ---- */

int mlsl_grid_coords(int64_t rank, int64_t data_parts, int64_t seq_parts,
                     int64_t model_parts, int64_t coords[4]) {
  if (data_parts <= 0 || seq_parts <= 0 || model_parts <= 0 || rank < 0)
    return MLSL_FAIL;
  const int64_t l_size = data_parts * seq_parts * model_parts;
  const int64_t l_id = rank % l_size;
  coords[0] = rank / l_size;                       /* replica */
  coords[1] = l_id / (model_parts * seq_parts);    /* data */
  coords[2] = (l_id / model_parts) % seq_parts;    /* seq */
  coords[3] = l_id % model_parts;                  /* model */
  return MLSL_OK;
}

int64_t mlsl_grid_rank(const int64_t c[4], int64_t data_parts,
                       int64_t seq_parts, int64_t model_parts) {
  return ((c[0] * data_parts + c[1]) * seq_parts + c[2]) * model_parts + c[3];
}

int mlsl_grid_colors(int64_t rank, int64_t data_parts, int64_t model_parts,
                     int64_t* data_color, int64_t* model_color,
                     int64_t* replica_color) {
  /* exact reference formulas (src/mlsl_impl.hpp:224-240), seq absent there */
  if (data_parts <= 0 || model_parts <= 0 || rank < 0) return MLSL_FAIL;
  const int64_t l_size = data_parts * model_parts;
  const int64_t l_id = rank % l_size;
  const int64_t i_r = rank / l_size;
  const int64_t i_m = l_id / model_parts;
  const int64_t i_f = l_id % model_parts;
  if (model_color) *model_color = i_r * l_size + i_m;
  if (data_color) *data_color = i_r * l_size + i_f;
  if (replica_color) *replica_color = l_id;
  return MLSL_OK;
}

/* ---- case selection (reference src/mlsl_impl.cpp:139-241) ---- */

int mlsl_select_case(int out_need_reduce, int same_dist, int64_t world,
                     int64_t out_data, int64_t out_model, int64_t in_data,
                     int64_t in_model) {
  const bool need_comm = world > 1 && (out_need_reduce || !same_dist);
  if (!need_comm) return 0;
  if (out_need_reduce && same_dist) return 1;
  if (out_need_reduce && in_model == 1 && out_data == in_data) return 2;
  if (out_need_reduce && in_model == 1 && out_data > 0 &&
      in_data % out_data == 0 && in_data == out_model * out_data)
    return 3;
  if (!out_need_reduce && out_model == 1) return 4;
  if (!out_need_reduce && in_model == 1) return 5;
  return MLSL_FAIL;
}

/* ---- block layouts (reference src/mlsl_impl.cpp:243-347) ---- */

int mlsl_blocks_pack_reduce_scatter(int64_t model_parts, int64_t local_mb,
                                    int64_t local_fm, int64_t fm_size,
                                    mlsl_block_t* out) {
  if (model_parts <= 0 || local_fm % model_parts != 0) return MLSL_FAIL;
  const int64_t fm = local_fm / model_parts;
  for (int64_t i = 0; i < model_parts; ++i)
    out[i] = {0, local_mb, i * fm, fm, fm_size, i * local_mb * fm * fm_size};
  return MLSL_OK;
}

int mlsl_blocks_pack_reduce_scatter2(int64_t model_parts, int64_t local_mb,
                                     int64_t local_fm, int64_t fm_size,
                                     mlsl_block_t* out) {
  if (model_parts <= 0 || local_mb % model_parts != 0) return MLSL_FAIL;
  const int64_t mb = local_mb / model_parts;
  for (int64_t i = 0; i < model_parts; ++i)
    out[i] = {i * mb, mb, 0, local_fm, fm_size, i * mb * local_fm * fm_size};
  return MLSL_OK;
}

int mlsl_blocks_unpack_allgather(int64_t model_parts, int64_t local_mb,
                                 int64_t local_fm, int64_t fm_size,
                                 mlsl_block_t* out) {
  return mlsl_blocks_pack_reduce_scatter(model_parts, local_mb, local_fm,
                                         fm_size, out);
}

int mlsl_blocks_unpack_allgather2(int64_t model_parts, int64_t local_mb,
                                  int64_t local_fm, int64_t fm_size,
                                  mlsl_block_t* out) {
  return mlsl_blocks_pack_reduce_scatter2(model_parts, local_mb, local_fm,
                                          fm_size, out);
}

int64_t mlsl_blocks_alltoall(int64_t my_local_mb, int64_t my_local_fm,
                             int64_t my_fm_size, int64_t other_local_mb,
                             int64_t other_local_fm, int64_t other_fm_size,
                             mlsl_block_t* out) {
  const int64_t local_mb = std::min(my_local_mb, other_local_mb);
  const int64_t fmx =
      std::min(my_local_fm * my_fm_size, other_local_fm * other_fm_size);
  if (local_mb <= 0 || fmx <= 0 || fmx % my_fm_size != 0) return MLSL_FAIL;
  const int64_t my_fm = fmx / my_fm_size;
  int64_t idx = 0;
  for (int64_t i = 0; i < my_local_mb; i += local_mb)
    for (int64_t j = 0; j < my_local_fm; j += my_fm) {
      if (out)
        out[idx] = {i, local_mb, j, my_fm, my_fm_size, idx * local_mb * fmx};
      ++idx;
    }
  return idx;
}

/* ---- parameter-set partitioning ---- */

int mlsl_param_partition(int64_t global_kernel_count, int64_t model_parts,
                         int64_t grad_group_size, int distributed_update,
                         mlsl_param_part_t* out) {
  if (model_parts <= 0 || grad_group_size <= 0 ||
      global_kernel_count % model_parts != 0)
    return MLSL_FAIL;
  int64_t local = global_kernel_count / model_parts;
  int64_t owned = local;
  if (distributed_update) {
    owned = (local + grad_group_size - 1) / grad_group_size;
    local = owned * grad_group_size; /* padded (reference :403-405) */
  }
  out->local_kernel_count = local;
  out->owned_kernel_count = owned;
  out->need_comm = grad_group_size > 1 ? 1 : 0;
  return MLSL_OK;
}

/* ---- priority scheduler ---- */

struct mlsl_sched {
  int64_t threshold;
  bool lifo;
  std::deque<uint64_t> q;
  std::mutex mu;
};

mlsl_sched_t* mlsl_sched_create(int64_t threshold, int lifo) {
  auto* s = new mlsl_sched();
  s->threshold = threshold;
  s->lifo = lifo != 0;
  return s;
}

void mlsl_sched_destroy(mlsl_sched_t* s) { delete s; }

int mlsl_sched_submit(mlsl_sched_t* s, uint64_t req_id, int64_t bytes) {
  if (bytes <= s->threshold) return 1; /* small: dispatch immediately */
  std::lock_guard<std::mutex> lk(s->mu);
  /* a restart supersedes the stale entry */
  for (auto it = s->q.begin(); it != s->q.end();) {
    if (*it == req_id)
      it = s->q.erase(it);
    else
      ++it;
  }
  s->q.push_back(req_id);
  return 0;
}

int mlsl_sched_next(mlsl_sched_t* s, uint64_t* req_id) {
  std::lock_guard<std::mutex> lk(s->mu);
  if (s->q.empty()) return 0;
  if (s->lifo) {
    *req_id = s->q.back();
    s->q.pop_back();
  } else {
    *req_id = s->q.front();
    s->q.pop_front();
  }
  return 1;
}

int64_t mlsl_sched_pending(mlsl_sched_t* s) {
  std::lock_guard<std::mutex> lk(s->mu);
  return static_cast<int64_t>(s->q.size());
}

/* ---- request storage ---- */

struct mlsl_reqstore {
  std::unordered_set<uint64_t> live;
  std::mutex mu;
};

mlsl_reqstore_t* mlsl_reqstore_create(void) { return new mlsl_reqstore(); }
void mlsl_reqstore_destroy(mlsl_reqstore_t* r) { delete r; }

void mlsl_reqstore_register(mlsl_reqstore_t* r, uint64_t req_id) {
  std::lock_guard<std::mutex> lk(r->mu);
  r->live.insert(req_id);
}

void mlsl_reqstore_remove(mlsl_reqstore_t* r, uint64_t req_id) {
  std::lock_guard<std::mutex> lk(r->mu);
  r->live.erase(req_id);
}

int64_t mlsl_reqstore_size(mlsl_reqstore_t* r) {
  std::lock_guard<std::mutex> lk(r->mu);
  return static_cast<int64_t>(r->live.size());
}

const char* mlsl_core_version(void) { return "mlsl_core 0.1.0"; }
