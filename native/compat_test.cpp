/* compat_test — the reference's mlsl_test workload ported to the MLSL compat
 * surface (include/mlsl.hpp).
 *
 * Re-implements the reference correctness program's semantics
 * (tests/examples/mlsl_test/mlsl_test.cpp): a 2-layer CONV graph registered
 * through Session/Operation/Distribution, driven through Forward / Backward1 /
 * Backward2 / Update phases for 2 epochs x 3 minibatches, with buffers filled
 * by algebraic index patterns and every exchanged value checked against the
 * closed-form expectation:
 *   - layer-1 forward input (after the model-group reduce+redistribute):
 *     expected = fmGroupSize * (mb*localFm*fmSize*fmGroupSize
 *                               + (fmOffset+fm)*fmSize + space)
 *     (reference oracle mlsl_test.cpp:276-301);
 *   - gradient after data-group sync: expected = mbGroupSize * (ownedOff+idx)
 *     (reference oracle mlsl_test.cpp:397-406);
 *   - parameters after the distributed-update increment AllGather: param[i]==i.
 *
 * Launcher difference from the reference: mpiexec spawns processes; here
 * MLSL::RunRanks spawns one rank thread per device (the compat execution
 * model). Everything between Init and Finalize is the same rank-local
 * program.
 *
 * Usage: compat_test GROUP_COUNT [DIST_UPDATE] [USER_BUF] [USE_TEST]
 */

#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "../include/mlsl.hpp"

using namespace MLSL;

namespace {

const size_t kGlobalMinibatch = 16;
const size_t kLayers = 2;
const size_t kEpochs = 2;
const size_t kMinibatchesPerEpoch = 3;

struct Config {
  size_t group_count = 1;
  bool dist_update = false;
  bool user_buf = false;
  bool use_test = false;
};
Config cfg;

struct Shape {
  size_t ifm, ofm, fm_w, fm_h, kw, kh;
};
/* same conv shapes as the reference matrix (mlsl_test.cpp:619-644) */
const Shape kShapes[kLayers] = {
    {128, 256, 12, 12, 3, 3},
    {256, 256, 12, 12, 3, 3},
};

#define CHECK(cond, ...)                                              \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::printf("[rank %zu] %s:%d CHECK(%s) failed: ",              \
                  Environment::GetEnv().GetProcessIdx(), __FILE__,    \
                  __LINE__, #cond);                                   \
      std::printf(__VA_ARGS__);                                       \
      std::printf("\n");                                              \
      std::exit(1);                                                   \
    }                                                                 \
  } while (0)

/* Pack a local activation tensor (localMb, localFm, fmSize) into the wire
 * buffer per the CommBlockInfo layout (the user-side copy loop the reference
 * test performs, mlsl_test.cpp:214-233 — rewritten, same contract). */
void pack(Activation* act, const float* local, float* wire) {
  size_t lfm = act->GetLocalFmCount();
  for (size_t bi = 0; bi < act->GetPackBlockCount(); bi++) {
    CommBlockInfo* b = act->GetPackBlock(bi);
    float* dst = wire + b->GetBufOffset();
    size_t fms = b->GetFmSize();
    for (size_t m = 0; m < b->GetMbCount(); m++)
      for (size_t f = 0; f < b->GetFmCount(); f++)
        std::memcpy(
            dst + (m * b->GetFmCount() + f) * fms,
            local + ((m + b->GetMbOffset()) * lfm + f + b->GetFmOffset()) * fms,
            fms * sizeof(float));
  }
}

void unpack(Activation* act, const float* wire, float* local) {
  size_t lfm = act->GetLocalFmCount();
  for (size_t bi = 0; bi < act->GetUnpackBlockCount(); bi++) {
    CommBlockInfo* b = act->GetUnpackBlock(bi);
    const float* src = wire + b->GetBufOffset();
    size_t fms = b->GetFmSize();
    for (size_t m = 0; m < b->GetMbCount(); m++)
      for (size_t f = 0; f < b->GetFmCount(); f++)
        std::memcpy(
            local + ((m + b->GetMbOffset()) * lfm + f + b->GetFmOffset()) * fms,
            src + (m * b->GetFmCount() + f) * fms, fms * sizeof(float));
  }
}

class TestLayer {
 public:
  TestLayer(size_t idx, Operation* op, TestLayer* prev)
      : idx_(idx), op_(op) {
    Activation* in = op->GetInput(0);
    Activation* out = op->GetOutput(0);
    size_t in_elems =
        in->GetLocalFmCount() * op->GetLocalMinibatchSize() * in->GetFmSize();
    out_elems_ =
        out->GetLocalFmCount() * op->GetLocalMinibatchSize() * out->GetFmSize();
    /* adjacent layers SHARE the activation buffer (reference
     * mlsl_test.cpp:139-162: prev->outputActBuf = inputActBuf) so data flows
     * even on edges with no communication (e.g. pure data parallelism) */
    size_t store = in_elems;
    if (prev != nullptr && prev->out_elems_ > store) store = prev->out_elems_;
    in_store_.resize(store);
    in_grad_store_.resize(store);
    in_act_ = in_store_.data();
    in_grad_ = in_grad_store_.data();
    if (prev != nullptr) {
      prev->out_act_ = in_act_;
      prev->out_grad_ = in_grad_;
      op->SetPrev(prev->op_, 0, 0);
    }

    ParameterSet* ps = op->GetParameterSet(0);
    param_elems_ = ps->GetLocalKernelCount() * ps->GetKernelSize();
    size_t bytes = param_elems_ * sizeof(float);
    if (cfg.user_buf) {
      param_ = (float*)std::malloc(bytes);
      param_grad_ = (float*)std::malloc(bytes);
    } else {
      param_ = (float*)Environment::GetEnv().Alloc(bytes, 64);
      param_grad_ = (float*)Environment::GetEnv().Alloc(bytes, 64);
    }
    CHECK(param_ != nullptr && param_grad_ != nullptr, "param alloc");
    for (size_t i = 0; i < param_elems_; i++) param_[i] = (float)i;
  }

  ~TestLayer() {
    if (cfg.user_buf) {
      std::free(param_);
      std::free(param_grad_);
    } else {
      Environment::GetEnv().Free(param_);
      Environment::GetEnv().Free(param_grad_);
    }
  }

  float* param() { return param_; }
  size_t param_elems() const { return param_elems_; }
  Operation* op() { return op_; }

  /* Phase 1: receive input activation (and the previous increment), verify,
   * produce output, send it (reference flow mlsl_test.cpp:440-461). */
  void Forward() {
    Activation* in = op_->GetInput(0);
    float* wire = (float*)in->WaitComm();
    if (wire != nullptr) unpack(in, wire, in_act_);
    op_->GetParameterSet(0)->WaitIncrementComm();

    VerifyForward();

    Activation* out = op_->GetOutput(0);
    if (idx_ == 0) {
      /* layer 0 writes index values into its output */
      for (size_t i = 0; i < out_elems_; i++) out_act_[i] = (float)i;
    }
    float* comm = (float*)out->GetCommBuf();
    if (comm != nullptr && out_act_ != nullptr) {
      pack(out, out_act_, comm);
      out->StartComm(comm);
    }
    bwd_unpacked_ = false;
  }

  /* Phase 2: receive output-activation gradient, produce and send the
   * input-activation gradient (mlsl_test.cpp:464-483). */
  void Backward1() {
    ReceiveOutputGrad();
    if (idx_ == 0) {
      VerifyOutputGrad();
    } else {
      /* last layer seeds the gradient so that layer 0's check below holds:
       * grad value at (mb, fm, space) = mb*localFm*fmSize*groupSize
       *                                 + (fmOffset+fm)*fmSize + space */
      Activation* in = op_->GetInput(0);
      size_t lfm = in->GetLocalFmCount();
      size_t fms = in->GetFmSize();
      size_t off = in->GetGlobalFmOffset();
      size_t g = op_->GetDistribution()->GetProcessCount(GT_MODEL);
      size_t mb = op_->GetLocalMinibatchSize();
      for (size_t m = 0; m < mb; m++)
        for (size_t f = 0; f < lfm; f++)
          for (size_t s = 0; s < fms; s++)
            in_grad_[(m * lfm + f) * fms + s] =
                (float)(m * lfm * fms * g + (off + f) * fms + s);
    }
    Activation* in = op_->GetInput(0);
    float* comm = (float*)in->GetCommBuf();
    if (comm != nullptr) {
      pack(in, in_grad_, comm);
      in->StartComm(comm);
    }
  }

  /* Phase 3: produce and send the parameter gradient (mlsl_test.cpp:486-503). */
  void Backward2() {
    ReceiveOutputGrad();
    for (size_t i = 0; i < param_elems_; i++) param_grad_[i] = (float)i;
    op_->GetParameterSet(0)->StartGradientComm(param_grad_);
  }

  /* Phase 4: receive the synced gradient, verify the data-group reduction,
   * update owned parameters, send the increment (mlsl_test.cpp:506-528). */
  void Update() {
    ParameterSet* ps = op_->GetParameterSet(0);
    float* synced = nullptr;
    if (cfg.use_test) {
      bool done = false;
      while (!done) synced = (float*)ps->TestGradientComm(&done);
    } else {
      synced = (float*)ps->WaitGradientComm();
    }
    if (synced == nullptr) synced = param_grad_;

    size_t ksize = ps->GetKernelSize();
    size_t owned = ps->GetOwnedKernelCount() * ksize;
    size_t owned_off = ps->GetOwnedKernelOffset() * ksize;
    size_t mb_group = op_->GetDistribution()->GetProcessCount(GT_DATA);
    size_t bad = 0;
    for (size_t i = 0; i < owned; i++) {
      float expected = (float)(mb_group * (owned_off + i));
      if (std::fabs(synced[i] - expected) > 1e-4) bad++;
      param_[owned_off + i] = (float)(owned_off + i);
    }
    CHECK(bad == 0, "update_%zu: %zu gradient mismatches", idx_, bad);
    ps->StartIncrementComm(param_);
  }

 private:
  void ReceiveOutputGrad() {
    if (bwd_unpacked_) return;
    Activation* out = op_->GetOutput(0);
    float* wire = (float*)out->WaitComm();
    if (wire != nullptr && out_grad_ != nullptr) unpack(out, wire, out_grad_);
    bwd_unpacked_ = true;
  }

  void VerifyForward() {
    /* parameters must hold index values on every rank after increment sync */
    size_t bad = 0;
    for (size_t i = 0; i < param_elems_; i++)
      if (std::fabs(param_[i] - (float)i) > 1e-4) bad++;
    CHECK(bad == 0, "forward_%zu: %zu parameter mismatches", idx_, bad);

    if (idx_ != 1) return;
    /* layer 1's input came from layer 0's output through the model-group
     * reduce + redistribution; closed form per mlsl_test.cpp:276-301 */
    Activation* in = op_->GetInput(0);
    size_t lfm = in->GetLocalFmCount();
    size_t fms = in->GetFmSize();
    size_t off = in->GetGlobalFmOffset();
    size_t g = op_->GetDistribution()->GetProcessCount(GT_MODEL);
    size_t mb = op_->GetLocalMinibatchSize();
    bad = 0;
    for (size_t m = 0; m < mb && bad < 5; m++)
      for (size_t f = 0; f < lfm; f++)
        for (size_t s = 0; s < fms; s++) {
          float expected =
              (float)(g * (m * lfm * fms * g + (off + f) * fms + s));
          float got = in_act_[(m * lfm + f) * fms + s];
          if (std::fabs(got - expected) > 1e-4) {
            if (bad < 5)
              std::printf("[rank %zu] fwd_%zu mismatch at (%zu,%zu,%zu): "
                          "want %.0f got %.0f\n",
                          Environment::GetEnv().GetProcessIdx(), idx_, m, f, s,
                          expected, got);
            bad++;
          }
        }
    CHECK(bad == 0, "forward_%zu: input activation mismatches", idx_);
  }

  void VerifyOutputGrad() {
    /* layer 0's output gradient equals layer 1's seeded input gradient:
     * identity after the backward redistribution (mlsl_test.cpp:338-361) */
    size_t bad = 0;
    for (size_t i = 0; i < out_elems_; i++)
      if (std::fabs(out_grad_[i] - (float)i) > 1e-4) bad++;
    CHECK(bad == 0, "backward_%zu: %zu output-grad mismatches", idx_, bad);
  }

  size_t idx_;
  Operation* op_;
  std::vector<float> in_store_, in_grad_store_;
  float* in_act_ = nullptr;
  float* in_grad_ = nullptr;
  float* out_act_ = nullptr;   // aliases the next layer's input store
  float* out_grad_ = nullptr;  // aliases the next layer's input-grad store
  size_t out_elems_ = 0;
  float* param_ = nullptr;
  float* param_grad_ = nullptr;
  size_t param_elems_ = 0;
  bool bwd_unpacked_ = false;
};

/* Deliberate rendezvous mismatch: rank 0 issues a collective the other ranks
 * never join. The watchdog (MLSL_COMPAT_WATCHDOG_S) must abort with a
 * per-rank diagnostic instead of hanging — the compat analog of MPI dying
 * loudly on divergent collective order. */
int rank_main_mismatch(int argc, char** argv) {
  Environment& env = Environment::GetEnv();
  env.Init(&argc, &argv);
  size_t world = env.GetProcessCount();
  size_t rank = env.GetProcessIdx();
  Distribution* dist = env.CreateDistribution(world, 1);
  if (rank == 0) {
    std::vector<float> buf(16, 1.0f);
    CommReq* req = dist->AllReduce(buf.data(), buf.data(), 16, DT_FLOAT,
                                   RT_SUM, GT_GLOBAL);
    env.Wait(req);  // unreachable: the watchdog aborts in the rendezvous
  } else {
    std::this_thread::sleep_for(std::chrono::seconds(60));
  }
  return 0;
}

/* Slow-but-healthy collective under an aggressive watchdog
 * (MLSL_COMPAT_WATCHDOG_S=1): a multi-second AllReduce on the CPU mesh keeps
 * the executing rank inside the collective past the deadline. The watchdog
 * must RE-ARM for the waiting ranks (all ranks joined; slow is not divergent)
 * instead of spuriously aborting, and the result must still be exact. */
int rank_main_slowwait(int argc, char** argv) {
  Environment& env = Environment::GetEnv();
  env.Init(&argc, &argv);
  size_t world = env.GetProcessCount();
  size_t rank = env.GetProcessIdx();
  Distribution* dist = env.CreateDistribution(world, 1);
  const size_t n = 32u << 20;  // 32M floats: seconds of wire+reduce per core
  std::vector<float> buf(n);
  for (size_t i = 0; i < n; i++)
    buf[i] = (float)(rank + 1) + (float)(i % 17);
  CommReq* req = dist->AllReduce(buf.data(), buf.data(), n, DT_FLOAT, RT_SUM,
                                 GT_GLOBAL);
  env.Wait(req);
  double wsum = world * (world + 1) / 2.0;
  size_t bad = 0;
  for (size_t i = 0; i < n; i++) {
    double want = wsum + (double)world * (double)(i % 17);
    if (std::fabs(buf[i] - want) > 1e-3 * (std::fabs(want) + 1.0)) bad++;
  }
  CHECK(bad == 0, "slowwait: %zu allreduce mismatches", bad);
  env.DeleteDistribution(dist);
  env.Finalize();
  if (rank == 0) std::printf("compat_test slowwait: PASSED\n");
  return 0;
}

int rank_main(int argc, char** argv) {
  Environment& env = Environment::GetEnv();
  CHECK(MLSL_MAJOR(Environment::GetVersion()) == MLSL_MAJOR_VERSION,
        "API version mismatch");
  env.Configure("color=0");  // homogeneous colors: validated full-world no-op
  env.Init(&argc, &argv);

  size_t world = env.GetProcessCount();
  size_t rank = env.GetProcessIdx();
  if (cfg.group_count > world) cfg.group_count = world;

  Session* session = env.CreateSession();
  session->SetGlobalMinibatchSize(kGlobalMinibatch);
  Distribution* dist =
      env.CreateDistribution(world / cfg.group_count, cfg.group_count);

  if (rank == 0)
    std::printf("compat_test: world=%zu dist=%zux%zu dist_update=%d "
                "user_buf=%d use_test=%d\n",
                world, world / cfg.group_count, cfg.group_count,
                (int)cfg.dist_update, (int)cfg.user_buf, (int)cfg.use_test);

  std::vector<TestLayer*> layers;
  for (size_t li = 0; li < kLayers; li++) {
    const Shape& sh = kShapes[li];
    OperationRegInfo* reg = session->CreateOperationRegInfo(OT_CC);
    reg->SetName(("layer_" + std::to_string(li)).c_str());
    reg->AddInput(sh.ifm, sh.fm_w * sh.fm_h, DT_FLOAT);
    reg->AddOutput(sh.ofm, sh.fm_w * sh.fm_h, DT_FLOAT);
    reg->AddParameterSet(sh.ifm * sh.ofm, sh.kw * sh.kh, DT_FLOAT,
                         cfg.dist_update, CT_NONE);
    size_t op_idx = session->AddOperation(reg, dist);
    session->DeleteOperationRegInfo(reg);
    layers.push_back(new TestLayer(li, session->GetOperation(op_idx),
                                   li == 0 ? nullptr : layers[li - 1]));
    /* broadcast initial parameters from rank 0 (mlsl_test.cpp:651-652) */
    CommReq* req = dist->Bcast(layers[li]->param(), layers[li]->param_elems(),
                               DT_FLOAT, 0, GT_GLOBAL);
    env.Wait(req);
  }

  session->Commit();

  Statistics* stats = session->GetStats();
  stats->Start();

  for (size_t epoch = 0; epoch < kEpochs; epoch++) {
    for (size_t mb = 0; mb < kMinibatchesPerEpoch; mb++) {
      for (size_t li = 0; li < kLayers; li++) layers[li]->Forward();
      for (size_t li = kLayers; li-- > 0;) {
        layers[li]->Backward1();
        layers[li]->Backward2();
      }
      for (size_t li = 0; li < kLayers; li++) layers[li]->Update();
    }
    /* drain increment comms at epoch end (mlsl_test.cpp:689-697) */
    for (size_t li = 0; li < kLayers; li++)
      layers[li]->op()->GetParameterSet(0)->WaitIncrementComm();
  }

  stats->Stop();
  if (stats->IsEnabled()) stats->Print();

  /* v-collectives through the drop-in surface (reference mlsl.hpp:432,470):
   * AllGatherv with per-position counts; oracle = concatenation over the
   * global group of each member's (rank*100 + k) fill. */
  {
    std::vector<size_t> counts(world);
    size_t total = 0;
    for (size_t i = 0; i < world; i++) {
      counts[i] = 2 + (i % 3);
      total += counts[i];
    }
    size_t mine = counts[rank];
    std::vector<float> send(mine), recv(total, -1.0f);
    for (size_t k = 0; k < mine; k++) send[k] = (float)(rank * 100 + k);
    CommReq* vreq = dist->AllGatherv(send.data(), mine, recv.data(),
                                     counts.data(), DT_FLOAT, GT_GLOBAL);
    env.Wait(vreq);
    /* a second Wait on the completed request must be a harmless no-op
     * (MPI semantics; previously a use-after-free) */
    env.Wait(vreq);
    size_t off = 0;
    for (size_t i = 0; i < world; i++) {
      for (size_t k = 0; k < counts[i]; k++)
        CHECK(recv[off + k] == (float)(i * 100 + k), "AllGatherv payload");
      off += counts[i];
    }
    if (rank == 0) std::printf("compat_test: AllGatherv OK\n");
  }

  /* general per-rank AlltoAllv (reference mlsl.hpp:432, each rank its OWN
   * count/displacement vectors — the MPI_Ialltoallv generality): rank i
   * sends (i + j) % 3 + 1 elements to rank j; counts gathered across ranks,
   * pairwise invariant validated by the engine, blocks written back at this
   * rank's own displacements. Oracle: recv block j = rank j's segment toward
   * me, fill value sender*100 + send_offset + k. */
  {
    std::vector<size_t> sc(world), soff(world), rc(world), roff(world);
    size_t send_total = 0, recv_total = 0;
    for (size_t j = 0; j < world; j++) {
      sc[j] = (2 * rank + j) % 3 + 1;  /* S[i][j], deliberately asymmetric */
      soff[j] = send_total;
      send_total += sc[j];
      rc[j] = (2 * j + rank) % 3 + 1;  /* = S[j][rank], what j sends to me */
      roff[j] = recv_total;
      recv_total += rc[j];
    }
    std::vector<float> send(send_total), recv(recv_total, -1.0f);
    for (size_t k = 0; k < send_total; k++) send[k] = (float)(rank * 100 + k);
    CommReq* areq = dist->AlltoAllv(send.data(), sc.data(), soff.data(),
                                    recv.data(), rc.data(), roff.data(),
                                    DT_FLOAT, GT_GLOBAL);
    env.Wait(areq);
    env.Wait(areq);  /* second Wait = MPI no-op */
    for (size_t j = 0; j < world; j++) {
      /* sender j's offset of its segment toward me */
      size_t j_soff = 0;
      for (size_t t = 0; t < (size_t)rank; t++) j_soff += (2 * j + t) % 3 + 1;
      for (size_t k = 0; k < rc[j]; k++)
        CHECK(recv[roff[j] + k] == (float)(j * 100 + j_soff + k),
              "per-rank AlltoAllv payload");
    }
    if (rank == 0) std::printf("compat_test: per-rank AlltoAllv OK\n");
  }

  /* the same, on MODEL subgroups: counts keyed on the WORLD rank, so the
   * different group instances exchange genuinely different geometries (the
   * engine's per-rank (world, group) table path). Model groups are
   * consecutive ranks (model-minor layout). */
  if (cfg.group_count > 1) {
    size_t gsz = dist->GetProcessCount(GT_MODEL);
    size_t mypos = dist->GetProcessIdx(GT_MODEL);
    size_t base = rank - mypos; /* my instance's first world rank */
    std::vector<size_t> sc(gsz), soff(gsz), rc(gsz), roff(gsz);
    size_t send_total = 0, recv_total = 0;
    for (size_t j = 0; j < gsz; j++) {
      sc[j] = (3 * rank + j) % 4 + 1;
      soff[j] = send_total;
      send_total += sc[j];
      rc[j] = (3 * (base + j) + mypos) % 4 + 1; /* member j's count toward me */
      roff[j] = recv_total;
      recv_total += rc[j];
    }
    std::vector<float> send(send_total), recv(recv_total, -1.0f);
    for (size_t k = 0; k < send_total; k++) send[k] = (float)(rank * 100 + k);
    CommReq* areq = dist->AlltoAllv(send.data(), sc.data(), soff.data(),
                                    recv.data(), rc.data(), roff.data(),
                                    DT_FLOAT, GT_MODEL);
    env.Wait(areq);
    for (size_t j = 0; j < gsz; j++) {
      size_t wj = base + j;
      size_t j_soff = 0;
      for (size_t t = 0; t < mypos; t++) j_soff += (3 * wj + t) % 4 + 1;
      for (size_t k = 0; k < rc[j]; k++)
        CHECK(recv[roff[j] + k] == (float)(wj * 100 + j_soff + k),
              "subgroup per-rank AlltoAllv payload");
    }
    if (rank == 0)
      std::printf("compat_test: subgroup per-rank AlltoAllv OK\n");

    /* the same exchange completed via Test polling (reference TestComm
     * semantics): the gathered-count machinery must serve the non-blocking
     * path too */
    for (size_t k = 0; k < send_total; ++k)
      send[k] = (float)(rank * 100 + k) + 0.5f;
    std::fill(recv.begin(), recv.end(), -1.0f);
    CommReq* treq = dist->AlltoAllv(send.data(), sc.data(), soff.data(),
                                    recv.data(), rc.data(), roff.data(),
                                    DT_FLOAT, GT_MODEL);
    bool done = false;
    for (int spins = 0; !done && spins < 200000; ++spins) env.Test(treq, &done);
    CHECK(done, "per-rank AlltoAllv Test completion");
    for (size_t j = 0; j < gsz; ++j) {
      size_t wj = base + j;
      size_t j_soff = 0;
      for (size_t t = 0; t < mypos; ++t) j_soff += (3 * wj + t) % 4 + 1;
      for (size_t k = 0; k < rc[j]; ++k)
        CHECK(recv[roff[j] + k] == (float)(wj * 100 + j_soff + k) + 0.5f,
              "Test-driven per-rank AlltoAllv payload");
    }
    if (rank == 0)
      std::printf("compat_test: Test-driven per-rank AlltoAllv OK\n");
  }

  /* color-defined distribution (reference mlsl.hpp:864): unequal data groups
   * {ranks 0..2} and {ranks 3..}, allreduce summed within each group */
  if (world >= 4) {
    int my_color = rank < 3 ? 0 : 1;
    Distribution* cdist = env.CreateDistributionWithColors(my_color, 0);
    size_t gsz = rank < 3 ? 3 : world - 3;
    std::vector<float> v(8, (float)(rank + 1));
    CommReq* cr = cdist->AllReduce(v.data(), v.data(), 8, DT_FLOAT, RT_SUM,
                                   GT_DATA);
    env.Wait(cr);
    float want = 0.0f;
    for (size_t q = (rank < 3 ? 0 : 3); q < (rank < 3 ? 3 : world); q++)
      want += (float)(q + 1);
    for (float x : v) CHECK(x == want, "colored allreduce payload");
    CHECK(cdist->GetProcessCount(GT_DATA) >= gsz, "colored group size");
    env.DeleteDistribution(cdist);
    if (rank == 0) std::printf("compat_test: colored distribution OK\n");
  }

  for (TestLayer* l : layers) delete l;
  env.DeleteSession(session);
  env.DeleteDistribution(dist);
  env.Finalize();
  if (rank == 0) std::printf("compat_test: PASSED\n");
  return 0;
}

}  // namespace

#include <execinfo.h>
#include <csignal>
#include <unistd.h>

static void segv_handler(int sig) {
  void* frames[48];
  int n = backtrace(frames, 48);
  std::fprintf(stderr, "compat_test: signal %d, backtrace:\n", sig);
  backtrace_symbols_fd(frames, n, 2);
  _exit(139);
}

int main(int argc, char** argv) {
  std::setvbuf(stdout, nullptr, _IONBF, 0);
  std::signal(SIGSEGV, segv_handler);
  std::signal(SIGABRT, segv_handler);
  if (argc < 2) {
    std::printf(
        "usage: compat_test GROUP_COUNT [DIST_UPDATE] [USER_BUF] [USE_TEST]\n");
    return 0;
  }
  if (std::strcmp(argv[1], "mismatch") == 0)
    return MLSL::RunRanks(argc, argv, rank_main_mismatch);
  if (std::strcmp(argv[1], "slowwait") == 0)
    return MLSL::RunRanks(argc, argv, rank_main_slowwait);
  cfg.group_count = (size_t)std::atoi(argv[1]);
  if (cfg.group_count < 1) cfg.group_count = 1;
  if (argc > 2) cfg.dist_update = std::atoi(argv[2]) != 0;
  if (argc > 3) cfg.user_buf = std::atoi(argv[3]) != 0;
  if (argc > 4) cfg.use_test = std::atoi(argv[4]) != 0;
  return MLSL::RunRanks(argc, argv, rank_main);
}
