/* mlsl_tpu C++ API — class-based wrapper over the flat C API.
 *
 * Mirrors the shape of the reference's C++ surface (include/mlsl.hpp:
 * Environment singleton, Session/Operation/Distribution handle classes with
 * Start/Wait semantics) for C++ frameworks. Header-only over mlsl_tpu.h.
 */

#ifndef MLSL_TPU_HPP
#define MLSL_TPU_HPP

#include <cstdint>
#include <stdexcept>
#include <vector>

#include "mlsl_tpu.h"

namespace mlsl_tpu {

using DataType = mlsl_data_type_t;
using GroupType = mlsl_group_type_t;
using ReductionType = mlsl_reduction_t;
using OpType = mlsl_op_type_t;
using CompressionType = mlsl_compression_t;

inline void Check(int status, const char* what) {
  if (status != MLSL_TPU_SUCCESS) throw std::runtime_error(what);
}

class CommReq {
 public:
  explicit CommReq(mlsl_handle_t h) : h_(h) {
    if (h_ == 0) throw std::runtime_error("collective start failed");
  }
  /* recv: (world, recv_count) */
  void Wait(void* recv, int64_t recv_count, DataType dt) {
    Check(mlsl_request_wait(h_, recv, recv_count, dt), "request wait");
  }
  bool Test() { return mlsl_request_test(h_) == 1; }
  mlsl_handle_t handle() const { return h_; }

 private:
  mlsl_handle_t h_;
};

class Distribution {
 public:
  Distribution(int64_t data_parts, int64_t model_parts, int64_t seq_parts = 1)
      : h_(mlsl_environment_create_distribution(data_parts, model_parts,
                                                seq_parts)) {
    if (h_ == 0) throw std::runtime_error("create distribution failed");
  }
  int64_t GetProcessCount(GroupType g) const {
    return mlsl_distribution_get_process_count(h_, g);
  }
  CommReq AllReduce(const void* send, int64_t count, DataType dt,
                    ReductionType op, GroupType g) {
    return CommReq(mlsl_distribution_all_reduce(h_, send, count, dt, op, g));
  }
  CommReq Bcast(const void* send, int64_t count, DataType dt, int64_t root,
                GroupType g) {
    return CommReq(mlsl_distribution_bcast(h_, send, count, dt, root, g));
  }
  CommReq AllGather(const void* send, int64_t count, DataType dt, GroupType g) {
    return CommReq(mlsl_distribution_all_gather(h_, send, count, dt, g));
  }
  CommReq ReduceScatter(const void* send, int64_t count, DataType dt,
                        ReductionType op, GroupType g) {
    return CommReq(
        mlsl_distribution_reduce_scatter(h_, send, count, dt, op, g));
  }
  CommReq AlltoAll(const void* send, int64_t count, DataType dt, GroupType g) {
    return CommReq(mlsl_distribution_all_to_all(h_, send, count, dt, g));
  }
  void Barrier(GroupType g) { Check(mlsl_distribution_barrier(h_, g), "barrier"); }
  mlsl_handle_t handle() const { return h_; }

 private:
  mlsl_handle_t h_;
};

class Operation {
 public:
  explicit Operation(mlsl_handle_t h) : h_(h) {}
  void SetNext(const Operation& next, int64_t out_idx, int64_t in_idx) {
    Check(mlsl_operation_set_next(h_, next.h_, out_idx, in_idx), "set next");
  }
  int64_t GetLocalMinibatchSize() const {
    return mlsl_operation_get_local_minibatch_size(h_);
  }
  int64_t GetParameterLocalCount(int64_t idx) const {
    return mlsl_operation_get_parameter_local_count(h_, idx);
  }
  int64_t GetParameterOwnedCount(int64_t idx) const {
    return mlsl_operation_get_parameter_owned_count(h_, idx);
  }
  void StartGradientComm(int64_t ps_idx, const void* grads, DataType dt) {
    Check(mlsl_parameter_set_start_gradient_comm(h_, ps_idx, grads, dt),
          "start gradient comm");
  }
  /* returns per-rank element count written (0 = no comm needed) */
  int64_t WaitGradientComm(int64_t ps_idx, void* recv, DataType dt) {
    int64_t n = mlsl_parameter_set_wait_gradient_comm(h_, ps_idx, recv, dt);
    if (n < 0) throw std::runtime_error("wait gradient comm");
    return n;
  }
  mlsl_handle_t handle() const { return h_; }

 private:
  mlsl_handle_t h_;
};

class OperationRegInfo {
 public:
  explicit OperationRegInfo(mlsl_handle_t h) : h_(h) {}
  int64_t AddInput(int64_t count, int64_t size, DataType dt) {
    return mlsl_operation_reg_info_add_input(h_, count, size, dt);
  }
  int64_t AddOutput(int64_t count, int64_t size, DataType dt) {
    return mlsl_operation_reg_info_add_output(h_, count, size, dt);
  }
  int64_t AddParameterSet(int64_t kernel_count, int64_t kernel_size, DataType dt,
                          bool dist_update = false,
                          CompressionType comp = MLSL_CT_NONE) {
    return mlsl_operation_reg_info_add_parameter_set(
        h_, kernel_count, kernel_size, dt, dist_update ? 1 : 0, comp);
  }
  mlsl_handle_t handle() const { return h_; }

 private:
  mlsl_handle_t h_;
};

class Session {
 public:
  Session() : h_(mlsl_environment_create_session()) {
    if (h_ == 0) throw std::runtime_error("create session failed");
  }
  void SetGlobalMinibatchSize(int64_t size) {
    Check(mlsl_session_set_global_minibatch_size(h_, size), "set minibatch");
  }
  OperationRegInfo CreateOperationRegInfo(OpType t) {
    return OperationRegInfo(mlsl_session_create_operation_reg_info(h_, t));
  }
  Operation AddOperation(const OperationRegInfo& reg, const Distribution& d) {
    mlsl_handle_t op = mlsl_session_add_operation(h_, reg.handle(), d.handle());
    if (op == 0) throw std::runtime_error("add operation failed");
    return Operation(op);
  }
  void Commit() { Check(mlsl_session_commit(h_), "commit"); }
  mlsl_handle_t handle() const { return h_; }

 private:
  mlsl_handle_t h_;
};

class Environment {
 public:
  static Environment& GetEnv() {
    static Environment env;
    return env;
  }
  void Init() { Check(mlsl_environment_init(), "environment init"); }
  void Finalize() { Check(mlsl_environment_finalize(), "environment finalize"); }
  int64_t GetProcessCount() const {
    return mlsl_environment_get_process_count();
  }
};

}  // namespace mlsl_tpu

#endif /* MLSL_TPU_HPP */
