/* mlsl_tpu C++ API — class-based wrapper over the flat C API.
 *
 * Mirrors the shape of the reference's C++ surface (include/mlsl.hpp:
 * Environment singleton, Session/Operation/Distribution handle classes with
 * Start/Wait semantics) for C++ frameworks. Header-only over mlsl_tpu.h.
 */

#ifndef MLSL_TPU_HPP
#define MLSL_TPU_HPP

#include <cstdint>
#include <stdexcept>
#include <vector>

#include "mlsl_tpu.h"

namespace mlsl_tpu {

using DataType = mlsl_data_type_t;
using GroupType = mlsl_group_type_t;
using ReductionType = mlsl_reduction_t;
using OpType = mlsl_op_type_t;
using CompressionType = mlsl_compression_t;

inline void Check(int status, const char* what) {
  if (status != MLSL_TPU_SUCCESS) throw std::runtime_error(what);
}

class CommReq {
 public:
  explicit CommReq(mlsl_handle_t h) : h_(h) {
    if (h_ == 0) throw std::runtime_error("collective start failed");
  }
  /* recv: (world, recv_count) */
  void Wait(void* recv, int64_t recv_count, DataType dt) {
    Check(mlsl_request_wait(h_, recv, recv_count, dt), "request wait");
  }
  bool Test() { return mlsl_request_test(h_) == 1; }
  mlsl_handle_t handle() const { return h_; }

 private:
  mlsl_handle_t h_;
};

class Distribution {
 public:
  Distribution(int64_t data_parts, int64_t model_parts, int64_t seq_parts = 1)
      : h_(mlsl_environment_create_distribution(data_parts, model_parts,
                                                seq_parts)) {
    if (h_ == 0) throw std::runtime_error("create distribution failed");
  }
  int64_t GetProcessCount(GroupType g) const {
    return mlsl_distribution_get_process_count(h_, g);
  }
  CommReq AllReduce(const void* send, int64_t count, DataType dt,
                    ReductionType op, GroupType g) {
    return CommReq(mlsl_distribution_all_reduce(h_, send, count, dt, op, g));
  }
  CommReq Bcast(const void* send, int64_t count, DataType dt, int64_t root,
                GroupType g) {
    return CommReq(mlsl_distribution_bcast(h_, send, count, dt, root, g));
  }
  CommReq AllGather(const void* send, int64_t count, DataType dt, GroupType g) {
    return CommReq(mlsl_distribution_all_gather(h_, send, count, dt, g));
  }
  CommReq ReduceScatter(const void* send, int64_t count, DataType dt,
                        ReductionType op, GroupType g) {
    return CommReq(
        mlsl_distribution_reduce_scatter(h_, send, count, dt, op, g));
  }
  CommReq AlltoAll(const void* send, int64_t count, DataType dt, GroupType g) {
    return CommReq(mlsl_distribution_all_to_all(h_, send, count, dt, g));
  }
  CommReq Reduce(const void* send, int64_t count, DataType dt, ReductionType op,
                 int64_t root, GroupType g) {
    return CommReq(mlsl_distribution_reduce(h_, send, count, dt, op, root, g));
  }
  CommReq Gather(const void* send, int64_t count, DataType dt, int64_t root,
                 GroupType g) {
    return CommReq(mlsl_distribution_gather(h_, send, count, dt, root, g));
  }
  CommReq Scatter(const void* send, int64_t count, DataType dt, int64_t root,
                  GroupType g) {
    return CommReq(mlsl_distribution_scatter(h_, send, count, dt, root, g));
  }
  CommReq AllGatherv(const void* send, int64_t send_count,
                     const int64_t* recv_counts, DataType dt, GroupType g) {
    return CommReq(
        mlsl_distribution_all_gatherv(h_, send, send_count, recv_counts, dt, g));
  }
  CommReq AlltoAllv(const void* send, int64_t send_len,
                    const int64_t* send_counts, const int64_t* send_offsets,
                    const int64_t* recv_offsets, DataType dt, GroupType g) {
    return CommReq(mlsl_distribution_all_to_allv(
        h_, send, send_len, send_counts, send_offsets, recv_offsets, dt, g));
  }
  void Barrier(GroupType g) { Check(mlsl_distribution_barrier(h_, g), "barrier"); }
  mlsl_handle_t handle() const { return h_; }

 private:
  mlsl_handle_t h_;
};

/* One pack/unpack block (reference CommBlockInfo include/mlsl.hpp:177-204). */
struct CommBlockInfo {
  int64_t mb_offset, mb_count, fm_offset, fm_count, fm_size, buf_offset;
};

/* Activation handle (reference include/mlsl.hpp:210-268). */
class Activation {
 public:
  explicit Activation(mlsl_handle_t h) : h_(h) {
    if (h_ == 0) throw std::runtime_error("null activation");
  }
  int64_t GetGlobalFmCount() const { return mlsl_activation_get_global_fm_count(h_); }
  int64_t GetLocalFmCount() const { return mlsl_activation_get_local_fm_count(h_); }
  int64_t GetFmSize() const { return mlsl_activation_get_fm_size(h_); }
  bool NeedsComm() const { return mlsl_activation_needs_comm(h_) == 1; }
  int64_t GetWireCount() const { return mlsl_activation_get_wire_count(h_); }
  int64_t GetPackBlockCount() const {
    return mlsl_activation_get_pack_block_count(h_);
  }
  int64_t GetUnpackBlockCount() const {
    return mlsl_activation_get_unpack_block_count(h_);
  }
  CommBlockInfo GetPackBlock(int64_t idx) const { return Block_(idx, false); }
  CommBlockInfo GetUnpackBlock(int64_t idx) const { return Block_(idx, true); }
  /* buf: (world, wire_count), packed per the pack blocks */
  void StartComm(const void* buf, DataType dt) {
    Check(mlsl_activation_start_comm(h_, buf, dt), "activation start comm");
  }
  /* waits the PEER's transfer; returns per-rank count written (0 = no comm) */
  int64_t WaitComm(void* recv, DataType dt) {
    int64_t n = mlsl_activation_wait_comm(h_, recv, dt);
    if (n < 0) throw std::runtime_error("activation wait comm");
    return n;
  }
  mlsl_handle_t handle() const { return h_; }

 private:
  CommBlockInfo Block_(int64_t idx, bool unpack) const {
    CommBlockInfo b;
    int64_t* f[6] = {&b.mb_offset, &b.mb_count, &b.fm_offset,
                     &b.fm_count, &b.fm_size, &b.buf_offset};
    for (int i = 0; i < 6; ++i)
      *f[i] = unpack ? mlsl_activation_get_unpack_block(h_, idx, i)
                     : mlsl_activation_get_pack_block(h_, idx, i);
    return b;
  }
  mlsl_handle_t h_;
};

/* ParameterSet handle (reference include/mlsl.hpp:276-341); identified by
 * (operation, index) as in the flat C layer. */
class ParameterSet {
 public:
  ParameterSet(mlsl_handle_t op, int64_t idx) : op_(op), idx_(idx) {}
  int64_t GetGlobalKernelCount() const {
    return mlsl_parameter_set_get_global_kernel_count(op_, idx_);
  }
  int64_t GetLocalKernelCount() const {
    return mlsl_parameter_set_get_local_kernel_count(op_, idx_);
  }
  int64_t GetOwnedKernelCount() const {
    return mlsl_parameter_set_get_owned_kernel_count(op_, idx_);
  }
  int64_t GetKernelSize() const {
    return mlsl_parameter_set_get_kernel_size(op_, idx_);
  }
  bool IsDistributedUpdate() const {
    return mlsl_parameter_set_is_distributed_update(op_, idx_) == 1;
  }
  void StartGradientComm(const void* grads, DataType dt) {
    Check(mlsl_parameter_set_start_gradient_comm(op_, idx_, grads, dt),
          "start gradient comm");
  }
  int64_t WaitGradientComm(void* recv, DataType dt) {
    int64_t n = mlsl_parameter_set_wait_gradient_comm(op_, idx_, recv, dt);
    if (n < 0) throw std::runtime_error("wait gradient comm");
    return n;
  }
  bool TestGradientComm() {
    return mlsl_parameter_set_test_gradient_comm(op_, idx_) == 1;
  }
  void StartIncrementComm(const void* incs, DataType dt) {
    Check(mlsl_parameter_set_start_increment_comm(op_, idx_, incs, dt),
          "start increment comm");
  }
  int64_t WaitIncrementComm(void* recv, DataType dt) {
    int64_t n = mlsl_parameter_set_wait_increment_comm(op_, idx_, recv, dt);
    if (n < 0) throw std::runtime_error("wait increment comm");
    return n;
  }

 private:
  mlsl_handle_t op_;
  int64_t idx_;
};

class Operation {
 public:
  explicit Operation(mlsl_handle_t h) : h_(h) {}
  void SetNext(const Operation& next, int64_t out_idx, int64_t in_idx) {
    Check(mlsl_operation_set_next(h_, next.h_, out_idx, in_idx), "set next");
  }
  int64_t GetLocalMinibatchSize() const {
    return mlsl_operation_get_local_minibatch_size(h_);
  }
  int64_t GetInputCount() const { return mlsl_operation_get_input_count(h_); }
  int64_t GetOutputCount() const { return mlsl_operation_get_output_count(h_); }
  Activation GetInput(int64_t idx) const {
    return Activation(mlsl_operation_get_input(h_, idx));
  }
  Activation GetOutput(int64_t idx) const {
    return Activation(mlsl_operation_get_output(h_, idx));
  }
  ParameterSet GetParameterSet(int64_t idx) const {
    return ParameterSet(h_, idx);
  }
  int64_t GetParameterLocalCount(int64_t idx) const {
    return mlsl_operation_get_parameter_local_count(h_, idx);
  }
  int64_t GetParameterOwnedCount(int64_t idx) const {
    return mlsl_operation_get_parameter_owned_count(h_, idx);
  }
  void StartGradientComm(int64_t ps_idx, const void* grads, DataType dt) {
    Check(mlsl_parameter_set_start_gradient_comm(h_, ps_idx, grads, dt),
          "start gradient comm");
  }
  /* returns per-rank element count written (0 = no comm needed) */
  int64_t WaitGradientComm(int64_t ps_idx, void* recv, DataType dt) {
    int64_t n = mlsl_parameter_set_wait_gradient_comm(h_, ps_idx, recv, dt);
    if (n < 0) throw std::runtime_error("wait gradient comm");
    return n;
  }
  mlsl_handle_t handle() const { return h_; }

 private:
  mlsl_handle_t h_;
};

/* Statistics handle (reference include/mlsl.hpp:651-726); "cycles" are
 * nanoseconds (TPU analog of rdtsc cycles). */
class Statistics {
 public:
  explicit Statistics(mlsl_handle_t h) : h_(h) {
    if (h_ == 0) throw std::runtime_error("null statistics");
  }
  void Start() { Check(mlsl_statistics_start(h_), "stats start"); }
  void Stop() { Check(mlsl_statistics_stop(h_), "stats stop"); }
  void Reset() { Check(mlsl_statistics_reset(h_), "stats reset"); }
  bool IsEnabled() const { return mlsl_statistics_is_enabled(h_) == 1; }
  bool IsStarted() const { return mlsl_statistics_is_started(h_) == 1; }
  int64_t GetCommSize(int64_t op_idx) const {
    return mlsl_statistics_get_comm_size(h_, op_idx);
  }
  int64_t GetCommCycles(int64_t op_idx) const {
    return mlsl_statistics_get_comm_cycles(h_, op_idx);
  }
  int64_t GetComputeCycles(int64_t op_idx) const {
    return mlsl_statistics_get_compute_cycles(h_, op_idx);
  }
  int64_t GetIsolationCommCycles(int64_t op_idx) const {
    return mlsl_statistics_get_isolation_comm_cycles(h_, op_idx);
  }
  int64_t GetTotalCommSize() const {
    return mlsl_statistics_get_total_comm_size(h_);
  }
  int64_t GetTotalCommCycles() const {
    return mlsl_statistics_get_total_comm_cycles(h_);
  }
  int64_t GetTotalComputeCycles() const {
    return mlsl_statistics_get_total_compute_cycles(h_);
  }
  int64_t GetTotalIsolationCommCycles() const {
    return mlsl_statistics_get_total_isolation_comm_cycles(h_);
  }
  void Print() { Check(mlsl_statistics_print(h_), "stats print"); }
  mlsl_handle_t handle() const { return h_; }

 private:
  mlsl_handle_t h_;
};

class OperationRegInfo {
 public:
  explicit OperationRegInfo(mlsl_handle_t h) : h_(h) {}
  int64_t AddInput(int64_t count, int64_t size, DataType dt) {
    return mlsl_operation_reg_info_add_input(h_, count, size, dt);
  }
  int64_t AddOutput(int64_t count, int64_t size, DataType dt) {
    return mlsl_operation_reg_info_add_output(h_, count, size, dt);
  }
  int64_t AddParameterSet(int64_t kernel_count, int64_t kernel_size, DataType dt,
                          bool dist_update = false,
                          CompressionType comp = MLSL_CT_NONE) {
    return mlsl_operation_reg_info_add_parameter_set(
        h_, kernel_count, kernel_size, dt, dist_update ? 1 : 0, comp);
  }
  mlsl_handle_t handle() const { return h_; }

 private:
  mlsl_handle_t h_;
};

class Session {
 public:
  Session() : h_(mlsl_environment_create_session()) {
    if (h_ == 0) throw std::runtime_error("create session failed");
  }
  void SetGlobalMinibatchSize(int64_t size) {
    Check(mlsl_session_set_global_minibatch_size(h_, size), "set minibatch");
  }
  OperationRegInfo CreateOperationRegInfo(OpType t) {
    return OperationRegInfo(mlsl_session_create_operation_reg_info(h_, t));
  }
  Operation AddOperation(const OperationRegInfo& reg, const Distribution& d) {
    mlsl_handle_t op = mlsl_session_add_operation(h_, reg.handle(), d.handle());
    if (op == 0) throw std::runtime_error("add operation failed");
    return Operation(op);
  }
  void Commit() { Check(mlsl_session_commit(h_), "commit"); }
  Statistics GetStats() { return Statistics(mlsl_session_get_stats(h_)); }
  mlsl_handle_t handle() const { return h_; }

 private:
  mlsl_handle_t h_;
};

class Environment {
 public:
  static Environment& GetEnv() {
    static Environment env;
    return env;
  }
  void Init() { Check(mlsl_environment_init(), "environment init"); }
  void Finalize() { Check(mlsl_environment_finalize(), "environment finalize"); }
  int64_t GetProcessCount() const {
    return mlsl_environment_get_process_count();
  }
};

}  // namespace mlsl_tpu

#endif /* MLSL_TPU_HPP */
