/* mlsl_tpu C API — the flat-C surface for C/C++ frameworks.
 *
 * Mirrors the reference's C binding (include/mlsl.h + src/c_bind.cpp: opaque
 * handles, int status returns) over the TPU-native core. Architecture note:
 * the reference's core is C++ with Python bound on top; this framework's core
 * is Python/JAX with this C layer embedding the interpreter — the same flat
 * contract from the caller's point of view.
 *
 * Buffer convention (single-controller SPMD): a caller passes the WHOLE
 * world's data as one dense array of logical shape (world_size, count),
 * rank-major — the analog of each MPI rank passing its local buffer.
 *
 * All functions return MLSL_TPU_SUCCESS (0) or MLSL_TPU_FAILURE (-1) unless
 * documented otherwise; handle-returning calls return 0 on failure.
 */

#ifndef MLSL_TPU_H
#define MLSL_TPU_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define MLSL_TPU_SUCCESS 0
#define MLSL_TPU_FAILURE -1

typedef uint64_t mlsl_handle_t;

/* enums match mlsl_tpu.types (reference include/mlsl.hpp:88-155) */
typedef enum { MLSL_DT_FLOAT = 0, MLSL_DT_DOUBLE = 1, MLSL_DT_BYTE = 2,
               MLSL_DT_BF16 = 3, MLSL_DT_F16 = 4, MLSL_DT_INT8 = 5 } mlsl_data_type_t;
typedef enum { MLSL_GT_DATA = 0, MLSL_GT_MODEL = 1, MLSL_GT_GLOBAL = 2,
               MLSL_GT_SEQ = 3 } mlsl_group_type_t;
typedef enum { MLSL_RT_SUM = 0, MLSL_RT_MIN = 1, MLSL_RT_MAX = 2 } mlsl_reduction_t;
typedef enum { MLSL_OT_CC = 0, MLSL_OT_BIAS = 1, MLSL_OT_ACT = 2, MLSL_OT_POOL = 3,
               MLSL_OT_SPLIT = 4, MLSL_OT_CONCAT = 5, MLSL_OT_BCAST = 6,
               MLSL_OT_REDUCE = 7, MLSL_OT_DATA = 8, MLSL_OT_EVAL = 9 } mlsl_op_type_t;
typedef enum { MLSL_CT_NONE = 0, MLSL_CT_QUANTIZATION = 1,
               MLSL_CT_TOPK = 2 } mlsl_compression_t;

/* ---- environment ---- */
int mlsl_environment_init(void);
int mlsl_environment_finalize(void);
int64_t mlsl_environment_get_process_count(void);
mlsl_handle_t mlsl_environment_create_distribution(int64_t data_parts,
                                                   int64_t model_parts,
                                                   int64_t seq_parts);
mlsl_handle_t mlsl_environment_create_session(void);
/* Color-defined groups (reference CreateDistributionWithColors): int64[n]
 * per-rank color vectors; ranks sharing a color form that group. */
mlsl_handle_t mlsl_environment_create_distribution_with_colors(
    const int64_t* data_colors, const int64_t* model_colors, int64_t n);
/* Register codec params (reference SetQuantizationParams). lib_path (may be
 * NULL) selects a dlopen'd codec honoring the reference's symbol contract;
 * load failures return MLSL_TPU_FAILURE (see mlsl_get_last_error()). */
int mlsl_environment_set_quantization_params(
    const char* lib_path, const char* quant_name, const char* dequant_name,
    const char* reduce_name, int64_t block_size, int64_t elem_in_block);

/* ---- distribution collectives ---- */
int64_t mlsl_distribution_get_process_count(mlsl_handle_t dist,
                                            mlsl_group_type_t group);
/* Member index of world rank `global_idx` within the group (the per-rank
 * GetProcessIdx of reference mlsl.hpp:361, rank made explicit). */
int64_t mlsl_distribution_get_process_idx(mlsl_handle_t dist,
                                          mlsl_group_type_t group,
                                          int64_t global_idx);
/* send: (world, count); returns a request handle (0 on failure). */
mlsl_handle_t mlsl_distribution_all_reduce(mlsl_handle_t dist, const void* send,
                                           int64_t count, mlsl_data_type_t dt,
                                           mlsl_reduction_t op,
                                           mlsl_group_type_t group);
mlsl_handle_t mlsl_distribution_bcast(mlsl_handle_t dist, const void* send,
                                      int64_t count, mlsl_data_type_t dt,
                                      int64_t root, mlsl_group_type_t group);
mlsl_handle_t mlsl_distribution_all_gather(mlsl_handle_t dist, const void* send,
                                           int64_t send_count,
                                           mlsl_data_type_t dt,
                                           mlsl_group_type_t group);
mlsl_handle_t mlsl_distribution_reduce_scatter(mlsl_handle_t dist,
                                               const void* send,
                                               int64_t send_count,
                                               mlsl_data_type_t dt,
                                               mlsl_reduction_t op,
                                               mlsl_group_type_t group);
mlsl_handle_t mlsl_distribution_all_to_all(mlsl_handle_t dist, const void* send,
                                           int64_t send_count,
                                           mlsl_data_type_t dt,
                                           mlsl_group_type_t group);
mlsl_handle_t mlsl_distribution_reduce(mlsl_handle_t dist, const void* send,
                                       int64_t count, mlsl_data_type_t dt,
                                       mlsl_reduction_t op, int64_t root,
                                       mlsl_group_type_t group);
mlsl_handle_t mlsl_distribution_gather(mlsl_handle_t dist, const void* send,
                                       int64_t send_count, mlsl_data_type_t dt,
                                       int64_t root, mlsl_group_type_t group);
/* send: (world, send_count) where send_count = group_size * recv_count. */
mlsl_handle_t mlsl_distribution_scatter(mlsl_handle_t dist, const void* send,
                                        int64_t send_count, mlsl_data_type_t dt,
                                        int64_t root, mlsl_group_type_t group);
/* pairs: int64 array [src0, dst0, src1, dst1, ...] of length 2 * n_pairs;
 * n_pairs counts (src, dst) PAIRS, not array elements. */
mlsl_handle_t mlsl_distribution_send_recv_list(mlsl_handle_t dist,
                                               const void* send, int64_t count,
                                               mlsl_data_type_t dt,
                                               const int64_t* pairs,
                                               int64_t n_pairs,
                                               mlsl_group_type_t group);
int mlsl_distribution_barrier(mlsl_handle_t dist, mlsl_group_type_t group);

/* ---- request completion (reference Environment::Wait/Test) ---- */
/* recv: (world, recv_count) written on success. Frees the request. */
int mlsl_request_wait(mlsl_handle_t req, void* recv, int64_t recv_count,
                      mlsl_data_type_t dt);
/* 1 = complete, 0 = in flight, negative = error. Does not consume. */
int mlsl_request_test(mlsl_handle_t req);

/* ---- session graph ---- */
int mlsl_session_set_global_minibatch_size(mlsl_handle_t sess, int64_t size);
mlsl_handle_t mlsl_session_create_operation_reg_info(mlsl_handle_t sess,
                                                     mlsl_op_type_t op_type);
int64_t mlsl_operation_reg_info_add_input(mlsl_handle_t reg, int64_t count,
                                          int64_t size, mlsl_data_type_t dt);
int64_t mlsl_operation_reg_info_add_output(mlsl_handle_t reg, int64_t count,
                                           int64_t size, mlsl_data_type_t dt);
int64_t mlsl_operation_reg_info_add_parameter_set(mlsl_handle_t reg,
                                                  int64_t kernel_count,
                                                  int64_t kernel_size,
                                                  mlsl_data_type_t dt,
                                                  int dist_update,
                                                  mlsl_compression_t comp);
mlsl_handle_t mlsl_session_add_operation(mlsl_handle_t sess, mlsl_handle_t reg,
                                         mlsl_handle_t dist);
int mlsl_session_commit(mlsl_handle_t sess);
int mlsl_operation_set_next(mlsl_handle_t op, mlsl_handle_t next,
                            int64_t out_idx, int64_t in_idx);
int mlsl_operation_set_prev(mlsl_handle_t op, mlsl_handle_t prev,
                            int64_t in_idx, int64_t prev_out_idx);
int64_t mlsl_operation_get_local_minibatch_size(mlsl_handle_t op);
int64_t mlsl_operation_get_global_minibatch_size(mlsl_handle_t op);
int64_t mlsl_operation_get_parameter_local_count(mlsl_handle_t op, int64_t idx);
int64_t mlsl_operation_get_parameter_owned_count(mlsl_handle_t op, int64_t idx);

/* ---- v-collectives (reference mlsl.hpp:418-471) ----
 * Count/displacement arrays are int64[group_size], identical on every rank
 * (the MPI "same counts everywhere" mode). Pass NULL displacements for the
 * packed default. */
mlsl_handle_t mlsl_distribution_all_gatherv(mlsl_handle_t dist,
                                            const void* send,
                                            int64_t send_count,
                                            const int64_t* recv_counts,
                                            mlsl_data_type_t dt,
                                            mlsl_group_type_t group);
mlsl_handle_t mlsl_distribution_all_to_allv(mlsl_handle_t dist,
                                            const void* send, int64_t send_len,
                                            const int64_t* send_counts,
                                            const int64_t* send_offsets,
                                            const int64_t* recv_offsets,
                                            mlsl_data_type_t dt,
                                            mlsl_group_type_t group);
/* General per-rank AlltoAllv (full MPI generality, reference
 * src/comm_ep.cpp:1188-1265): count/offset tables are int64[world * group]
 * row-major — row w holds world rank w's own vectors (what each MPI rank
 * passes to MPI_Ialltoallv). recv_counts is validated against the transposed
 * send geometry (the MPI pairwise invariant) at setup; offsets may be NULL
 * for the packed default. send buffer is (world, send_len) staging with each
 * rank's row valid to its own send extent. */
mlsl_handle_t mlsl_distribution_all_to_allv_full(
    mlsl_handle_t dist, const void* send, int64_t send_len,
    const int64_t* send_counts, const int64_t* send_offsets,
    const int64_t* recv_counts, const int64_t* recv_offsets,
    mlsl_data_type_t dt, mlsl_group_type_t group);

/* ---- activations (reference mlsl.hpp:210-268, c_bind activation calls) ---- */
int64_t mlsl_operation_get_input_count(mlsl_handle_t op);
int64_t mlsl_operation_get_output_count(mlsl_handle_t op);
mlsl_handle_t mlsl_operation_get_input(mlsl_handle_t op, int64_t idx);
mlsl_handle_t mlsl_operation_get_output(mlsl_handle_t op, int64_t idx);

int64_t mlsl_activation_get_global_fm_count(mlsl_handle_t act);
int64_t mlsl_activation_get_local_fm_count(mlsl_handle_t act);
int64_t mlsl_activation_get_fm_size(mlsl_handle_t act);
/* Per-rank GetGlobalFmOffset with the rank's model-group index explicit. */
int64_t mlsl_activation_get_global_fm_offset(mlsl_handle_t act,
                                             int64_t model_idx);
int mlsl_activation_needs_comm(mlsl_handle_t act);
/* Per-rank wire-buffer element count for start_comm/wait_comm (0 = no comm). */
int64_t mlsl_activation_get_wire_count(mlsl_handle_t act);
/* Per-rank element count of this activation's request RESULT (what a peer's
 * wait_comm writes per rank; 0 = no comm). */
int64_t mlsl_activation_get_recv_count(mlsl_handle_t act);
int64_t mlsl_activation_get_pack_block_count(mlsl_handle_t act);
int64_t mlsl_activation_get_unpack_block_count(mlsl_handle_t act);
/* field: 0=mb_offset 1=mb_count 2=fm_offset 3=fm_count 4=fm_size 5=buf_offset
 * (reference CommBlockInfo mlsl.hpp:177-204). */
int64_t mlsl_activation_get_pack_block(mlsl_handle_t act, int64_t idx,
                                       int field);
int64_t mlsl_activation_get_unpack_block(mlsl_handle_t act, int64_t idx,
                                         int field);
/* buf: (world, wire_count), packed per the pack blocks. */
int mlsl_activation_start_comm(mlsl_handle_t act, const void* buf,
                               mlsl_data_type_t dt);
/* Waits the PEER's transfer; writes (world, n); returns n (0 = no comm on
 * this edge; negative = error). */
int64_t mlsl_activation_wait_comm(mlsl_handle_t act, void* recv,
                                  mlsl_data_type_t dt);

/* ---- parameter-set gradient sync ---- */
int mlsl_parameter_set_start_gradient_comm(mlsl_handle_t op, int64_t ps_idx,
                                           const void* grads,
                                           mlsl_data_type_t dt);
/* Writes (world, n) into recv; returns n (per-rank element count; 0 = no comm
 * was needed; negative = error). */
int64_t mlsl_parameter_set_wait_gradient_comm(mlsl_handle_t op, int64_t ps_idx,
                                              void* recv, mlsl_data_type_t dt);
/* 1 = complete, 0 = in flight, negative = error. */
int mlsl_parameter_set_test_gradient_comm(mlsl_handle_t op, int64_t ps_idx);
/* Distributed-update increment AllGather (reference mlsl.hpp:318-331). */
int mlsl_parameter_set_start_increment_comm(mlsl_handle_t op, int64_t ps_idx,
                                            const void* incs,
                                            mlsl_data_type_t dt);
int64_t mlsl_parameter_set_wait_increment_comm(mlsl_handle_t op, int64_t ps_idx,
                                               void* recv, mlsl_data_type_t dt);
int64_t mlsl_parameter_set_get_global_kernel_count(mlsl_handle_t op,
                                                   int64_t ps_idx);
int64_t mlsl_parameter_set_get_local_kernel_count(mlsl_handle_t op,
                                                  int64_t ps_idx);
int64_t mlsl_parameter_set_get_owned_kernel_count(mlsl_handle_t op,
                                                  int64_t ps_idx);
/* Per-rank GetOwnedKernelOffset with the rank's data-group index explicit. */
int64_t mlsl_parameter_set_get_owned_kernel_offset(mlsl_handle_t op,
                                                   int64_t ps_idx,
                                                   int64_t data_idx);
int64_t mlsl_parameter_set_get_kernel_size(mlsl_handle_t op, int64_t ps_idx);
int mlsl_parameter_set_is_distributed_update(mlsl_handle_t op, int64_t ps_idx);

/* ---- statistics (reference mlsl.hpp:651-726) ----
 * "Cycles" are nanoseconds (the TPU analog of the reference's rdtsc). */
mlsl_handle_t mlsl_session_get_stats(mlsl_handle_t sess);
int mlsl_statistics_start(mlsl_handle_t stats);
int mlsl_statistics_stop(mlsl_handle_t stats);
int mlsl_statistics_reset(mlsl_handle_t stats);
int mlsl_statistics_is_enabled(mlsl_handle_t stats);
int mlsl_statistics_is_started(mlsl_handle_t stats);
int64_t mlsl_statistics_get_comm_size(mlsl_handle_t stats, int64_t op_idx);
int64_t mlsl_statistics_get_comm_cycles(mlsl_handle_t stats, int64_t op_idx);
int64_t mlsl_statistics_get_compute_cycles(mlsl_handle_t stats, int64_t op_idx);
int64_t mlsl_statistics_get_isolation_comm_cycles(mlsl_handle_t stats,
                                                  int64_t op_idx);
int64_t mlsl_statistics_get_total_comm_size(mlsl_handle_t stats);
int64_t mlsl_statistics_get_total_comm_cycles(mlsl_handle_t stats);
int64_t mlsl_statistics_get_total_compute_cycles(mlsl_handle_t stats);
int64_t mlsl_statistics_get_total_isolation_comm_cycles(mlsl_handle_t stats);
/* Fraction (x1000) of pure-comm time hidden behind compute; -1 until
 * isolation stats and accounted steps exist. op_idx < 0 = session total. */
int64_t mlsl_statistics_get_overlap_permille(mlsl_handle_t stats,
                                             int64_t op_idx);
int mlsl_statistics_print(mlsl_handle_t stats);

int mlsl_handle_release(mlsl_handle_t h);

/* Last error message ("ExceptionType: message") from the most recent failed
 * call on any thread (process-wide, best effort), or "" if none. The returned
 * pointer refers to thread-local storage: valid on the calling thread until
 * its next mlsl_get_last_error call. */
const char* mlsl_get_last_error(void);

#ifdef __cplusplus
}
#endif

#endif /* MLSL_TPU_H */
