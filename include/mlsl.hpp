/* mlsl.hpp — MLSL-compatible C++ surface (namespace MLSL) for mlsl_tpu.
 *
 * Drop-in compatibility header for the reference MLSL API
 * (reference include/mlsl.hpp:82-913): same namespace, class names, method
 * signatures, and rank-local void* buffer semantics, so a program written
 * against the reference — e.g. its mlsl_test.cpp — ports with only launcher
 * changes.
 *
 * Execution model: the reference runs one OS process per rank under mpiexec;
 * this framework is single-controller SPMD (one process drives every device).
 * The compat runtime bridges the two by running each "rank" as a THREAD of the
 * controller process: MLSL::RunRanks(argc, argv, rankMain) spawns one thread
 * per device, and every communication call rendezvouses the rank threads,
 * assembles their rank-local buffers into the (world, count) device buffer,
 * executes the collective once through the mlsl_tpu C API, and hands each
 * rank its slice of the result. Rank-local pointer semantics (in-place Bcast,
 * WaitComm returning a wire-buffer pointer, owned-shard increment AllGather)
 * are preserved exactly.
 *
 * Requirements inherited from SPMD: every rank thread must issue collective
 * and graph-construction calls in the same order (the same congruence MPI
 * collectives already require).
 */

#ifndef MLSL_HPP
#define MLSL_HPP

#include <cstddef>

#define MLSL_MAJOR_VERSION 1
#define MLSL_MINOR_VERSION 0
#define MLSL_VERSION(major, minor) ((major << 16) | (minor))
#define MLSL_MAJOR(version) (version >> 16)
#define MLSL_MINOR(version) (version & 0xFFFF)
#define MLSL_VERSION_GE(v1, v2)                                               \
  ((MLSL_MAJOR(v1) > MLSL_MAJOR(v2)) ||                                       \
   (MLSL_MAJOR(v1) == MLSL_MAJOR(v2) && MLSL_MINOR(v1) == MLSL_MINOR(v2)) ||  \
   (MLSL_MAJOR(v1) == MLSL_MAJOR(v2) && MLSL_MINOR(v1) > MLSL_MINOR(v2)))
#define MLSL_VERSION_LT(v1, v2)                                               \
  ((MLSL_MAJOR(v1) < MLSL_MAJOR(v2)) ||                                       \
   (MLSL_MAJOR(v1) == MLSL_MAJOR(v2) && MLSL_MINOR(v1) < MLSL_MINOR(v2)))

namespace MLSL
{
    typedef int CommReq;

    enum DataType
    {
        DT_FLOAT  = 0,
        DT_DOUBLE = 1,
        DT_BYTE   = 2
    };

    enum PhaseType
    {
        PT_TRAIN = 0,
        PT_TEST  = 1
    };

    enum GroupType
    {
        GT_DATA   = 0,
        GT_MODEL  = 1,
        GT_GLOBAL = 2
    };

    enum ReductionType
    {
        RT_SUM = 0,
        RT_MIN = 1,
        RT_MAX = 2
    };

    enum OpType
    {
        OT_CC     = 0,
        OT_BIAS   = 1,
        OT_ACT    = 2,
        OT_POOL   = 3,
        OT_SPLIT  = 4,
        OT_CONCAT = 5,
        OT_BCAST  = 6,
        OT_REDUCE = 7,
        OT_DATA   = 8,
        OT_EVAL   = 9
    };

    enum CompressionType
    {
        CT_NONE         = 0,
        CT_QUANTIZATION = 1
    };

    typedef struct
    {
        char* lib_path;
        char* quant_buffer_func_name;
        char* dequant_buffer_func_name;
        char* reduce_sum_func_name;
        size_t block_size;
        size_t elem_in_block;
    } QuantParams;

    class CommBlockInfo
    {
    public:
        size_t GetMbOffset();
        size_t GetMbCount();
        size_t GetFmOffset();
        size_t GetFmCount();
        size_t GetFmSize();
        DataType GetDataType();
        size_t GetBufOffset();
    };

    class Activation
    {
    public:
        size_t GetGlobalFmCount();
        size_t GetGlobalFmOffset();
        size_t GetLocalFmCount();
        size_t GetPackBlockCount();
        size_t GetUnpackBlockCount();
        CommBlockInfo* GetPackBlock(size_t idx);
        CommBlockInfo* GetUnpackBlock(size_t idx);
        DataType GetDataType();
        size_t GetFmSize();
        void* GetCommBuf();
        size_t GetCommBufSize();
        void StartComm(void* buf);
        void* WaitComm();
    };

    class ParameterSet
    {
    public:
        size_t GetGlobalKernelCount();
        size_t GetGlobalKernelOffset();
        size_t GetLocalKernelCount();
        size_t GetOwnedKernelCount();
        size_t GetOwnedKernelOffset();
        DataType GetDataType();
        size_t GetKernelSize();
        bool IsDistributedUpdate();
        void StartGradientComm(void* buf);
        void StartIncrementComm(void* buf);
        void* WaitGradientComm();
        void* TestGradientComm(bool* isCompleted);
        void* WaitIncrementComm();
    };

    class Distribution
    {
    public:
        size_t GetProcessIdx(GroupType groupType);
        size_t GetProcessCount(GroupType groupType);
        CommReq* Bcast(void* buffer, size_t count, DataType dataType,
                       size_t rootIdx, GroupType groupType);
        CommReq* Reduce(void* sendBuffer, void* recvBuffer, size_t count,
                        DataType dataType, ReductionType redType,
                        size_t rootIdx, GroupType groupType);
        CommReq* AllReduce(void* sendBuffer, void* recvBuffer, size_t count,
                           DataType dataType, ReductionType redType,
                           GroupType groupType);
        CommReq* AlltoAll(void* sendBuffer, size_t sendCount, void* recvBuffer,
                          DataType dataType, GroupType groupType);
        CommReq* Gather(void* sendBuffer, size_t sendCount, void* recvBuffer,
                        DataType dataType, size_t rootIdx, GroupType groupType);
        CommReq* AllGather(void* sendBuffer, size_t sendCount, void* recvBuffer,
                           DataType dataType, GroupType groupType);
        /* recvCounts: size_t[group_size], identical on every rank (reference
         * include/mlsl.hpp:470) */
        CommReq* AllGatherv(void* sendBuffer, size_t sendCount,
                            void* recvBuffer, size_t* recvCounts,
                            DataType dataType, GroupType groupType);
        /* Each rank passes its OWN size_t[group_size] count/offset vectors
         * — full MPI_Ialltoallv generality (reference include/mlsl.hpp:432):
         * the runtime gathers the per-rank rows, validates the pairwise
         * invariant (recvCounts[j] here == sendCounts[myIdx] at member j),
         * and issues one static-geometry exchange. NULL offsets = packed
         * layout; the receive buffer is sized per the MPI contract (this
         * rank's total receive extent). A NULL recvCounts selects the legacy
         * rank-uniform mode (member j receives sendCounts[j] from each
         * peer). */
        CommReq* AlltoAllv(void* sendBuffer, size_t* sendCounts,
                           size_t* sendOffsets, void* recvBuffer,
                           size_t* recvCounts, size_t* recvOffsets,
                           DataType dataType, GroupType groupType);
        CommReq* Scatter(void* sendBuffer, void* recvBuffer, size_t recvCount,
                         DataType dataType, size_t rootIdx, GroupType groupType);
        CommReq* ReduceScatter(void* sendBuffer, void* recvBuffer,
                               size_t recvCount, DataType dataType,
                               ReductionType redType, GroupType groupType);
        void Barrier(GroupType groupType);
    };

    class OperationRegInfo
    {
    public:
        void SetName(const char* name);
        size_t AddInput(size_t featureMapCount, size_t featureMapSize,
                        DataType dataType);
        size_t AddOutput(size_t featureMapCount, size_t featureMapSize,
                         DataType dataType);
        size_t AddParameterSet(size_t kernelCount, size_t kernelSize,
                               DataType dataType, bool distributedUpdate = false,
                               CompressionType compressType = CT_NONE);
        void Validate(Distribution* dist = NULL);
    };

    class Session;

    class Operation
    {
    public:
        void SetDistribution(Distribution* dist);
        Distribution* GetDistribution();
        Session* GetSession();
        OpType GetOpType();
        void SetPrev(Operation* prev, size_t actIdx, size_t prevOpActIdx);
        void SetNext(Operation* next, size_t actIdx, size_t nextOpActIdx);
        const char* GetName();
        size_t GetGlobalMinibatchSize();
        size_t GetLocalMinibatchSize();
        size_t GetGlobalMinibatchOffset();
        size_t GetInputCount();
        Activation* GetInput(size_t idx);
        size_t GetOutputCount();
        Activation* GetOutput(size_t idx);
        bool HasParameterSets();
        size_t GetParameterSetCount();
        ParameterSet* GetParameterSet(size_t idx);
    };

    class Statistics
    {
    public:
        void Start();
        void Stop();
        void Reset();
        bool IsStarted();
        bool IsEnabled();
        void Print();
        unsigned long long GetIsolationCommCycles(size_t opIdx);
        size_t GetCommSize(size_t opIdx);
        unsigned long long GetCommCycles(size_t opIdx);
        unsigned long long GetComputeCycles(size_t opIdx);
        unsigned long long GetTotalIsolationCommCycles();
        size_t GetTotalCommSize();
        unsigned long long GetTotalCommCycles();
        unsigned long long GetTotalComputeCycles();
    };

    class Session
    {
    public:
        void SetGlobalMinibatchSize(size_t globalMinibatchSize);
        size_t GetGlobalMinibatchSize();
        PhaseType GetPhaseType();
        OperationRegInfo* CreateOperationRegInfo(OpType opType);
        void DeleteOperationRegInfo(OperationRegInfo* info);
        size_t AddOperation(OperationRegInfo* info, Distribution* dist = NULL);
        void RemoveOperations();
        size_t GetOperationCount();
        Operation* GetOperation(size_t idx);
        void Commit();
        Statistics* GetStats();
    };

    class Environment
    {
    public:
        static Environment& GetEnv();
        static int GetVersion();
        void Configure(const char* config = NULL);
        /* each rank passes ITS colors; ranks sharing a dataColor/modelColor
         * form that group (reference :864; unequal partitions follow the
         * padded ragged-group contract, docs/DESIGN.md) */
        Distribution* CreateDistributionWithColors(int dataColor,
                                                   int modelColor);
        void Init(int* argc, char** argv[]);
        void Finalize();
        bool IsInitialized();
        size_t GetProcessIdx();
        size_t GetProcessCount();
        Session* CreateSession(PhaseType phaseType = PT_TRAIN);
        void DeleteSession(Session* session);
        Distribution* CreateDistribution(size_t dataPartitions,
                                         size_t modelPartitions);
        void DeleteDistribution(Distribution* distribution);
        void Wait(CommReq* req);
        void Test(CommReq* req, bool* isCompleted);
        void* Alloc(size_t size, size_t alignment);
        void Free(void* ptr);
        void SetQuantizationParams(QuantParams* params);
        QuantParams* GetQuantizationParams();
    };

    /* Compat launcher (replaces mpiexec): spawns one rank thread per device of
     * the attached platform, each running rankMain(argc, argv) with rank-local
     * MLSL semantics. Returns the first nonzero rankMain result (0 if all
     * succeed). worldOverride > 0 forces the rank count (must not exceed the
     * device count). */
    int RunRanks(int argc, char** argv, int (*rankMain)(int, char**),
                 int worldOverride = 0);
};

#endif /* MLSL_HPP */
