"""A documented end-to-end walkthrough of mlsl_tpu (the analog of the reference's
tests/examples/mlsl_example/mlsl_example.cpp): create the environment, lay out a
data x model grid, register a small operation graph, and run training-loop phases
with asynchronous gradient synchronization.

Run on the 8-device CPU mesh (simulating a TPU slice):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 MLSL_TPU_PLATFORM=cpu \
        python examples/mlsl_example.py
or on real TPU hardware with no extra flags.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mlsl_tpu as mlsl
from mlsl_tpu.types import DataType, GroupType, OpType, ReductionType


def main():
    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    # 1. Bootstrap (reference: Environment::GetEnv().Init(&argc, &argv))
    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()
    print(f"process count: {world}")

    # 2. Parallelism layout: a data x model grid over the device world
    #    (reference: Environment::CreateDistribution(dataParts, modelParts))
    model_parts = 2 if world % 2 == 0 else 1
    data_parts = world // model_parts
    dist = env.create_distribution(data_parts, model_parts)
    print(f"grid: data={data_parts} x model={model_parts}")

    # 3. A raw collective through the Distribution (returns an async request;
    #    Environment.wait completes it — reference CommReq* + Environment::Wait)
    buf = dist.make_buffer(lambda p: np.full(4, float(p + 1)), 4)
    req = dist.AllReduce(buf, 4, DataType.FLOAT, ReductionType.SUM, GroupType.GLOBAL)
    out = env.wait(req)
    print("global allreduce:", dist.local_part(out, 0))

    # 4. Register a two-layer operation graph (reference: Session::AddOperation
    #    from OperationRegInfo, SetNext to wire edges, Commit to build comms)
    session = env.create_session()
    session.SetGlobalMinibatchSize(4 * data_parts)
    reg1 = session.CreateOperationRegInfo(OpType.CC)
    reg1.AddInput(8, 16, DataType.FLOAT)
    reg1.AddOutput(16, 16, DataType.FLOAT)
    reg1.AddParameterSet(8 * 16, 1, DataType.FLOAT)
    op1 = session.GetOperation(session.AddOperation(reg1, dist))

    reg2 = session.CreateOperationRegInfo(OpType.CC)
    reg2.AddInput(16, 16, DataType.FLOAT)
    reg2.AddOutput(4, 16, DataType.FLOAT)
    reg2.AddParameterSet(16 * 4, 1, DataType.FLOAT, distributed_update=True)
    op2 = session.GetOperation(session.AddOperation(reg2, dist))

    op1.SetNext(op2, 0, 0)
    session.Commit()

    # 5. Training-loop phases (reference mlsl_test loop :660-698): start the
    #    gradient collectives newest-first, overlap, then wait + update
    for it in range(3):
        for op in (op2, op1):  # backward order
            ps = op.GetParameterSet(0)
            n = ps.GetLocalKernelCount() * ps.GetKernelSize()
            grads = dist.make_buffer(lambda p: np.full(n, float(it + 1)), n)
            ps.StartGradientComm(grads)
        for op in (op1, op2):
            ps = op.GetParameterSet(0)
            reduced = ps.WaitGradientComm()
            kind = "owned shard" if ps.IsDistributedUpdate() else "full"
            if reduced is not None:
                print(
                    f"iter {it} {op.GetName()}: {kind} reduced[0] = "
                    f"{float(np.asarray(dist.local_part(reduced, 0))[0])}"
                )

    # 6. Statistics (reference Statistics::Print -> mlsl_stats.log)
    print(session.GetStats().Print("/tmp/mlsl_stats_example.log")[:200] or "(stats disabled; set MLSL_STATS=1)")

    env.finalize()
    print("example OK")


if __name__ == "__main__":
    main()
