"""Long-context training walkthrough: zigzag context parallelism.

Shows the sequence-parallel menu for causal attention over sequences that
don't fit one device, and why zigzag is the default choice for causal
training:

1. contiguous ring (`ring_attention`): k/v blocks rotate over the ICI ring;
   causal masking wastes ~half the computed score blocks;
2. zigzag ring (`zigzag_ring_attention` / `attention="zigzag"`): each device
   holds global chunks r and 2G-1-r, so every hop is two UNMASKED chunk
   updates — same math, ~2x fewer attention FLOPs (docs/DESIGN.md);
3. Ulysses (`ulysses_attention`): two all-to-alls trade sequence sharding
   for head sharding when heads are plentiful.

Run on the 8-device CPU mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 MLSL_TPU_PLATFORM=cpu \
        python examples/long_context.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mlsl_tpu as mlsl


def main():
    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    from mlsl_tpu.models import transformer as tfm

    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()
    sp = world  # the whole mesh shards the sequence (context parallelism)

    # a sequence this long lives only as shards of seq_len/sp per device
    cfg = dict(vocab=128, d_model=64, n_heads=8, head_dim=8, n_blocks=2,
               seq_len=64 * sp, dtype="float32")
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 128, size=(2, cfg["seq_len"])).astype(np.int32)
    labels = rng.integers(0, 128, size=(2, cfg["seq_len"])).astype(np.int32)

    losses = {}
    times = {}
    for mode in ("ring", "zigzag"):
        c = tfm.TransformerConfig(attention=mode, **cfg)
        trainer = tfm.HybridTrainer(env, c, dp=1, sp=sp, tp=1, batch=2, lr=0.3)
        # shard_tokens handles the zigzag data permutation transparently;
        # callers always pass sequences in natural order
        st, sl = trainer.shard_tokens(toks, labels)
        float(trainer.step(st, sl))  # compile + d2h sync before timing
        t0 = time.perf_counter()
        losses[mode] = [float(trainer.step(st, sl)) for _ in range(3)]
        times[mode] = (time.perf_counter() - t0) / 3
        print(f"{mode:7s}: losses {['%.4f' % x for x in losses[mode]]}  "
              f"({times[mode] * 1e3:.0f} ms/step)")

    # identical math, different schedule: trajectories agree to rounding
    np.testing.assert_allclose(losses["zigzag"], losses["ring"], rtol=1e-4)
    print("zigzag == ring trajectory (to rounding): OK")
    print("long-context example OK")
    env.finalize()


if __name__ == "__main__":
    main()
