"""Hybrid-parallel transformer training walkthrough: dp x sp x tp with MLSL-driven
gradient sync, async data loading and checkpointing.

Run on the 8-device CPU mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 MLSL_TPU_PLATFORM=cpu \
        python examples/train_transformer.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mlsl_tpu as mlsl


def main():
    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    from mlsl_tpu.checkpoint import CheckpointManager, restore_trainer, save_trainer
    from mlsl_tpu.data import AsyncLoader
    from mlsl_tpu.models import transformer as tfm

    env = mlsl.Environment.get_env().init()
    world = env.get_process_count()

    # Factor the world into data x seq x model parallelism so dp*sp*tp == world
    # for ANY device count: peel a factor of 2 for tensor parallelism, another
    # for sequence sharding (ring attention), and give the rest to the batch.
    tp = 2 if world % 2 == 0 else 1
    sp = 2 if (world // tp) % 2 == 0 and world // tp > 1 else 1
    dp = world // (tp * sp)

    cfg = tfm.TransformerConfig(
        vocab=128, d_model=64, n_heads=8, head_dim=8, n_blocks=2, seq_len=64,
        attention="ring",
    )
    batch = 4 * dp
    trainer = tfm.HybridTrainer(env, cfg, dp, sp, tp, batch=batch, lr=0.3)
    print(f"world={world}: dp={dp} sp={sp} tp={tp}; "
          f"{sum(trainer.local_counts.values())} params/device")

    # a fixed synthetic corpus: memorize next-token prediction on 4 sequences
    rng = np.random.default_rng(0)
    corpus = rng.integers(0, cfg.vocab, size=(batch, cfg.seq_len)).astype(np.int32)

    def batches():
        while True:
            yield corpus, np.roll(corpus, -1, axis=1)

    loader = AsyncLoader(batches(), trainer.shard_tokens, depth=2)
    mgr = CheckpointManager("/tmp/mlsl_tpu_tfm_ckpt")

    for step, (toks, labels) in enumerate(loader):
        loss = float(np.asarray(trainer.step(toks, labels)))
        if step % 5 == 0:
            print(f"step {step:3d}  loss {loss:.4f}")
        if step == 10:
            save_trainer(mgr, trainer, step=step, wait=True)
        if step >= 20:
            break
    loader.close()

    restored = restore_trainer(mgr, trainer)
    print(f"checkpoint restored from step {restored}")
    mgr.close()
    env.finalize()
    print("transformer example OK")


if __name__ == "__main__":
    main()
