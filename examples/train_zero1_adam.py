"""ZeRO-1 Adam with gradient accumulation and checkpoint resume.

Demonstrates the trainer-side framework features on top of the MLSL graph:
- optax optimizer with state sharded on each rank's OWNED gradient shard
  (ZeRO-1: the distributed-update path, reference src/mlsl_impl.cpp:401-435,
  with Adam moments instead of plain SGD);
- gradient accumulation (the Caffe iter_size pattern): k local fwd/bwd passes,
  one gradient sync;
- checkpointing that persists the optimizer state, so a resumed run continues
  the Adam trajectory instead of restarting from zero moments.

Run on the 8-device CPU mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 MLSL_TPU_PLATFORM=cpu \
        python examples/train_zero1_adam.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import optax

import mlsl_tpu as mlsl
from mlsl_tpu.checkpoint import CheckpointManager, restore_trainer, save_trainer
from mlsl_tpu.models.mlp import LAYERS, get_layer, init as mlp_init, loss_fn
from mlsl_tpu.models.train import DataParallelTrainer


def main():
    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()

    env = mlsl.Environment.get_env().init()
    n = len(env.devices)
    dist = env.create_distribution(n, 1)
    sess = env.create_session()
    sess.set_global_minibatch_size(2 * n)

    trainer = DataParallelTrainer(
        env, dist, sess, mlp_init(jax.random.PRNGKey(0)), loss_fn, LAYERS,
        get_layer,
        distributed_update=True,          # ZeRO-1: ReduceScatter + owned update
        optimizer=optax.adam(5e-3),       # moments live on the owned shard only
    )

    rng = np.random.default_rng(0)

    def micro_batch():
        x = rng.normal(size=(2 * n, 8)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.int32) + 2 * (x[:, 1] > 0).astype(np.int32)
        return trainer.shard_batch(x, y)

    ckpt_dir = os.path.join(tempfile.mkdtemp(prefix="mlsl_zero1_"), "ckpt")
    mgr = CheckpointManager(ckpt_dir)

    for step in range(6):
        # 2x gradient accumulation: effective batch 4n, one sync per step
        loss = trainer.step_accum([micro_batch(), micro_batch()])
        lv = float(np.asarray(loss).mean())
        print(f"step {step}: loss {lv:.4f}")
        if step == 2:
            save_trainer(mgr, trainer, step, wait=True)

    # Resume: a fresh trainer restored from step 2 continues the Adam
    # trajectory (moments + count come back with the params).
    sess2 = env.create_session()
    sess2.set_global_minibatch_size(2 * n)
    trainer2 = DataParallelTrainer(
        env, dist, sess2, mlp_init(jax.random.PRNGKey(0)), loss_fn, LAYERS,
        get_layer, distributed_update=True, optimizer=optax.adam(5e-3),
    )
    mgr2 = CheckpointManager(ckpt_dir)
    restored = restore_trainer(mgr2, trainer2)
    print(f"resumed from step {restored}")
    loss = trainer2.step_accum([micro_batch(), micro_batch()])
    print(f"post-resume loss {float(np.asarray(loss).mean()):.4f}")
    mgr.close()
    mgr2.close()
    env.finalize()
    print("OK")


if __name__ == "__main__":
    main()
