"""User-pluggable gradient compression, both plug-in forms (the analog of the
reference's dlopen'd quantization library, quant/quant.c:96-133, registered via
Environment::SetQuantizationParams, src/mlsl.cpp:798).

Form 1 — jittable Python callables (the TPU-native form): compress/decompress
(and optionally reduce) trace straight into the compressed allreduce ring, so
the codec runs on-device with no host round-trips.

Form 2 — the reference's exact shared-library contract: a .so exposing
compress/decompress/reduce_sum symbols is dlopen'd and bridged with host
callbacks. Geometry is calibrated at registration: a declared block_size the
codec does not honor fails loudly instead of corrupting memory.

Run on the 8-device CPU mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 MLSL_TPU_PLATFORM=cpu \
        python examples/custom_codec.py
"""

import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mlsl_tpu as mlsl
from mlsl_tpu.types import (
    CompressionType, DataType, GroupType, QuantParams, ReductionType,
)


def quantized_allreduce(env, dist, n, vals):
    req = dist.all_reduce(
        dist.make_buffer(lambda p: vals[p], n), n, DataType.FLOAT,
        ReductionType.SUM, GroupType.DATA,
        compression=CompressionType.QUANTIZATION,
    )
    return env.wait(req)


def main():
    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()
    import jax.numpy as jnp

    env = mlsl.Environment.get_env().init()
    dist = env.create_distribution(len(env.devices), 1)
    world = len(env.devices)
    n = 1024
    rng = np.random.default_rng(7)
    vals = {p: (rng.normal(size=n) * 3).astype(np.float32) for p in range(world)}
    want = np.sum([vals[p] for p in range(world)], axis=0)

    # --- Form 1: jittable callables (f16 truncation, on-device) ------------
    env.set_quantization_params(QuantParams(
        compress_fn=lambda v: v.astype(jnp.float16),
        decompress_fn=lambda payload, count: payload.astype(jnp.float32),
        reduce_sum_fn=lambda a, b: a + b,   # reduce in the compressed domain
    ))
    out = quantized_allreduce(env, dist, n, vals)
    got = np.asarray(dist.local_part(out, 0))
    err = float(np.median(np.abs(got - want) / (np.abs(want) + 1e-3)))
    print(f"jittable f16 codec: median relative error {err:.5f}")
    assert err < 0.01

    # --- Form 2: the reference's shared-library contract -------------------
    with tempfile.TemporaryDirectory() as td:
        so = os.path.join(td, "libsample_codec.so")
        subprocess.run(
            ["gcc", "-shared", "-fPIC", "-O2", "-o", so,
             os.path.join(os.path.dirname(__file__), "..", "native",
                          "sample_codec.c")],
            check=True,
        )
        env.set_quantization_params(QuantParams(
            lib_path=so,
            quant_buffer_func_name="sample_compress",
            dequant_buffer_func_name="sample_decompress",
            reduce_sum_func_name="sample_reduce_sum",
            elem_in_block=128, block_size=256,  # 128 f32 in -> 256 B of f16 out
        ))
        out = quantized_allreduce(env, dist, n, vals)
        got = np.asarray(dist.local_part(out, 0))
        err = float(np.median(np.abs(got - want) / (np.abs(want) + 1e-3)))
        print(f"dlopen'd library codec: median relative error {err:.5f}")
        assert err < 0.01

        # A geometry the codec does not honor is rejected at registration.
        try:
            env.set_quantization_params(QuantParams(
                lib_path=so,
                quant_buffer_func_name="sample_compress",
                dequant_buffer_func_name="sample_decompress",
                reduce_sum_func_name="sample_reduce_sum",
                elem_in_block=256, block_size=256,  # codec writes 512 B/block
            ))
        except mlsl.MLSLError as e:
            print(f"inconsistent geometry rejected: {e}")
        else:
            raise AssertionError(
                "geometry mismatch was accepted — the calibration probe "
                "regressed"
            )

    # Back to the built-in Pallas int8 block codec.
    env.set_quantization_params(QuantParams(elem_in_block=256))
    out = quantized_allreduce(env, dist, n, vals)
    got = np.asarray(dist.local_part(out, 0))
    err = float(np.median(np.abs(got - want) / (np.abs(want) + 1e-3)))
    print(f"built-in int8 block codec: median relative error {err:.5f}")
    assert err < 0.05

    env.finalize()
    print("custom codec example OK")


if __name__ == "__main__":
    main()
