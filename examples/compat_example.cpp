/* A documented end-to-end walkthrough of the DROP-IN C++ surface
 * (include/mlsl.hpp): the program below is written exactly as a user of the
 * original library would write it (cf. the reference's
 * tests/examples/mlsl_example/mlsl_example.cpp) — create the environment, lay
 * out a data x model grid, register a two-operation graph, and run training
 * phases with asynchronous gradient synchronization. The only addition is the
 * MLSL::RunRanks launcher, which stands in for mpiexec: each MPI rank becomes
 * a rank thread over the shared TPU mesh (docs/MIGRATION.md).
 *
 * Build & run on the 8-device CPU mesh (the Makefile computes the portable
 * embed-Python link flags via python3-config):
 *   make -C native compat_example
 *   PYTHONPATH=. MLSL_TPU_PLATFORM=cpu \
 *       XLA_FLAGS=--xla_force_host_platform_device_count=8 \
 *       ./native/compat_example
 */

#include <cstdio>
#include <vector>

#include "mlsl.hpp"

using namespace MLSL;

namespace {

constexpr size_t kMinibatch = 8;  /* global; split over the data group */
constexpr size_t kFmIn = 16, kFmOut = 8, kFmSize = 4;

int rank_main(int argc, char** argv) {
  /* 1. Bootstrap (identical to the reference's Environment::GetEnv().Init) */
  Environment& env = Environment::GetEnv();
  env.Init(&argc, &argv);
  size_t world = env.GetProcessCount();
  size_t rank = env.GetProcessIdx();

  /* 2. Parallelism layout: data x model process grid */
  size_t model_parts = world >= 4 ? 2 : 1;
  Distribution* dist = env.CreateDistribution(world / model_parts, model_parts);

  /* 3. Register the graph: two fully-connected operations wired by an edge.
   * The library derives, per edge and parameter set, WHICH collective to run
   * on WHICH process subgroup. */
  Session* session = env.CreateSession();
  session->SetGlobalMinibatchSize(kMinibatch);

  OperationRegInfo* reg1 = session->CreateOperationRegInfo(OT_CC);
  reg1->AddInput(kFmIn, kFmSize, DT_FLOAT);
  reg1->AddOutput(kFmOut, kFmSize, DT_FLOAT);
  reg1->AddParameterSet(kFmIn * kFmOut, 1, DT_FLOAT, /*distributedUpdate=*/false,
                        CT_NONE);
  Operation* op1 = session->GetOperation(session->AddOperation(reg1, dist));
  session->DeleteOperationRegInfo(reg1);

  OperationRegInfo* reg2 = session->CreateOperationRegInfo(OT_CC);
  reg2->AddInput(kFmOut, kFmSize, DT_FLOAT);
  reg2->AddOutput(kFmIn, kFmSize, DT_FLOAT);
  reg2->AddParameterSet(kFmOut * kFmIn, 1, DT_FLOAT, /*distributedUpdate=*/true,
                        CT_NONE);
  Operation* op2 = session->GetOperation(session->AddOperation(reg2, dist));
  session->DeleteOperationRegInfo(reg2);

  op1->SetNext(op2, 0, 0);  /* op1's output 0 feeds op2's input 0 */
  session->Commit();        /* builds and compiles every per-edge collective */

  /* 4. Broadcast initial parameters from rank 0 (as the reference example
   * initializes weights identically on every rank) */
  ParameterSet* ps1 = op1->GetParameterSet(0);
  size_t n1 = ps1->GetLocalKernelCount() * ps1->GetKernelSize();
  std::vector<float> weights(n1, rank == 0 ? 0.5f : 0.0f);
  env.Wait(dist->Bcast(weights.data(), n1, DT_FLOAT, 0, GT_GLOBAL));

  /* 5. Training phases (the reference loop: Forward / Backward / Update).
   * StartGradientComm is ASYNC — the collective runs while this rank keeps
   * computing; WaitGradientComm delivers the reduced gradients. */
  for (int iter = 0; iter < 2; iter++) {
    std::vector<float> grads1(n1);
    for (size_t i = 0; i < n1; i++) grads1[i] = (float)(rank + 1);

    ps1->StartGradientComm(grads1.data());
    /* ... overlap: compute the next layer's gradients here ... */
    ParameterSet* ps2 = op2->GetParameterSet(0);
    size_t n2 = ps2->GetLocalKernelCount() * ps2->GetKernelSize();
    std::vector<float> grads2(n2);
    for (size_t i = 0; i < n2; i++) grads2[i] = (float)(rank + 1) * 0.5f;
    ps2->StartGradientComm(grads2.data());

    /* WaitGradientComm returns a pointer to the reduced gradients (the
     * library's wire buffer, reference semantics) */
    float* r1 = (float*)ps1->WaitGradientComm();
    /* op2 uses distributedUpdate (ZeRO-1): each data rank receives only its
     * OWNED shard of the reduced gradient; increments would be all-gathered
     * back by StartIncrementComm after the local optimizer step */
    float* r2 = (float*)ps2->WaitGradientComm();
    (void)r1;
    (void)r2;
  }

  /* 6. Statistics: per-op bytes/time accounting (enable with MLSL_STATS=1) */
  Statistics* stats = session->GetStats();
  if (stats->IsEnabled()) stats->Print();

  env.DeleteSession(session);
  env.DeleteDistribution(dist);
  env.Finalize();
  if (rank == 0) std::printf("compat example OK (world=%zu)\n", world);
  return 0;
}

}  // namespace

int main(int argc, char** argv) { return RunRanks(argc, argv, rank_main); }
